"""CI probe: a live ``bibfs-serve --metrics-port`` process answers
``/metrics`` with the documented names.

What the in-process endpoint tests (tests/test_obs_http.py) cannot
prove: the CLI wiring end to end — flag parsing, the ephemeral-port
startup line on stderr, the registry populated by a REAL serving
subprocess, and a clean shutdown. So this script spawns
``python -m bibfs_tpu.serve.cli GRAPH --pipeline --metrics-port 0``,
streams queries over stdin (keeping stdin open holds the server up),
scrapes ``/metrics`` over HTTP, and asserts the documented metric
names appear with non-zero traffic. Exit 0 = pass; any other exit (or
a hang, bounded by the workflow's timeout) fails the CI step.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

# derived from the ONE canonical metric list (bibfs_tpu/obs/names.py —
# the metric-mint lint keeps it, the mint sites and the README in
# lockstep); histograms expand to their _bucket/_count/_sum exposition
# series
from bibfs_tpu.obs.names import SERVE_ENDPOINT_METRICS, exposition_names

REQUIRED_NAMES = [
    series
    for family in SERVE_ENDPOINT_METRICS
    for series in exposition_names(family)
]


def main() -> int:
    from bibfs_tpu.graph.io import write_graph_bin

    n = 300
    edges = [[i, i + 1] for i in range(n - 1)]
    edges += [[i, i + 7] for i in range(n - 7)]
    tmp = tempfile.mkdtemp(prefix="bibfs-obs-ci-")
    gpath = os.path.join(tmp, "g.bin")
    write_graph_bin(gpath, n, np.array(edges))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "bibfs_tpu.serve.cli", gpath,
         "--pipeline", "--no-path", "--metrics-port", "0"],
        stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE, text=True, env=env,
    )

    # the startup line ("[Obs] serving /metrics on http://...") carries
    # the ephemeral port; read stderr on a thread so a wedged CLI can't
    # deadlock this probe on a full pipe
    url_box: list[str] = []
    stderr_lines: list[str] = []

    def read_stderr():
        for line in proc.stderr:
            stderr_lines.append(line.rstrip())
            if "[Obs] serving /metrics on " in line:
                url_box.append(line.split()[-1])

    t = threading.Thread(target=read_stderr, daemon=True)
    t.start()

    try:
        deadline = time.time() + 60
        while not url_box:
            if proc.poll() is not None or time.time() > deadline:
                print("FAIL: server never announced its metrics port",
                      file=sys.stderr)
                print("\n".join(stderr_lines), file=sys.stderr)
                return 1
            time.sleep(0.05)
        url = url_box[0]

        rng = np.random.default_rng(0)
        for s, d in rng.integers(0, n, size=(50, 2)):
            proc.stdin.write(f"{s} {d}\n")
        proc.stdin.flush()

        # scrape until the traffic shows up (the pipelined flusher
        # resolves within its deadline; CI boxes get a generous bound)
        body = ""
        deadline = time.time() + 60
        while time.time() < deadline:
            with urllib.request.urlopen(url, timeout=10) as r:
                body = r.read().decode()
            if "bibfs_queries_total" in body and " 50" in body:
                break
            time.sleep(0.25)

        missing = [m for m in REQUIRED_NAMES if m not in body]
        if missing:
            print(f"FAIL: /metrics missing {missing}", file=sys.stderr)
            print(body[:4000], file=sys.stderr)
            return 1
        if 'le="+Inf"' not in body:
            print("FAIL: histogram exposition lacks the +Inf bucket",
                  file=sys.stderr)
            return 1
        # the names render at value 0 from engine construction alone —
        # the gate must also prove the TRAFFIC landed (a wedged flusher
        # resolves nothing and would otherwise still pass)
        import re

        m = re.search(r"^bibfs_queries_total\{[^}]*\} (\d+)", body,
                      re.MULTILINE)
        served = int(m.group(1)) if m else 0
        if served < 50:
            print(f"FAIL: only {served}/50 queries visible in "
                  "bibfs_queries_total — serving traffic never landed",
                  file=sys.stderr)
            return 1
        print(f"OK: {url} exposes {len(REQUIRED_NAMES)} required metric "
              f"names with {served} served queries")
        return 0
    finally:
        try:
            proc.stdin.close()  # EOF drains and exits the server
            proc.wait(timeout=60)
        except Exception:
            proc.kill()


if __name__ == "__main__":
    raise SystemExit(main())
