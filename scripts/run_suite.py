"""Regenerate the reference's headline artifact on the CURRENT platform.

The reference ships ONE published table: every version on every suite
graph (benchmark_test.sh:61-124 -> benchmark_results.csv /
benchmark_table.txt). This script rebuilds that table through the
framework's own CLI core — all four suite graphs x the host backends
(serial, native) x the device backends (dense, sharded) — and then adds
the device rows the reference never had: the fused whole-level kernel
config and the batch-throughput rows (vmapped dense + native host loop
on the same 64 pairs). Every row carries platform/config stamps
(VERDICT r4 weak #6: the old CSV could not tell a CPU-substrate row
from a real device row).

Graphs are generated once into a cache dir and reused across retries;
the run degrades per-row (cli.bench keeps a sweep alive through
failures), so a tunnel drop mid-run still yields a labeled partial
table. Writes benchmark_results.csv + benchmark_table.txt at the repo
root and prints a RESULT line for the watcher protocol.

Usage: python scripts/run_suite.py [--repeats 5] [--out-dir /tmp/...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SUITE_DIR = "/tmp/bibfs_suite_r5"


def ensure_graphs(out_dir: str) -> tuple[list[str], str]:
    """Suite .bin files + a 64-query pairs file, generated once and
    reused (atomic per-file: generate_with_ground_truth writes whole
    files; the marker file gates reuse so a killed generation rerun
    starts clean)."""
    from bibfs_tpu.graph.suite import SUITE, make_suite

    marker = os.path.join(out_dir, ".complete")
    paths = [os.path.join(out_dir, f"{label}.bin") for _n, label in SUITE]
    pairs_path = os.path.join(out_dir, "pairs_100k.txt")
    if not os.path.exists(marker):
        make_suite(out_dir, seed=0)
        import numpy as np

        rng = np.random.default_rng(0)
        n = SUITE[-1][0]
        pairs = np.stack(
            [rng.integers(0, n, 64), rng.integers(0, n, 64)], axis=1)
        np.savetxt(pairs_path, pairs, fmt="%d")
        with open(marker, "w") as f:
            f.write("ok\n")
    return paths, pairs_path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--out-dir", default=SUITE_DIR)
    ap.add_argument("--csv", default=os.path.join(REPO,
                                                  "benchmark_results.csv"))
    ap.add_argument("--table", default=os.path.join(REPO,
                                                    "benchmark_table.txt"))
    args = ap.parse_args(argv)
    t0 = time.time()

    from bibfs_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    from bibfs_tpu.cli.bench import _write_csv, _write_table, run_bench

    graphs, pairs_path = ensure_graphs(args.out_dir)
    tmp_csv = args.csv + ".leg.tmp"
    tmp_table = args.table + ".leg.tmp"

    rows = []
    # leg 1: the reference's own matrix — every backend, sync schedule.
    # The pairs file indexes the 100k graph, so batch rows only run
    # there (out-of-range pairs would fail every smaller graph's row)
    rows += run_bench(
        graphs[:-1], ["serial", "native", "dense", "sharded"],
        repeats=args.repeats, mode="sync", layout="ell",
        csv_path=tmp_csv, table_path=tmp_table,
    )
    rows += run_bench(
        graphs[-1:], ["serial", "native", "dense", "sharded"],
        repeats=args.repeats, mode="sync", layout="ell",
        pairs_file=pairs_path, csv_path=tmp_csv, table_path=tmp_table,
    )
    # leg 2: the device configs beyond the reference — the whole-level
    # fused kernel and the measured-best beamer/tiered config, 100k only
    # (the small graphs answer nothing the sync rows did not)
    for mode, layout in (("fused", "ell"), ("beamer", "tiered")):
        rows += run_bench(
            graphs[-1:], ["dense"], repeats=args.repeats, mode=mode,
            layout=layout, csv_path=tmp_csv, table_path=tmp_table,
        )
    for p in (tmp_csv, tmp_table):
        try:
            os.remove(p)
        except OSError:
            pass
    _write_csv(rows, args.csv)
    _write_table(rows, args.table)

    platforms = sorted({str(r.get("platform")) for r in rows})
    ok_rows = sum(1 for r in rows if r.get("ok"))
    out = dict(
        item="suite", rows=len(rows), ok_rows=ok_rows,
        platforms=platforms, elapsed_s=round(time.time() - t0, 1),
        csv=args.csv,
    )
    if not any(r.get("platform") not in ("host", "cpu", "?", None)
               and r.get("ok") and r.get("time_sec")
               for r in rows):
        # the watcher wants the table on REAL hardware; a CPU-substrate
        # or all-rows-failed regeneration is still written (labeled
        # rows) but not "done" — failed device rows keep their platform
        # stamp, so the platform alone proves nothing
        out["error"] = "no successful device-platform rows (tunnel down?)"
    if ok_rows < len(rows):
        out["failed_rows"] = len(rows) - ok_rows
    print("RESULT " + json.dumps(out))
    return 0 if "error" not in out else 1


if __name__ == "__main__":
    sys.exit(main())
