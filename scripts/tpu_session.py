"""One bounded TPU session: every on-chip measurement round 3 needs.

Runs each item in its own bounded subprocess (a wedged tunnel or an
HBM-exceeding program must not take the whole session down) and appends
one JSON line per item to ``TPU_SESSION.jsonl``:

1. ``pallas``   — does the reformulated pull kernel COMPILE on Mosaic?
                  Parity vs the XLA path on a 10k graph + full-solve and
                  per-level timing vs sync/ell at 100k.
2. ``mesh1``    — the 1D shard_map and 2D programs compiled + solved on a
                  real-TPU 1-device mesh (proves the collective programs
                  lower under the TPU toolchain, VERDICT r2 weak #6).
3. ``batch``    — vmapped batch sweep: per-query us at batch 32/128/256/
                  1024/2048/4096 on the 100k bench graph (the device's
                  win-regime question, VERDICT r2 next-#4).
4. ``batch_rmat`` — the same question on an RMAT-18 tiered graph, where
                  per-level device work dwarfs the fixed per-level cost.
                  Its own item (not a leg of ``batch``): a device-level
                  failure wedges a process's TPU context, so the two
                  must not share one (2026-07-31 on-chip run).
5. ``levels``   — dispatch-vs-device decomposition without a profiler:
                  fixed-trip fori_loop of the pull level at two trip
                  counts; the slope is pure device+loop cost per level,
                  the intercept is the tunnel dispatch tax.
6. ``fusion``   — the round-3 dual-exchange A/B (sync vs sync_unfused)
                  on the chip, where the per-collective fixed cost the
                  fusion targets actually lives.

Usage:  python scripts/tpu_session.py [--items pallas mesh1 batch
        batch_rmat levels fusion]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TPU_SESSION.jsonl")

PALLAS_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax, jax.numpy as jnp
out = dict(item="pallas", platform=jax.devices()[0].platform)

from bibfs_tpu.ops.pallas_expand import (
    expand_pull_pallas, pallas_available, pallas_available_at,
)
out["compiles"] = pallas_available()
if out["compiles"]:
    from bibfs_tpu.graph.csr import build_ell
    from bibfs_tpu.graph.generate import gnp_random_graph
    from bibfs_tpu.ops.expand import expand_pull

    # parity ON THE CHIP (compiled kernel vs compiled XLA path)
    rng = np.random.default_rng(0)
    n = 10_000
    edges = gnp_random_graph(n, 3.0 / n, seed=1)
    g = build_ell(n, edges)
    nbr = jnp.asarray(g.nbr); deg = jnp.asarray(g.deg)
    fr = jnp.asarray(rng.random(g.n_pad) < 0.3)
    vis = jnp.asarray(rng.random(g.n_pad) < 0.2)
    nf0, p0 = expand_pull(fr, vis, nbr, deg)
    nf1, p1 = expand_pull_pallas(fr, vis, nbr, deg)
    nf0, nf1, p0, p1 = map(np.asarray, (nf0, nf1, p0, p1))
    out["parity_nf"] = bool((nf0 == nf1).all())
    out["parity_par"] = bool((p0[nf0] == p1[nf0]).all())

    # full-solve timing: pallas vs sync on the 100k bench graph
    from bibfs_tpu.solvers.dense import DeviceGraph, time_search_only
    from bibfs_tpu.solvers.serial import solve_serial
    n2 = 100_000
    edges2 = gnp_random_graph(n2, 2.2 / n2, seed=1)
    want = solve_serial(n2, edges2, 0, n2 - 1)
    g2 = DeviceGraph.build(n2, edges2)
    # geometry-true probes: the toy pass above does NOT prove the bench
    # shape compiles (VERDICT r3 weak #1)
    out["compiles_at_bench_geom"] = pallas_available_at(
        g2.n_pad, g2.n_pad, g2.width)
    out["compiles_at_multichunk_geom"] = pallas_available_at(
        140_000, 140_000, g2.width)
    from bibfs_tpu.ops.pallas_fused import fused_available
    out["fused_compiles"] = fused_available(g2.n_pad, g2.width)
    modes = ["sync", "pallas"] + (
        ["fused", "fused_alt"] if out["fused_compiles"] else [])
    # record what each kernel mode RESOLVED to — a Mosaic-rejected mode's
    # timing row must not masquerade as a kernel number (the AOT audit
    # says 'pallas' resolves to the XLA path on real TPUs)
    from bibfs_tpu.solvers.dense import _geom_of, _resolve_pallas_mode
    out["resolved_modes"] = dict(
        (m, _resolve_pallas_mode(m, _geom_of(g2))) for m in modes)
    for mode in modes:
        times = time_search_only(g2, 0, n2 - 1, repeats=8, mode=mode)
        out["{{}}_median_s".format(mode)] = float(np.median(times))
    from bibfs_tpu.solvers.dense import solve_dense_graph
    res = solve_dense_graph(g2, 0, n2 - 1, mode="pallas")
    out["pallas_hops_ok"] = bool(res.hops == want.hops)
    if out["fused_compiles"]:
        resf = solve_dense_graph(g2, 0, n2 - 1, mode="fused")
        out["fused_hops_ok"] = bool(resf.hops == want.hops)
print("RESULT " + json.dumps(out))
"""

MESH1_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax, jax.numpy as jnp
out = dict(item="mesh1", platform=jax.devices()[0].platform)
from jax.sharding import Mesh
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.serial import solve_serial
from bibfs_tpu.parallel.mesh import VERTEX_AXIS, make_1d_mesh, make_2d_mesh
from bibfs_tpu.solvers.sharded import ShardedGraph, time_search
from bibfs_tpu.solvers.sharded2d import Sharded2DGraph, time_search_2d

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
want = solve_serial(n, edges, 0, n - 1)

g1 = ShardedGraph.build(n, edges, make_1d_mesh(1), layout="tiered")
t1, r1 = time_search(g1, 0, n - 1, repeats=5, mode="sync")
out["sharded1_median_s"] = float(np.median(t1))
out["sharded1_hops_ok"] = bool(r1.hops == want.hops)

# pallas + fused modes under a REAL (1-device) TPU mesh: the compiled
# kernel bodies execute inside shard_map (VERDICT r3 weak #2's on-chip
# half) and the whole-level kernel's per-level cost shows on the mesh
# (v2 needs no shard alignment — default padding qualifies)
gp = ShardedGraph.build(n, edges, make_1d_mesh(1))
for mode in ("pallas", "fused"):
    try:
        tm, rm = time_search(gp, 0, n - 1, repeats=5, mode=mode)
        out["sharded1_%s_median_s" % mode] = float(np.median(tm))
        out["sharded1_%s_hops_ok" % mode] = bool(rm.hops == want.hops)
    except Exception as e:
        out["sharded1_%s_error" % mode] = str(e)[:300]

g2 = Sharded2DGraph.build(n, edges, make_2d_mesh(1, 1))
t2, r2 = time_search_2d(g2, 0, n - 1, repeats=5, mode="sync")
out["sharded2d_median_s"] = float(np.median(t2))
out["sharded2d_hops_ok"] = bool(r2.hops == want.hops)
print("RESULT " + json.dumps(out))
"""

BATCH_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="batch", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.dense import DeviceGraph, time_batch_only

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
g = DeviceGraph.build(n, edges)
# the sweep owns this rng: its draw order (and so its query pairs) must
# not depend on any other leg, or runs stop being comparable
rng = np.random.default_rng(0)
rows = {{}}
# extend until HBM refuses (VERDICT r3 next-7: find where the per-query
# curve flattens, or the asymptote that bounds the win regime)
for b in (32, 128, 256, 1024, 2048, 4096):
    pairs = np.stack([rng.integers(0, n, b), rng.integers(0, n, b)], axis=1)
    reps = 5 if b <= 256 else 3
    try:
        bt = time_batch_only(g, pairs, repeats=reps, mode="sync")
        med = float(np.median(bt))
        rows[str(b)] = dict(batch_s=med, per_query_us=med / b * 1e6)
        print("batch", b, rows[str(b)], file=sys.stderr, flush=True)
    except Exception as e:
        rows[str(b)] = dict(error=str(e)[:200])
        print("batch", b, rows[str(b)], file=sys.stderr, flush=True)
        msg = str(e).lower()
        if "resource" in msg or "memory" in msg or "oom" in msg:
            break  # larger sizes will only OOM harder; transients go on
        if "unavailable" in msg or "device error" in msg:
            rows[str(b)]["note"] = (
                "device-level failure wedges this process's TPU context;"
                " stopping the escalation (later sizes would die of the"
                " wedge, not their own workload)")
            break
out["batch_100k"] = rows
if not any("per_query_us" in v for v in rows.values()):
    # no measurement landed: surface it as a retryable item failure
    # instead of a clean-looking record the watcher would accept
    out["error"] = next(iter(rows.values()))["error"]
print("RESULT " + json.dumps(out))
"""

# The other axis of the win regime: a graph where per-level device work
# dwarfs the per-level fixed cost (RMAT-18 skew, tiered layout). Its OWN
# session item, not a leg of ``batch``: a device-level failure
# (UNAVAILABLE "TPU device error") wedges a process's TPU context, so
# the legs must not share a process — on the 2026-07-31 on-chip run the
# b=2048 wedge killed the RMAT leg that followed in-process — and as a
# separate item it gets its own watcher budget, retry state, and
# artifact gate instead of being buried inside the batch record.
BATCH_RMAT_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="batch_rmat", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import rmat_graph
from bibfs_tpu.solvers.dense import DeviceGraph, time_batch_only

rng = np.random.default_rng(1)
n2, edges2 = rmat_graph(18, edge_factor=8, seed=1)
g2 = DeviceGraph.build(n2, edges2, layout="tiered")
rows2 = {{}}
wedged = False
# native C++ control on the SAME pairs: the head-to-head that decides
# whether the device batch beats the host runtime in the scale regime
try:
    from bibfs_tpu.solvers.native import NativeGraph, time_batch_native
    gn = NativeGraph.build(n2, edges2)
except Exception as e:
    gn = None
    rows2["native"] = dict(error=str(e)[:200])
# mode axis: the vmapped batch vs the batch-MINOR tiered layout (slab
# tier passes; solvers/batch_minor.py) on the SAME pairs per size
sweep2 = {{}}
for b in (32, 256):
    sweep2[b] = np.stack(
        [rng.integers(0, n2, b), rng.integers(0, n2, b)], axis=1)
for b, pairs in sweep2.items():
    if gn is not None:
        try:
            tn, _rn = time_batch_native(gn, pairs, repeats=3)
            medn = float(np.median(tn))
            rows2["native/%d" % b] = dict(
                batch_s=medn, per_query_us=medn / b * 1e6)
        except Exception as e:
            # the control must never cost the device legs the session
            rows2["native/%d" % b] = dict(error=str(e)[:200])
        print("rmat18", "native/%d" % b, rows2["native/%d" % b],
              file=sys.stderr, flush=True)
for mode in ("sync", "minor"):
    for b, pairs in sweep2.items():
        if wedged:
            break
        key = "%s/%d" % (mode, b)
        try:
            bt = time_batch_only(g2, pairs, repeats=3, mode=mode)
            med = float(np.median(bt))
            rows2[key] = dict(batch_s=med, per_query_us=med / b * 1e6)
            print("rmat18", key, rows2[key], file=sys.stderr, flush=True)
        except Exception as e:
            rows2[key] = dict(error=str(e)[:200])
            print("rmat18", key, rows2[key], file=sys.stderr, flush=True)
            wedged = True  # the context is suspect after any failure
out["batch_rmat18"] = rows2
dev_rows = {{k: v for k, v in rows2.items()
             if not k.startswith("native")}}
if not any("per_query_us" in v for v in dev_rows.values()):
    # no DEVICE measurement landed (the host-native control rows do not
    # count): surface it as a retryable item failure instead of a
    # clean-looking record the watcher would accept
    out["error"] = (next(iter(dev_rows.values()))["error"] if dev_rows
                    else "no device rows ran")
print("RESULT " + json.dumps(out))
"""

# The batch-MINOR layout on the chip (solvers/batch_minor.py): same
# graph family and sweep shape as ``batch``, so the two items' per-query
# curves are directly comparable. The vmapped sync control runs FIRST on
# the same pairs at b=256 (before any size that could wedge the TPU
# context), and an 8-pair oracle parity gate guards the whole sweep —
# a fast wrong answer must read as a failure, not a win.
BATCH_MINOR_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="batch_minor", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.dense import (
    DeviceGraph, solve_batch_graph, time_batch_only,
)
from bibfs_tpu.solvers.serial import solve_serial

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
g = DeviceGraph.build(n, edges)
rng = np.random.default_rng(0)  # the sweep owns this rng (see batch item)

# oracle parity gate on-chip: 8 mixed pairs incl. src==dst, BOTH modes
gate = np.stack([rng.integers(0, n, 8), rng.integers(0, n, 8)], axis=1)
gate[3] = (7, 7)
ok = True
for gmode in ("minor", "minor8"):
    res = solve_batch_graph(g, gate, mode=gmode)
    for (s, d), r in zip(gate, res):
        ref = solve_serial(n, edges, int(s), int(d))
        ok = ok and (r.found == ref.found) and (
            not ref.found or r.hops == ref.hops)
out["parity_ok"] = bool(ok)
if not ok:
    out["error"] = "minor-path hop parity FAILED on chip"
    print("RESULT " + json.dumps(out))
    sys.exit(0)

rows = {{}}
pairs256 = np.stack(
    [rng.integers(0, n, 256), rng.integers(0, n, 256)], axis=1)
# vmapped sync control, SAME pairs, before any size that could wedge
bt = time_batch_only(g, pairs256, repeats=3, mode="sync")
med = float(np.median(bt))
out["sync_control_256"] = dict(batch_s=med, per_query_us=med / 256 * 1e6)
print("sync control", out["sync_control_256"], file=sys.stderr, flush=True)

wedged = False
sweep_pairs = {{}}
for b in (32, 128, 256, 1024, 2048, 4096):
    sweep_pairs[b] = (pairs256[:b] if b <= 256 else np.stack(
        [rng.integers(0, n, b), rng.integers(0, n, b)], axis=1))
for mode in ("minor", "minor8"):
    rows = {{}}
    for b, pairs in sweep_pairs.items():
        if wedged:
            break
        reps = 5 if b <= 256 else 3
        try:
            bt = time_batch_only(g, pairs, repeats=reps, mode=mode)
            med = float(np.median(bt))
            rows[str(b)] = dict(batch_s=med, per_query_us=med / b * 1e6)
            print(mode, b, rows[str(b)], file=sys.stderr, flush=True)
        except Exception as e:
            rows[str(b)] = dict(error=str(e)[:200])
            print(mode, b, rows[str(b)], file=sys.stderr, flush=True)
            msg = str(e).lower()
            if "resource" in msg or "memory" in msg or "oom" in msg:
                break
            if "unavailable" in msg or "device error" in msg:
                rows[str(b)]["note"] = (
                    "device-level failure wedges this process's TPU "
                    "context; stopping every further escalation")
                wedged = True
    out["%s_100k" % mode] = rows
for key in ("minor_100k", "minor8_100k"):
    rows = out[key]
    if "error" not in out and not any(
            "per_query_us" in v for v in rows.values()):
        # no measurement landed for this mode (wedged earlier, or every
        # size errored): surface it as a retryable item failure instead
        # of a clean-looking record the watcher would accept. First
        # failure wins — a later mode's derived symptom must not
        # overwrite the root-cause device error
        out["error"] = (
            next(iter(rows.values()))["error"] if rows
            else "%s: no sizes ran (context wedged earlier)" % key)
print("RESULT " + json.dumps(out))
"""

LEVELS_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax, jax.numpy as jnp
from functools import partial
out = dict(item="levels", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.ops.expand import expand_pull_dual_tiered
from bibfs_tpu.ops.pallas_expand import (
    pallas_available, pallas_pull_level_dual, prepare_pallas_tables,
)
from bibfs_tpu.solvers.dense import INF32, DeviceGraph

# fixed-trip loop of the real dual-pull level body: wall(T) = dispatch +
# T * level_cost. Two trip counts give both terms without a profiler; the
# same protocol runs the XLA level and the compiled Pallas level, so
# their per-level device costs are directly comparable (VERDICT r2 #1:
# "at least matching sync/ell, with the level time measured").
n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
g = DeviceGraph.build(n, edges)

@partial(jax.jit, static_argnames=("trips", "use_pallas"))
def run(nbr, deg, tables, trips, use_pallas):
    n_pad = nbr.shape[0]
    fr = jnp.zeros(n_pad, jnp.bool_).at[0].set(True)
    st = (fr, fr, jnp.full(n_pad, -1, jnp.int32),
          jnp.where(fr, 0, INF32).astype(jnp.int32),
          jnp.full(n_pad, -1, jnp.int32),
          jnp.where(fr, 0, INF32).astype(jnp.int32))
    def body(i, st):
        fs, ft, ps, ds, pt, dt = st
        if use_pallas:
            nf_s, ps, ds, _m1, nf_t, pt, dt, _m2 = pallas_pull_level_dual(
                fs, ft, ps, ds, pt, dt, tables, deg, (), i + 1, i + 1,
                inf=INF32)
        else:
            nf_s, ps, ds, _m1, nf_t, pt, dt, _m2 = expand_pull_dual_tiered(
                fs, ft, ps, ds, pt, dt, nbr, deg, (), i + 1, i + 1,
                inf=INF32)
        return (nf_s, nf_t, ps, ds, pt, dt)
    st = jax.lax.fori_loop(0, trips, body, st)
    return st[2].sum() + st[4].sum()

variants = [("xla", False)]
if pallas_available():
    variants.append(("pallas", True))
out["pallas_compiles"] = len(variants) == 2
# built ONCE, outside the timed region (its own contract), so the pallas
# variant's dispatch_s stays comparable to xla's
tables = jax.jit(prepare_pallas_tables)(g.nbr, g.deg)
# per-level HBM traffic models: the XLA/pallas pull reads the table once
# plus ~13 B/vertex of state; the fused v2 level additionally writes and
# re-reads the gathered vals block (one table-sized intermediate)
bytes_per_level = g.n_pad * g.width * 4 + g.n_pad * 13
bytes_per_level_fused = 3 * g.n_pad * g.width * 4 + g.n_pad * 13


def decompose(walls, bpl):
    per_level = (walls[64] - walls[4]) / 60.0
    return dict(
        wall_T4_s=walls[4], wall_T64_s=walls[64],
        device_level_s=per_level,
        dispatch_s=walls[4] - 4 * per_level,
        hbm_gbps_per_level=(
            bpl / per_level / 1e9 if per_level > 0 else None),
    )


def protocol(fn, bpl=bytes_per_level):
    walls = {{}}
    for trips in (4, 64):
        vals = []
        for rep in range(6):
            t0 = time.perf_counter()
            fn(trips)  # must force a value read
            vals.append(time.perf_counter() - t0)
        walls[trips] = float(np.median(vals[1:]))
    return decompose(walls, bpl)


for name, use_pallas in variants:
    out[name] = protocol(
        lambda trips: int(run(g.nbr, g.deg, tables, trips, use_pallas)))

# the round-4 whole-level kernel (v2: XLA dual gather + ONE kernel):
# the same fixed-trip protocol over the fused state — the per-level
# DELTA vs xla/pallas is the measured answer to VERDICT r3 item 2
from bibfs_tpu.ops.pallas_fused import (
    INF32, dual_seed, fused_available, fused_dual_level, key_stride,
    prepare_fused_tables,
)
out["fused_compiles"] = fused_available(g.n_pad, g.width)
if out["fused_compiles"]:
    ftables = jax.jit(prepare_fused_tables)(g.nbr, g.deg)
    n_rows_p = ftables[0].shape[1]
    ks = key_stride(g.n_pad)

    @partial(jax.jit, static_argnames=("trips",))
    def run_fused(tabs, trips):
        nbr_t, deg2 = tabs
        dual = dual_seed(jnp.int32(0), jnp.int32(1), n_rows_p)
        dist = jnp.full((1, n_rows_p), INF32, jnp.int32).at[0, 0].set(0)
        par = jnp.full((1, n_rows_p), -1, jnp.int32)
        st = (dual, dist, dist, par, par)
        def body(i, st):
            outs = fused_dual_level(
                st[0], nbr_t, deg2, st[1], st[2], st[3], st[4],
                i + 1, i + 1, ks=ks)
            return outs[:5]
        st = jax.lax.fori_loop(0, trips, body, st)
        return st[1].sum() + st[2].sum()

    out["fused"] = protocol(
        lambda trips: int(run_fused(ftables, trips)),
        bpl=bytes_per_level_fused)
print("RESULT " + json.dumps(out))
"""

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from ab_fusion import (  # noqa: E402
    FUSION_ITEM_TEMPLATE,
    run_result_subprocess,
)

ITEMS = {
    "pallas": (PALLAS_SUB, 900),
    "mesh1": (MESH1_SUB, 900),
    "batch": (BATCH_SUB, 2100),
    "batch_minor": (BATCH_MINOR_SUB, 1500),
    # two modes x two sizes + compiles: needs more than the old 900
    "batch_rmat": (BATCH_RMAT_SUB, 1500),
    "levels": (LEVELS_SUB, 900),
    # the round-3 dual-fusion A/B (sync vs sync_unfused) on the chip,
    # where the per-level fixed cost the fusion targets actually lives
    "fusion": (FUSION_ITEM_TEMPLATE, 1200),
}


def run_item(name: str) -> dict:
    code, timeout = ITEMS[name]
    # the shared bounded-subprocess/RESULT protocol lives in ab_fusion
    return run_result_subprocess(name, code.format(repo=REPO), timeout)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", nargs="+", default=list(ITEMS),
                    choices=list(ITEMS))
    args = ap.parse_args(argv)
    rc = 0
    for name in args.items:
        out = run_item(name)
        out["recorded"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        with open(OUT, "a") as f:
            f.write(json.dumps(out) + "\n")
        print(json.dumps(out), flush=True)
        if "error" in out:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
