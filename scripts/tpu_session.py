"""One bounded TPU session: every on-chip measurement round 3 needs.

Runs each item in its own bounded subprocess (a wedged tunnel or an
HBM-exceeding program must not take the whole session down) and appends
one JSON line per item to ``TPU_SESSION.jsonl``:

1. ``pallas``   — does the reformulated pull kernel COMPILE on Mosaic?
                  Parity vs the XLA path on a 10k graph + full-solve and
                  per-level timing vs sync/ell at 100k.
2. ``mesh1``    — the 1D shard_map and 2D programs compiled + solved on a
                  real-TPU 1-device mesh (proves the collective programs
                  lower under the TPU toolchain, VERDICT r2 weak #6).
3. ``batch``    — vmapped batch sweep: per-query us at batch 32/128/256/
                  1024/2048/4096 on the 100k bench graph (the device's
                  win-regime question, VERDICT r2 next-#4).
4. ``batch_rmat`` — the same question on an RMAT-18 tiered graph, where
                  per-level device work dwarfs the fixed per-level cost.
                  Its own item (not a leg of ``batch``): a device-level
                  failure wedges a process's TPU context, so the two
                  must not share one (2026-07-31 on-chip run).
5. ``levels``   — dispatch-vs-device decomposition without a profiler:
                  fixed-trip fori_loop of the pull level at two trip
                  counts; the slope is pure device+loop cost per level,
                  the intercept is the tunnel dispatch tax.
6. ``fusion``   — the round-3 dual-exchange A/B (sync vs sync_unfused)
                  on the chip, where the per-collective fixed cost the
                  fusion targets actually lives.

Usage:  python scripts/tpu_session.py [--items pallas mesh1 batch
        batch_rmat levels fusion]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TPU_SESSION.jsonl")

PALLAS_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax, jax.numpy as jnp
out = dict(item="pallas", platform=jax.devices()[0].platform)

from bibfs_tpu.ops.pallas_expand import (
    expand_pull_pallas, pallas_available, pallas_available_at,
)
out["compiles"] = pallas_available()
if out["compiles"]:
    from bibfs_tpu.graph.csr import build_ell
    from bibfs_tpu.graph.generate import gnp_random_graph
    from bibfs_tpu.ops.expand import expand_pull

    # parity ON THE CHIP (compiled kernel vs compiled XLA path)
    rng = np.random.default_rng(0)
    n = 10_000
    edges = gnp_random_graph(n, 3.0 / n, seed=1)
    g = build_ell(n, edges)
    nbr = jnp.asarray(g.nbr); deg = jnp.asarray(g.deg)
    fr = jnp.asarray(rng.random(g.n_pad) < 0.3)
    vis = jnp.asarray(rng.random(g.n_pad) < 0.2)
    nf0, p0 = expand_pull(fr, vis, nbr, deg)
    nf1, p1 = expand_pull_pallas(fr, vis, nbr, deg)
    nf0, nf1, p0, p1 = map(np.asarray, (nf0, nf1, p0, p1))
    out["parity_nf"] = bool((nf0 == nf1).all())
    out["parity_par"] = bool((p0[nf0] == p1[nf0]).all())

    # full-solve timing: pallas vs sync on the 100k bench graph
    from bibfs_tpu.solvers.dense import DeviceGraph, time_search_only
    from bibfs_tpu.solvers.serial import solve_serial
    n2 = 100_000
    edges2 = gnp_random_graph(n2, 2.2 / n2, seed=1)
    want = solve_serial(n2, edges2, 0, n2 - 1)
    g2 = DeviceGraph.build(n2, edges2)
    # geometry-true probes: the toy pass above does NOT prove the bench
    # shape compiles (VERDICT r3 weak #1)
    out["compiles_at_bench_geom"] = pallas_available_at(
        g2.n_pad, g2.n_pad, g2.width)
    out["compiles_at_multichunk_geom"] = pallas_available_at(
        140_000, 140_000, g2.width)
    from bibfs_tpu.ops.pallas_fused import fused_available
    out["fused_compiles"] = fused_available(g2.n_pad, g2.width)
    modes = ["sync", "pallas"] + (
        ["fused", "fused_alt"] if out["fused_compiles"] else [])
    # record what each kernel mode RESOLVED to — a Mosaic-rejected mode's
    # timing row must not masquerade as a kernel number (the AOT audit
    # says 'pallas' resolves to the XLA path on real TPUs)
    from bibfs_tpu.solvers.dense import _geom_of, _resolve_pallas_mode
    out["resolved_modes"] = dict(
        (m, _resolve_pallas_mode(m, _geom_of(g2))) for m in modes)
    for mode in modes:
        times = time_search_only(g2, 0, n2 - 1, repeats=8, mode=mode)
        out["{{}}_median_s".format(mode)] = float(np.median(times))
    from bibfs_tpu.solvers.dense import solve_dense_graph
    res = solve_dense_graph(g2, 0, n2 - 1, mode="pallas")
    out["pallas_hops_ok"] = bool(res.hops == want.hops)
    if out["fused_compiles"]:
        resf = solve_dense_graph(g2, 0, n2 - 1, mode="fused")
        out["fused_hops_ok"] = bool(resf.hops == want.hops)
print("RESULT " + json.dumps(out))
"""

MESH1_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax, jax.numpy as jnp
out = dict(item="mesh1", platform=jax.devices()[0].platform)
from jax.sharding import Mesh
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.serial import solve_serial
from bibfs_tpu.parallel.mesh import VERTEX_AXIS, make_1d_mesh, make_2d_mesh
from bibfs_tpu.solvers.sharded import ShardedGraph, time_search
from bibfs_tpu.solvers.sharded2d import Sharded2DGraph, time_search_2d

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
want = solve_serial(n, edges, 0, n - 1)

g1 = ShardedGraph.build(n, edges, make_1d_mesh(1), layout="tiered")
t1, r1 = time_search(g1, 0, n - 1, repeats=5, mode="sync")
out["sharded1_median_s"] = float(np.median(t1))
out["sharded1_hops_ok"] = bool(r1.hops == want.hops)

# pallas + fused modes under a REAL (1-device) TPU mesh: the compiled
# kernel bodies execute inside shard_map (VERDICT r3 weak #2's on-chip
# half) and the whole-level kernel's per-level cost shows on the mesh
# (v2 needs no shard alignment — default padding qualifies)
gp = ShardedGraph.build(n, edges, make_1d_mesh(1))
for mode in ("pallas", "fused"):
    try:
        tm, rm = time_search(gp, 0, n - 1, repeats=5, mode=mode)
        out["sharded1_%s_median_s" % mode] = float(np.median(tm))
        out["sharded1_%s_hops_ok" % mode] = bool(rm.hops == want.hops)
    except Exception as e:
        out["sharded1_%s_error" % mode] = str(e)[:300]

g2 = Sharded2DGraph.build(n, edges, make_2d_mesh(1, 1))
t2, r2 = time_search_2d(g2, 0, n - 1, repeats=5, mode="sync")
out["sharded2d_median_s"] = float(np.median(t2))
out["sharded2d_hops_ok"] = bool(r2.hops == want.hops)
print("RESULT " + json.dumps(out))
"""

BATCH_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="batch", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.dense import DeviceGraph, time_batch_only

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
g = DeviceGraph.build(n, edges)
# the sweep owns this rng: its draw order (and so its query pairs) must
# not depend on any other leg, or runs stop being comparable
rng = np.random.default_rng(0)
rows = {{}}
# extend until HBM refuses (VERDICT r3 next-7: find where the per-query
# curve flattens, or the asymptote that bounds the win regime)
for b in (32, 128, 256, 1024, 2048, 4096):
    pairs = np.stack([rng.integers(0, n, b), rng.integers(0, n, b)], axis=1)
    reps = 5 if b <= 256 else 3
    try:
        bt = time_batch_only(g, pairs, repeats=reps, mode="sync")
        med = float(np.median(bt))
        rows[str(b)] = dict(batch_s=med, per_query_us=med / b * 1e6)
        print("batch", b, rows[str(b)], file=sys.stderr, flush=True)
    except Exception as e:
        rows[str(b)] = dict(error=str(e)[:200])
        print("batch", b, rows[str(b)], file=sys.stderr, flush=True)
        msg = str(e).lower()
        if "resource" in msg or "memory" in msg or "oom" in msg:
            break  # larger sizes will only OOM harder; transients go on
        if "unavailable" in msg or "device error" in msg:
            rows[str(b)]["note"] = (
                "device-level failure wedges this process's TPU context;"
                " stopping the escalation (later sizes would die of the"
                " wedge, not their own workload)")
            break
out["batch_100k"] = rows
if not any("per_query_us" in v for v in rows.values()):
    # no measurement landed: surface it as a retryable item failure
    # instead of a clean-looking record the watcher would accept
    out["error"] = next(iter(rows.values()))["error"]
print("RESULT " + json.dumps(out))
"""

# The other axis of the win regime: a graph where per-level device work
# dwarfs the per-level fixed cost (RMAT-18 skew, tiered layout). Round
# 4 ran this as ONE monolithic subprocess and a single slow leg burned
# a whole 900 s hardware window (TPU_WATCH_STATUS r4); it is now a
# RESUMABLE per-leg driver (`run_batch_rmat`): the graph + query pairs
# are generated once into a host-side cache, every (mode, b) leg runs
# in its own bounded subprocess with a FRESH TPU context (a wedge in
# one leg cannot poison the next), and completed device legs persist in
# a partial file so a watcher retry only pays for what is still
# missing. The native C++ control runs first (host-only — it cannot
# wedge anything) on the SAME pairs.
RMAT_PARTIAL = os.path.join(REPO, ".rmat_partial.json")

RMAT_PREP_SUB = """
import json, sys
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import force_cpu
force_cpu()  # generation is host work; never touch the tunnel
import numpy as np
from bibfs_tpu.graph.generate import rmat_graph
# rng draw order matches the round-4 item exactly (default_rng(seed),
# then one src draw + one dst draw per size, ascending), so the pairs
# (and any numbers already published for them) stay comparable
rng = np.random.default_rng({seed})
n, edges = rmat_graph({scale}, edge_factor={ef}, seed={seed})
pairs = {{}}
for b in {sizes!r}:
    pairs[b] = np.stack(
        [rng.integers(0, n, b), rng.integers(0, n, b)], axis=1)
# atomic write: a watchdog kill mid-savez must not leave a truncated
# cache that os.path.exists would then trust forever
import os
tmp = {cache!r} + ".tmp.npz"
np.savez(tmp, n=n, edges=edges,
         **{{"p%d" % b: p for b, p in pairs.items()}})
os.replace(tmp, {cache!r})
print("RESULT " + json.dumps(
    dict(item="rmat_prep", n=int(n), m=int(len(edges)))))
"""

RMAT_NATIVE_SUB = """
import json, sys
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import force_cpu
force_cpu()  # host C++ control; the TPU context stays untouched
import numpy as np
d = np.load({cache!r})
n = int(d["n"]); edges = d["edges"]
from bibfs_tpu.solvers.native import NativeGraph, time_batch_native
g = NativeGraph.build(n, edges)
rows = {{}}
for b in {sizes!r}:
    pairs = d["p%d" % b]
    key = "native/%d" % b
    t, _ = time_batch_native(g, pairs, repeats=3)
    med = float(np.median(t))
    rows[key] = dict(batch_s=med, per_query_us=med / len(pairs) * 1e6)
    print("rmat", key, rows[key], file=sys.stderr, flush=True)
print("RESULT " + json.dumps(
    dict(item="rmat_leg", platform="host", rows=rows)))
"""

RMAT_DEV_LEG_SUB = """
import json, sys
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
d = np.load({cache!r})
n = int(d["n"]); edges = d["edges"]
pairs = d["p%d" % {b}]
from bibfs_tpu.solvers.dense import DeviceGraph, time_batch_only
g = DeviceGraph.build(n, edges, layout="tiered")
bt = time_batch_only(g, pairs, repeats=3, mode={mode!r})
med = float(np.median(bt))
out = dict(item="rmat_leg", platform=jax.devices()[0].platform,
           rows={{{key!r}: dict(batch_s=med,
                                per_query_us=med / {b} * 1e6)}})
print("RESULT " + json.dumps(out))
"""


def _load_rmat_partial(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"rows": {}}


def _save_rmat_partial(path: str, partial: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(partial, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def run_batch_rmat(scale: int = 18, ef: int = 8, seed: int = 1,
                   sizes: tuple = (32, 256), partial_path: str = RMAT_PARTIAL,
                   leg_timeout: int = 900) -> dict:
    """Resumable RMAT batch sweep: one bounded subprocess per leg.

    A leg is DONE when its row carries ``per_query_us`` — and, for the
    device legs, a non-cpu platform (a degraded CPU-substrate run is
    re-tried, never banked). Done legs are skipped on retry via the
    partial file, so after a mid-sweep watchdog kill the next attempt
    pays only for the missing legs. Every leg gets a FRESH process (and
    so a fresh TPU context): a wedge in one cannot poison the next.
    The merged record is clean (no ``error``) once the head-to-head
    VERDICT r4 asks for exists: at least one non-cpu ``sync/*`` row AND
    one non-cpu ``minor/*`` row; the partial file is removed once every
    device leg has landed."""
    t0 = time.time()
    # the sizes tuple is part of the cache identity: the prep writes one
    # 'p<b>' pairs array per size, so a cache built for a different size
    # set would fail every device leg with KeyError 'p<b>' until the
    # stale npz is hand-deleted (ADVICE r5 #1)
    cache = "/tmp/bibfs_rmat%d_ef%d_s%d_b%s.npz" % (
        scale, ef, seed, "x".join(str(int(b)) for b in sizes))
    rows = dict(_load_rmat_partial(partial_path).get("rows", {}))
    if not os.path.exists(cache):
        prep = run_result_subprocess(
            "rmat_prep", RMAT_PREP_SUB.format(
                repo=REPO, cache=cache, scale=scale, ef=ef, seed=seed,
                sizes=tuple(sizes)),
            leg_timeout)
        if "error" in prep:
            return dict(item="batch_rmat",
                        error="prep: %s" % str(prep["error"])[:300],
                        elapsed_s=round(time.time() - t0, 1))
    dev_keys = ["%s/%d" % (m, b) for m in ("sync", "minor") for b in sizes]

    def dev_done(key: str) -> bool:
        r = rows.get(key, {})
        return "per_query_us" in r and r.get("platform") not in (
            None, "", "cpu")

    if not all("per_query_us" in rows.get("native/%d" % b, {})
               for b in sizes):
        leg = run_result_subprocess(
            "rmat_native",
            RMAT_NATIVE_SUB.format(repo=REPO, cache=cache,
                                   sizes=tuple(sizes)),
            leg_timeout)
        for k, v in leg.get("rows", {}).items():
            rows[k] = v
        if "error" in leg:  # the control must not cost the device legs
            # dedicated key: writing the error into rows['native/<b>']
            # could overwrite a previously banked good row when the leg
            # partially resumed (ADVICE r5 #3)
            rows["native_error"] = dict(error=str(leg["error"])[:200])
        _save_rmat_partial(partial_path, {"rows": rows})
    for key in dev_keys:
        if dev_done(key):
            continue
        mode, b = key.split("/")
        leg = run_result_subprocess(
            "rmat_" + key.replace("/", "_"),
            RMAT_DEV_LEG_SUB.format(repo=REPO, cache=cache,
                                    b=int(b), mode=mode, key=key),
            leg_timeout)
        legplat = leg.get("platform")
        for k, v in leg.get("rows", {}).items():
            rows[k] = dict(v, platform=legplat)
        if "error" in leg:
            rows[key] = dict(error=str(leg["error"])[:200])
        # bank progress after EVERY leg: a later wedge or watchdog kill
        # must not lose this leg's measurement
        _save_rmat_partial(partial_path, {"rows": rows})
    platform = next((rows[k]["platform"] for k in dev_keys
                     if dev_done(k)), "cpu")
    out = dict(item="batch_rmat", platform=platform, batch_rmat18=rows,
               elapsed_s=round(time.time() - t0, 1))
    have_sync = any(dev_done(k) for k in dev_keys if k.startswith("sync"))
    have_minor = any(dev_done(k) for k in dev_keys if k.startswith("minor"))
    if not (have_sync and have_minor):
        missing = [k for k in dev_keys if not dev_done(k)]
        first_err = next((rows[k]["error"] for k in dev_keys
                          if "error" in rows.get(k, {})), None)
        out["error"] = "device legs incomplete: %s%s" % (
            ",".join(missing),
            (" (first error: %s)" % first_err) if first_err else "")
    elif all(dev_done(k) for k in dev_keys):
        try:  # sweep complete: the partial file has served its purpose
            os.remove(partial_path)
        except OSError:
            pass
    return out

# The batch-MINOR layout on the chip (solvers/batch_minor.py): same
# graph family and sweep shape as ``batch``, so the two items' per-query
# curves are directly comparable. The vmapped sync control runs FIRST on
# the same pairs at b=256 (before any size that could wedge the TPU
# context), and an 8-pair oracle parity gate guards the whole sweep —
# a fast wrong answer must read as a failure, not a win.
BATCH_MINOR_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="batch_minor", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.dense import (
    DeviceGraph, solve_batch_graph, time_batch_only,
)
from bibfs_tpu.solvers.serial import solve_serial

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
g = DeviceGraph.build(n, edges)
rng = np.random.default_rng(0)  # the sweep owns this rng (see batch item)

# oracle parity gate on-chip: 8 mixed pairs incl. src==dst, BOTH modes
gate = np.stack([rng.integers(0, n, 8), rng.integers(0, n, 8)], axis=1)
gate[3] = (7, 7)
ok = True
for gmode in ("minor", "minor8"):
    res = solve_batch_graph(g, gate, mode=gmode)
    for (s, d), r in zip(gate, res):
        ref = solve_serial(n, edges, int(s), int(d))
        ok = ok and (r.found == ref.found) and (
            not ref.found or r.hops == ref.hops)
out["parity_ok"] = bool(ok)
if not ok:
    out["error"] = "minor-path hop parity FAILED on chip"
    print("RESULT " + json.dumps(out))
    sys.exit(0)

rows = {{}}
pairs256 = np.stack(
    [rng.integers(0, n, 256), rng.integers(0, n, 256)], axis=1)
# vmapped sync control, SAME pairs, before any size that could wedge
bt = time_batch_only(g, pairs256, repeats=3, mode="sync")
med = float(np.median(bt))
out["sync_control_256"] = dict(batch_s=med, per_query_us=med / 256 * 1e6)
print("sync control", out["sync_control_256"], file=sys.stderr, flush=True)

wedged = False
sweep_pairs = {{}}
for b in (32, 128, 256, 1024, 2048, 4096):
    sweep_pairs[b] = (pairs256[:b] if b <= 256 else np.stack(
        [rng.integers(0, n, b), rng.integers(0, n, b)], axis=1))
for mode in ("minor", "minor8"):
    rows = {{}}
    for b, pairs in sweep_pairs.items():
        if wedged:
            break
        reps = 5 if b <= 256 else 3
        try:
            bt = time_batch_only(g, pairs, repeats=reps, mode=mode)
            med = float(np.median(bt))
            rows[str(b)] = dict(batch_s=med, per_query_us=med / b * 1e6)
            print(mode, b, rows[str(b)], file=sys.stderr, flush=True)
        except Exception as e:
            rows[str(b)] = dict(error=str(e)[:200])
            print(mode, b, rows[str(b)], file=sys.stderr, flush=True)
            msg = str(e).lower()
            if "resource" in msg or "memory" in msg or "oom" in msg:
                break
            if "unavailable" in msg or "device error" in msg:
                rows[str(b)]["note"] = (
                    "device-level failure wedges this process's TPU "
                    "context; stopping every further escalation")
                wedged = True
    out["%s_100k" % mode] = rows
for key in ("minor_100k", "minor8_100k"):
    rows = out[key]
    if "error" not in out and not any(
            "per_query_us" in v for v in rows.values()):
        # no measurement landed for this mode (wedged earlier, or every
        # size errored): surface it as a retryable item failure instead
        # of a clean-looking record the watcher would accept. First
        # failure wins — a later mode's derived symptom must not
        # overwrite the root-cause device error
        out["error"] = (
            next(iter(rows.values()))["error"] if rows
            else "%s: no sizes ran (context wedged earlier)" % key)
print("RESULT " + json.dumps(out))
"""

# Round-5 question (VERDICT r4 weak #2 / next #5): the fused schedule's
# residual ~12 ms/level is a FIXED per-while-iteration cost, not device
# compute (the fori_loop slope in `levels` is far smaller). dense._unrolled
# runs k rounds per while iteration — this item measures the 100k single
# query at k = 1/2/4/8 for the two best schedules, hop-parity-gated, and
# reports ms/level so the before/after the VERDICT asks for is explicit.
UNROLL_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="unroll", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.dense import DeviceGraph, time_search
from bibfs_tpu.solvers.serial import solve_serial

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
want = solve_serial(n, edges, 0, n - 1)
g = DeviceGraph.build(n, edges)
rows = {{}}
bad = None
for mode in ("fused", "sync"):
    for k in (1, 2, 4, 8):
        key = "%s/u%d" % (mode, k)
        try:
            times, res = time_search(g, 0, n - 1, repeats=6,
                                     mode=mode, unroll=k)
            med = float(np.median(times))
            rows[key] = dict(
                median_s=med, levels=int(res.levels),
                ms_per_level=med / max(res.levels, 1) * 1e3,
                hops_ok=bool(res.hops == want.hops))
            if not rows[key]["hops_ok"]:
                bad = key  # a fast wrong answer must fail the item
        except Exception as e:
            rows[key] = dict(error=str(e)[:200])
        print("unroll", key, rows[key], file=sys.stderr, flush=True)
out["unroll_100k"] = rows
# the mesh program's rounds add collectives to the fixed per-iteration
# cost — two rows on a real-TPU 1-device mesh say whether unrolling
# amortizes that tax too (collectives unroll under the replicated
# vote). Own dict + fully guarded: a sharded failure (or OOM building
# a second 100k graph) must neither discard the dense rows above nor
# let a sharded success mask a total dense-sweep failure below.
sh_rows = {{}}
try:
    from bibfs_tpu.parallel.mesh import make_1d_mesh
    from bibfs_tpu.solvers.sharded import ShardedGraph
    from bibfs_tpu.solvers.sharded import time_search as ts_sh

    gs = ShardedGraph.build(n, edges, make_1d_mesh(1))
    for k in (1, 8):
        key = "u%d" % k
        try:
            times, res = ts_sh(gs, 0, n - 1, repeats=4,
                               mode="sync", unroll=k)
            med = float(np.median(times))
            sh_rows[key] = dict(
                median_s=med, levels=int(res.levels),
                ms_per_level=med / max(res.levels, 1) * 1e3,
                hops_ok=bool(res.hops == want.hops))
            if not sh_rows[key]["hops_ok"]:
                bad = "sharded1/" + key
        except Exception as e:
            sh_rows[key] = dict(error=str(e)[:200])
        print("unroll sharded1", key, sh_rows[key],
              file=sys.stderr, flush=True)
except Exception as e:
    sh_rows["build"] = dict(error=str(e)[:200])
out["unroll_sharded1"] = sh_rows
if bad is not None:
    out["error"] = "hop parity FAILED at %s" % bad
elif not any("median_s" in v for v in rows.values()):
    # the guard is scoped to the DENSE rows — the item's primary
    # question — so sharded success cannot mask a dense failure
    out["error"] = next(iter(rows.values()))["error"]
print("RESULT " + json.dumps(out))
"""

# VERDICT r4 weak #5: the minor8 depth-cap re-solve (INF8=127 forces a
# round cap; still-live queries refill through the int32 kernel in the
# untimed finish) had only ever run via a forced splice on CPU. This
# item drives it for real on the chip: a deep line graph (399 hops >>
# the 126-round cap) through mode='minor8' AND mode='auto', asserting
# the capped flag actually fires and oracle parity holds after refill.
DEEPCAP_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="deepcap", platform=jax.devices()[0].platform)
from bibfs_tpu.solvers.dense import (
    DeviceGraph, _batch_dispatch, solve_batch_graph,
)
from bibfs_tpu.solvers.serial import solve_serial

n = 400
edges = np.array([[i, i + 1] for i in range(n - 1)])
g = DeviceGraph.build(n, edges)
rng = np.random.default_rng(3)
# half shallow pairs, half deep ones that MUST trip the cap (the line's
# endpoints are 399 hops apart; the cap stops both sides at round 126)
pairs = np.stack([rng.integers(0, n, 32), rng.integers(0, n, 32)], axis=1)
pairs[16:] = [(i % 4, n - 1 - (i % 4)) for i in range(16)]
t0 = time.perf_counter()
_, thunk, finish = _batch_dispatch(g, pairs, "minor8")
raw = thunk()
capped = int(np.asarray(raw[-1])[: len(pairs)].sum())
res8 = finish(raw)
out["capped_queries"] = capped
out["solve_s"] = time.perf_counter() - t0
bad = 0
best8 = np.asarray(res8[0])
for i, (s, d) in enumerate(pairs):
    ref = solve_serial(n, edges, int(s), int(d))
    ok = (best8[i] < 2**30) == ref.found and (
        not ref.found or int(best8[i]) == ref.hops)
    bad += 0 if ok else 1
out["parity_bad"] = bad
# the public path too: auto resolves to minor8 for this shape
res_auto = solve_batch_graph(g, pairs, mode="auto")
auto_bad = 0
for (s, d), r in zip(pairs, res_auto):
    ref = solve_serial(n, edges, int(s), int(d))
    ok = r.found == ref.found and (not ref.found or r.hops == ref.hops)
    auto_bad += 0 if ok else 1
out["auto_parity_bad"] = auto_bad
if capped == 0:
    out["error"] = "depth cap never fired (test graph too shallow?)"
elif bad or auto_bad:
    out["error"] = "parity FAILED after depth-cap refill"
print("RESULT " + json.dumps(out))
"""

# VERDICT r4 next #5: a committed profiler decomposition of the fused
# 100k solve. jax.profiler's perfetto trace is plain JSON: summing slice
# durations per process (host python / TPU device lanes) and per op name
# separates tunnel/dispatch time from on-chip compute without any xprof
# tooling. The summary lands in PROFILE_FUSED.json at the repo root.
PROFILE_SUB = """
import collections, glob, gzip, json, os, sys, tempfile, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
out = dict(item="profile", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.dense import (
    DeviceGraph, solve_dense_graph, time_search_only,
)
from bibfs_tpu.solvers.serial import solve_serial

n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
want = solve_serial(n, edges, 0, n - 1)
g = DeviceGraph.build(n, edges)
res = solve_dense_graph(g, 0, n - 1, mode="fused")  # warm-up + parity
out["hops_ok"] = bool(res.hops == want.hops)
out["levels"] = int(res.levels)
d = tempfile.mkdtemp(prefix="bibfs_prof_")
t0 = time.perf_counter()
with jax.profiler.trace(d, create_perfetto_trace=True):
    times = time_search_only(g, 0, n - 1, repeats=3, mode="fused")
out["traced_wall_s"] = time.perf_counter() - t0
out["median_solve_s"] = float(np.median(times))
pf = sorted(glob.glob(d + "/**/perfetto_trace.json.gz", recursive=True))
if not pf:
    out["error"] = "no perfetto trace written"
elif not out["hops_ok"]:
    out["error"] = "hop parity FAILED"
else:
    ev = json.loads(gzip.open(pf[-1]).read())
    evs = ev["traceEvents"] if isinstance(ev, dict) else ev
    pname = {{}}
    for e in evs:
        if (isinstance(e, dict) and e.get("ph") == "M"
                and e.get("name") == "process_name"):
            pname[e.get("pid")] = e.get("args", {{}}).get("name", "?")
    per_proc = collections.Counter()
    per_op = collections.Counter()
    for e in evs:
        if isinstance(e, dict) and e.get("ph") == "X":
            p = pname.get(e.get("pid"), str(e.get("pid")))
            per_proc[p] += e.get("dur", 0)
            per_op[e.get("name", "?")] += e.get("dur", 0)
    out["per_process_us"] = {{k: round(v, 1) for k, v
                             in per_proc.most_common(8)}}
    out["top_ops_us"] = {{k: round(v, 1) for k, v
                         in per_op.most_common(15)}}
    out["trace_dir"] = d
    if out["platform"] != "cpu":
        # only a real device decomposition may become the committed
        # artifact — a CPU smoke run must never clobber chip data
        with open(os.path.join({repo!r}, "PROFILE_FUSED.json"), "w") as f:
            json.dump(out, f, indent=1)
print("RESULT " + json.dumps(out))
"""

LEVELS_SUB = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax, jax.numpy as jnp
from functools import partial
out = dict(item="levels", platform=jax.devices()[0].platform)
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.ops.expand import expand_pull_dual_tiered
from bibfs_tpu.ops.pallas_expand import (
    pallas_available, pallas_pull_level_dual, prepare_pallas_tables,
)
from bibfs_tpu.solvers.dense import INF32, DeviceGraph

# fixed-trip loop of the real dual-pull level body: wall(T) = dispatch +
# T * level_cost. Two trip counts give both terms without a profiler; the
# same protocol runs the XLA level and the compiled Pallas level, so
# their per-level device costs are directly comparable (VERDICT r2 #1:
# "at least matching sync/ell, with the level time measured").
n = 100_000
edges = gnp_random_graph(n, 2.2 / n, seed=1)
g = DeviceGraph.build(n, edges)

@partial(jax.jit, static_argnames=("trips", "use_pallas"))
def run(nbr, deg, tables, trips, use_pallas):
    n_pad = nbr.shape[0]
    fr = jnp.zeros(n_pad, jnp.bool_).at[0].set(True)
    st = (fr, fr, jnp.full(n_pad, -1, jnp.int32),
          jnp.where(fr, 0, INF32).astype(jnp.int32),
          jnp.full(n_pad, -1, jnp.int32),
          jnp.where(fr, 0, INF32).astype(jnp.int32))
    def body(i, st):
        fs, ft, ps, ds, pt, dt = st
        if use_pallas:
            nf_s, ps, ds, _m1, nf_t, pt, dt, _m2 = pallas_pull_level_dual(
                fs, ft, ps, ds, pt, dt, tables, deg, (), i + 1, i + 1,
                inf=INF32)
        else:
            nf_s, ps, ds, _m1, nf_t, pt, dt, _m2 = expand_pull_dual_tiered(
                fs, ft, ps, ds, pt, dt, nbr, deg, (), i + 1, i + 1,
                inf=INF32)
        return (nf_s, nf_t, ps, ds, pt, dt)
    st = jax.lax.fori_loop(0, trips, body, st)
    return st[2].sum() + st[4].sum()

variants = [("xla", False)]
if pallas_available():
    variants.append(("pallas", True))
out["pallas_compiles"] = len(variants) == 2
# built ONCE, outside the timed region (its own contract), so the pallas
# variant's dispatch_s stays comparable to xla's
tables = jax.jit(prepare_pallas_tables)(g.nbr, g.deg)
# per-level HBM traffic models: the XLA/pallas pull reads the table once
# plus ~13 B/vertex of state; the fused v2 level additionally writes and
# re-reads the gathered vals block (one table-sized intermediate)
bytes_per_level = g.n_pad * g.width * 4 + g.n_pad * 13
bytes_per_level_fused = 3 * g.n_pad * g.width * 4 + g.n_pad * 13


def decompose(walls, bpl):
    per_level = (walls[64] - walls[4]) / 60.0
    return dict(
        wall_T4_s=walls[4], wall_T64_s=walls[64],
        device_level_s=per_level,
        dispatch_s=walls[4] - 4 * per_level,
        hbm_gbps_per_level=(
            bpl / per_level / 1e9 if per_level > 0 else None),
    )


def protocol(fn, bpl=bytes_per_level):
    walls = {{}}
    for trips in (4, 64):
        vals = []
        for rep in range(6):
            t0 = time.perf_counter()
            fn(trips)  # must force a value read
            vals.append(time.perf_counter() - t0)
        walls[trips] = float(np.median(vals[1:]))
    return decompose(walls, bpl)


for name, use_pallas in variants:
    out[name] = protocol(
        lambda trips: int(run(g.nbr, g.deg, tables, trips, use_pallas)))

# the round-4 whole-level kernel (v2: XLA dual gather + ONE kernel):
# the same fixed-trip protocol over the fused state — the per-level
# DELTA vs xla/pallas is the measured answer to VERDICT r3 item 2
from bibfs_tpu.ops.pallas_fused import (
    INF32, dual_seed, fused_available, fused_dual_level, key_stride,
    prepare_fused_tables,
)
out["fused_compiles"] = fused_available(g.n_pad, g.width)
if out["fused_compiles"]:
    ftables = jax.jit(prepare_fused_tables)(g.nbr, g.deg)
    n_rows_p = ftables[0].shape[1]
    ks = key_stride(g.n_pad)

    @partial(jax.jit, static_argnames=("trips",))
    def run_fused(tabs, trips):
        nbr_t, deg2 = tabs
        dual = dual_seed(jnp.int32(0), jnp.int32(1), n_rows_p)
        dist = jnp.full((1, n_rows_p), INF32, jnp.int32).at[0, 0].set(0)
        par = jnp.full((1, n_rows_p), -1, jnp.int32)
        st = (dual, dist, dist, par, par)
        def body(i, st):
            outs = fused_dual_level(
                st[0], nbr_t, deg2, st[1], st[2], st[3], st[4],
                i + 1, i + 1, ks=ks)
            return outs[:5]
        st = jax.lax.fori_loop(0, trips, body, st)
        return st[1].sum() + st[2].sum()

    out["fused"] = protocol(
        lambda trips: int(run_fused(ftables, trips)),
        bpl=bytes_per_level_fused)
print("RESULT " + json.dumps(out))
"""

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from ab_fusion import (  # noqa: E402
    FUSION_ITEM_TEMPLATE,
    _git_sha,
    run_result_subprocess,
)

ITEMS = {
    "pallas": (PALLAS_SUB, 900),
    "mesh1": (MESH1_SUB, 900),
    "batch": (BATCH_SUB, 2100),
    "batch_minor": (BATCH_MINOR_SUB, 1500),
    # resumable per-leg driver, not a template (see run_batch_rmat)
    "batch_rmat": (None, None),
    "levels": (LEVELS_SUB, 900),
    # 8 configs x 6 repeats + up to 8 compiles of the same while program
    "unroll": (UNROLL_SUB, 1800),
    # tiny graph, but the refill's int32 re-solve runs ~200 rounds and
    # the serial oracle loop is host-side python over 64 solves
    "deepcap": (DEEPCAP_SUB, 900),
    # one warm-up compile + three traced solves + trace parse
    "profile": (PROFILE_SUB, 1500),
    # the round-3 dual-fusion A/B (sync vs sync_unfused) on the chip,
    # where the per-level fixed cost the fusion targets actually lives
    "fusion": (FUSION_ITEM_TEMPLATE, 1200),
}


def run_item(name: str) -> dict:
    if name == "batch_rmat":
        out = run_batch_rmat()
        out["git"] = _git_sha()
        return out
    code, timeout = ITEMS[name]
    # the shared bounded-subprocess/RESULT protocol lives in ab_fusion
    return run_result_subprocess(name, code.format(repo=REPO), timeout)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", nargs="+", default=list(ITEMS),
                    choices=list(ITEMS))
    args = ap.parse_args(argv)
    rc = 0
    for name in args.items:
        out = run_item(name)
        out["recorded"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        with open(OUT, "a") as f:
            f.write(json.dumps(out) + "\n")
        print(json.dumps(out), flush=True)
        if "error" in out:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
