"""1D vs 2D sharding A/B at high device counts (VERDICT r4 next #7).

Round 4 shipped the 2D block partition with a pod-scale rationale and a
wire-bytes model (sharded2d.frontier_exchange_bytes_2d: O(n/C + n/R)
per level vs the 1D owner-computes O(n) all_gather) but no measured
regime where 2D actually wins — it lost at every size on <= 8 devices.
This script runs the head-to-head the verdict asks for: scale the
device count (8 -> 32) and the graph (2^18 -> 2^20 vertices, avg deg 8
so the frontier exchange is a meaningful fraction of level work) on the
virtual CPU mesh, same graph and endpoints per cell, hop-parity-gated
against the serial oracle. Writes AB_2D.json at the repo root with the
timing matrix AND the wire-bytes model per cell, so the conclusion
(win regime found / formally demoted to pod-scale with the math) is a
committed measurement either way.

Each cell runs in its own bounded subprocess: a 32-virtual-device
XLA client cannot change device count mid-process, and one wedged cell
must not take the sweep down.

Usage: python scripts/ab_2d.py [--scales 18 20] [--devices 8 32]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ab_fusion import run_result_subprocess  # noqa: E402

CELL = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import force_cpu
force_cpu({devices})
import jax
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.parallel.collectives import frontier_exchange_bytes
from bibfs_tpu.parallel.mesh import make_1d_mesh, make_2d_mesh
from bibfs_tpu.solvers.serial import solve_serial
from bibfs_tpu.solvers.sharded import ShardedGraph, time_search
from bibfs_tpu.solvers.sharded2d import (
    Sharded2DGraph, frontier_exchange_bytes_2d, time_search_2d,
)

n = 1 << {scale}
deg = 8.0
edges = gnp_random_graph(n, deg / n, seed=7)
want = solve_serial(n, edges, 0, n - 1)
out = dict(item="ab2d_cell", n=n, scale={scale}, devices={devices},
           m=int(len(edges)), oracle_hops=want.hops,
           oracle_found=bool(want.found))

g1 = ShardedGraph.build(n, edges, make_1d_mesh({devices}))
t1, r1 = time_search(g1, 0, n - 1, repeats={repeats}, mode="sync")
out["oneD_median_s"] = float(np.median(t1))
out["oneD_hops_ok"] = bool((r1.found == want.found)
                           and (not want.found or r1.hops == want.hops))
out["oneD_wire_bytes_per_level"] = frontier_exchange_bytes(g1.n_pad)

R, C = {rc}
g2 = Sharded2DGraph.build(n, edges, make_2d_mesh(R, C))
t2, r2 = time_search_2d(g2, 0, n - 1, repeats={repeats}, mode="sync")
out["twoD_median_s"] = float(np.median(t2))
out["twoD_hops_ok"] = bool((r2.found == want.found)
                           and (not want.found or r2.hops == want.hops))
out["twoD_grid"] = [R, C]
out["twoD_wire_bytes_per_level"] = frontier_exchange_bytes_2d(
    g2.n_pad, R, C)
out["speedup_2d_over_1d"] = out["oneD_median_s"] / out["twoD_median_s"]
if not (out["oneD_hops_ok"] and out["twoD_hops_ok"]):
    out["error"] = "hop parity FAILED"
print("RESULT " + json.dumps(out))
"""


def grid_of(devices: int) -> tuple[int, int]:
    """Squarest R x C factorization, R <= C."""
    r = int(devices ** 0.5)
    while devices % r:
        r -= 1
    return r, devices // r


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scales", type=int, nargs="+", default=[18, 20])
    ap.add_argument("--devices", type=int, nargs="+", default=[8, 32])
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--timeout", type=int, default=2400)
    ap.add_argument("--out", default=os.path.join(REPO, "AB_2D.json"))
    args = ap.parse_args(argv)

    cells = []
    for scale in args.scales:
        for devices in args.devices:
            name = f"ab2d_s{scale}_d{devices}"
            code = CELL.format(
                repo=REPO, scale=scale, devices=devices,
                rc=grid_of(devices), repeats=args.repeats,
            )
            rec = run_result_subprocess(name, code, args.timeout)
            rec["recorded"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            print(json.dumps(rec), flush=True)
            cells.append(rec)

    wins = [c for c in cells
            if c.get("speedup_2d_over_1d", 0) > 1.0 and "error" not in c]
    result = dict(
        cells=cells,
        win_cells=[f"s{c['scale']}_d{c['devices']}" for c in wins],
        conclusion=(
            "2D wins at the listed cells" if wins else
            "no 2D win on the shared-memory virtual mesh even at 32 "
            "devices: collective traffic is ~free there, so the O(n) vs "
            "O(n/C+n/R) wire advantage cannot show; 2D remains a "
            "pod-scale capability justified by the wire-bytes model only"
        ),
    )
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, args.out)
    print(f"wrote {args.out}: {result['conclusion']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
