"""Round-long tunnel watcher: fire the TPU measurements the moment the
chip answers.

The round-3 postmortem was unambiguous: every on-chip item was scripted
and ready, but the tunnel was down whenever someone happened to try it by
hand, so the round produced zero real-TPU evidence (VERDICT.md r3 missing
#1). This watcher closes that loop structurally. It runs for the whole
round as a detached background process:

1. probe the tunneled accelerator in a bounded subprocess, reusing
   ``bench.py``'s probe helpers (the read-a-value contract — on the lazy
   tunneled runtime only a readback proves dispatch works);
2. the moment a probe succeeds, run the measurement steps in priority
   order, each in its own bounded subprocess:

   - ``session``  — ``scripts/tpu_session.py --items pallas mesh1 batch
     levels`` → ``TPU_SESSION.jsonl`` (compile truth for the Mosaic
     kernel, 1-device-mesh collectives, batch win regime, per-level
     dispatch/device decomposition);
   - ``bench``    — root ``bench.py`` → refreshed ``bench_last_tpu.json``
     and headline vs the reference baseline;
   - ``scale24`` / ``scale25`` — ``scripts/run_scale.py`` dense rows at
     16.8M/33.5M vertices, replacing round 2's ``ok=False``
     ``tpu-single-chip-exceeded`` row;

3. a step "done" is judged by its ARTIFACT, not its exit code: every
   measurement script here degrades to the CPU platform rather than
   crash when the tunnel drops mid-run (that is their own documented
   contract), so rc==0 proves nothing about on-chip evidence. The
   session items must have a clean non-cpu record in
   ``TPU_SESSION.jsonl``, bench must have refreshed
   ``bench_last_tpu.json``, and the scale steps must have an ok dense
   row at their scale on a non-cpu platform in ``SCALE_RESULTS.csv``;
4. a step that fails while the tunnel is still up counts toward its
   deterministic-attempt cap; a step that fails and the immediate
   re-probe finds the tunnel dead is refunded (it died of the drop, not
   of its own bug) and retried on the next tunnel-up, bounded by a
   separate transient cap so a crash that takes the tunnel down with it
   cannot spin forever. The four session items are separate steps, so
   one deterministically-failing item cannot force re-measuring the
   other three.

State lives in ``TPU_WATCH_STATUS.json`` at the repo root (gitignored —
it churns every probe tick; the builder snapshots it with ``git add -f``
once at round end as evidence either way); the chatty log goes to
``/tmp/tpu_watch.log``. The watcher never touches git — the builder
commits artifacts when they appear.

Usage: python scripts/tpu_watch.py [--max-hours 11] [--poll-s 120]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench as _bench  # probe contract lives in one place: bench.py
from ab_fusion import cache_env  # one cache-env definition for both harnesses

STATUS = os.path.join(REPO, "TPU_WATCH_STATUS.json")
LOG = "/tmp/tpu_watch.log"

PY = sys.executable

# refunded (tunnel-drop) failures per step before giving up anyway — a
# step whose crash reliably wedges the tunnel must not retry forever
TRANSIENT_CAP = 8

# the watcher's own start: "bench refreshed" means refreshed during THIS
# watcher's life, so a stale round-2 bench_last_tpu.json cannot satisfy it
WATCH_START = time.time()


def session_item_ok(item: str) -> str | None:
    """A clean, non-cpu TPU_SESSION.jsonl record for ``item`` (any time —
    an item measured on-chip earlier in the round stays measured)."""
    path = os.path.join(REPO, "TPU_SESSION.jsonl")
    try:
        lines = open(path).read().splitlines()
    except OSError:
        return "no TPU_SESSION.jsonl yet"
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("item") != item or "error" in rec:
            continue
        if rec.get("platform") in (None, "", "cpu"):
            continue
        return None
    return f"no clean non-cpu '{item}' record in TPU_SESSION.jsonl"


def bench_ok() -> str | None:
    p = os.path.join(REPO, "bench_last_tpu.json")
    try:
        if os.path.getmtime(p) < WATCH_START:
            return "bench_last_tpu.json not refreshed (degraded/CPU run?)"
        with open(p) as f:
            line = json.load(f).get("line", {})
    except (OSError, ValueError) as e:
        return f"bench_last_tpu.json unreadable: {e}"
    # bench persists the file whenever its PROBE saw the accelerator,
    # even if the tunnel then dropped and every device config failed —
    # require an actual device measurement in the artifact. platform and
    # device_best_s live under the line's ``detail`` dict (bench.emit)
    det = line.get("detail") or {}
    if det.get("platform") in (None, "", "cpu"):
        return "bench artifact has cpu platform"
    if not isinstance(det.get("device_best_s"), (int, float)):
        return "bench artifact has no device measurement (all configs failed?)"
    return None


def scale_ok(scale: int) -> str | None:
    import csv

    try:
        with open(os.path.join(REPO, "SCALE_RESULTS.csv")) as f:
            rows = list(csv.DictReader(f))
    except OSError:
        return "no SCALE_RESULTS.csv"
    for r in rows:
        if (r.get("scale") == str(scale)
                and (r.get("config") or "").startswith("dense")
                and (r.get("ok") or "").lower() in ("true", "1")
                and r.get("platform") not in (None, "", "cpu")):
            return None
    return f"no ok dense non-cpu row at scale {scale} in SCALE_RESULTS.csv"


def suite_ok() -> str | None:
    """benchmark_results.csv regenerated during THIS watch with at least
    one real device-platform row (the platform column is the round-5
    schema; its absence means a stale pre-column file)."""
    import csv

    p = os.path.join(REPO, "benchmark_results.csv")
    try:
        if os.path.getmtime(p) < WATCH_START:
            return "benchmark_results.csv not refreshed this watch"
        with open(p) as f:
            rows = list(csv.DictReader(f))
    except OSError as e:
        return f"benchmark_results.csv unreadable: {e}"
    # a failed device row still carries its platform stamp (provenance
    # is recorded for failures too) — "done" needs a row that actually
    # MEASURED something on the device, like the bench/scale gates
    if not any(r.get("platform") not in (None, "", "host", "cpu", "?")
               and (r.get("ok") or "").lower() in ("true", "1", "yes")
               and r.get("time_sec")
               for r in rows):
        return "no successful device-platform row in benchmark_results.csv"
    return None


def _session_argv(item: str) -> list[str]:
    return [PY, os.path.join(REPO, "scripts", "tpu_session.py"),
            "--items", item]


def _scale_argv(scale: int) -> list[str]:
    return [PY, os.path.join(REPO, "scripts", "run_scale.py"),
            "--scales", str(scale), "--configs", "dense", "--repeats", "3",
            "--dense-timeout", "2400"]


# (name, argv, timeout_s, max_deterministic_attempts, artifact_check)
# priority order: the Mosaic compile question first, then the perf
# decomposition, the batch win regime, the mesh programs, the headline
# bench, then the scale rows
STEPS = [
    ("session_pallas", _session_argv("pallas"), 1500, 3,
     lambda: session_item_ok("pallas")),
    ("session_levels", _session_argv("levels"), 1200, 3,
     lambda: session_item_ok("levels")),
    ("session_batch", _session_argv("batch"), 2400, 3,
     lambda: session_item_ok("batch")),
    # its own step, not a leg of session_batch: a device-level failure
    # in either wedges the process's TPU context (2026-07-31 run), and
    # a separate step gives it independent budget + retry + artifact
    # the batch-MINOR layout sweep (contiguous-row expansion gather) —
    # the round-4 answer to the 26.8 ms/query vmapped asymptote, and
    # the single most valuable pending artifact: it goes FIRST among
    # the not-yet-landed steps in case the tunnel only returns briefly
    ("session_batch_minor", _session_argv("batch_minor"), 1800, 3,
     lambda: session_item_ok("batch_minor")),
    # per-leg resumable driver (tpu_session.run_batch_rmat): banks each
    # leg as it lands, so a watchdog kill only costs the in-flight leg.
    # Worst fresh case = prep + native + four device legs at the 900 s
    # per-leg bound = 5400 s; 5700 covers it with driver overhead, and
    # banking means even a kill mid-sweep converges across retries
    ("session_batch_rmat", _session_argv("batch_rmat"), 5700, 3,
     lambda: session_item_ok("batch_rmat")),
    # the round-5 multi-level-fusion A/B: does k-rounds-per-while-
    # iteration amortize the ~12 ms/level fixed residual? Right after
    # the batch items: it is this round's single-query headline question
    ("session_unroll", _session_argv("unroll"), 2100, 3,
     lambda: session_item_ok("unroll")),
    # minor8's correctness-critical depth-cap refill, driven for real
    # on the chip (VERDICT r4 weak #5) — cheap, so it rides early
    ("session_deepcap", _session_argv("deepcap"), 900, 3,
     lambda: session_item_ok("deepcap")),
    # committed profiler decomposition of the fused solve (r4 next #5)
    ("session_profile", _session_argv("profile"), 1500, 3,
     lambda: session_item_ok("profile")),
    ("session_mesh1", _session_argv("mesh1"), 1200, 3,
     lambda: session_item_ok("mesh1")),
    ("session_fusion", _session_argv("fusion"), 1500, 3,
     lambda: session_item_ok("fusion")),
    ("bench", [PY, os.path.join(REPO, "bench.py")], 2700, 3, bench_ok),
    # the reference's one published artifact, regenerated on hardware
    # with per-row platform/config stamps (VERDICT r4 weak #6 / next #6)
    ("suite", [PY, os.path.join(REPO, "scripts", "run_suite.py")], 3600, 2,
     suite_ok),
    # watchdog must cover RMAT gen + CSR + serial oracle (~20-25 min at
    # scale 25) ON TOP of the --dense-timeout 2400 the script is given
    ("scale24", _scale_argv(24), 5400, 2, lambda: scale_ok(24)),
    ("scale25", _scale_argv(25), 7200, 2, lambda: scale_ok(25)),
]


def log(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%S')} {msg}"
    with open(LOG, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


def load_status() -> dict:
    try:
        with open(STATUS) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"steps": {}, "probes": {"ok": 0, "fail": 0}}


def save_status(st: dict) -> None:
    tmp = STATUS + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1, sort_keys=True)
    os.replace(tmp, STATUS)


def probe(st: dict) -> str | None:
    """Bounded accelerator probe via bench.py's helpers. Returns the
    platform name or None; records the outcome (incl. the failure
    diagnostic) in the status file either way."""
    plat, why = _bench._finish_probe(
        _bench._start_probe(), _bench.PROBE_TIMEOUT_S
    )
    now = time.strftime("%Y-%m-%dT%H:%M:%S")
    if plat is None:
        st["probes"]["fail"] += 1
        st["last_probe"] = {"ok": False, "at": now,
                            "why": (why or "")[-300:]}
    else:
        st["probes"]["ok"] += 1
        st["last_probe"] = {"ok": True, "platform": plat, "at": now}
    save_status(st)
    return plat


def _step_rec(st: dict, name: str) -> dict:
    return st["steps"].setdefault(
        name, {"attempts": 0, "transient": 0, "done": False})


def step_pending(st: dict, name: str, cap: int, check) -> bool:
    rec = st["steps"].get(name, {})
    if rec.get("done"):
        return False
    if check() is None:
        # the artifact already exists (e.g. a previous watcher run or a
        # manual session landed it) — record and skip
        rec = _step_rec(st, name)
        rec["done"] = True
        rec["via"] = "artifact already present"
        save_status(st)
        return False
    return (rec.get("attempts", 0) < cap
            and rec.get("transient", 0) < TRANSIENT_CAP)


def run_step(name: str, argv: list[str], timeout_s: int, st: dict,
             check) -> bool:
    rec = _step_rec(st, name)
    rec["attempts"] += 1
    rec["started"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    save_status(st)
    log(f"step {name}: attempt {rec['attempts']} starting: {' '.join(argv)}")
    t0 = time.time()
    try:
        # own session: the measurement scripts spawn their own jax
        # subprocesses, and a watchdog kill must take the WHOLE group or
        # an orphaned grandchild keeps the chip busy into the next step.
        # cache_env: a retry after a mid-run tunnel drop re-uses every
        # program the aborted attempt already compiled on the chip
        p = subprocess.Popen(
            argv, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, errors="replace",
            start_new_session=True, env=cache_env(),
        )
        try:
            out, _ = p.communicate(timeout=timeout_s)
            rc = p.returncode
        except subprocess.TimeoutExpired:
            # SIGINT first: KeyboardInterrupt lets the scripts' finally
            # blocks flush partial artifacts (run_scale appends completed
            # rows to SCALE_RESULTS.csv on the way out); SIGKILL the
            # group only if that grace period expires
            try:
                os.killpg(p.pid, signal.SIGINT)
            except ProcessLookupError:
                pass
            try:
                out, _ = p.communicate(timeout=90)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                out, _ = p.communicate()
            rc = -9
            out = (out or "") + f"\n[watchdog timeout after {timeout_s}s;" \
                                " process group interrupted then killed]"
    except OSError as e:
        rc, out = -1, str(e)
    rec["elapsed_s"] = round(time.time() - t0, 1)
    rec["rc"] = rc
    rec["tail"] = (out or "")[-2000:]
    # the artifact is the truth: every step's script degrades to the CPU
    # platform (rc==0, no on-chip evidence) when the tunnel drops
    # mid-run, and conversely a nonzero rc with a clean artifact (e.g. a
    # later session item failing) is still a success for THIS step
    verify_err = check()
    rec["done"] = verify_err is None
    if verify_err is not None:
        rec["verify_error"] = verify_err
    else:
        rec.pop("verify_error", None)
    save_status(st)
    log(f"step {name}: rc={rc} artifact={'ok' if rec['done'] else verify_err}"
        f" in {rec['elapsed_s']}s")
    return rec["done"]


def refund_attempt(st: dict, name: str) -> None:
    """The step died WITH the tunnel — charge it to the drop, not the
    step's deterministic cap (bounded by TRANSIENT_CAP)."""
    rec = _step_rec(st, name)
    rec["attempts"] = max(0, rec["attempts"] - 1)
    rec["transient"] += 1
    save_status(st)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--poll-s", type=float, default=120.0)
    ap.add_argument("--skip", nargs="*", default=[],
                    help="step names to leave alone this run (e.g. a "
                         "freshness-gated step whose artifact is already "
                         "committed, after the status file was lost)")
    args = ap.parse_args(argv)

    known = {s[0] for s in STEPS}
    unknown = set(args.skip) - known
    if unknown:
        ap.error(f"--skip: unknown step(s) {sorted(unknown)}; "
                 f"known: {sorted(known)}")
    steps = [s for s in STEPS if s[0] not in set(args.skip)]
    deadline = time.time() + args.max_hours * 3600
    st = load_status()
    log(f"watcher up: pid={os.getpid()} deadline in {args.max_hours}h"
        + (f" skip={sorted(set(args.skip))}" if args.skip else ""))
    while time.time() < deadline:
        pending = [s for s in steps if step_pending(st, s[0], s[3], s[4])]
        if not pending:
            log("all steps done (or attempt-capped); watcher exiting")
            break
        plat = probe(st)
        if plat is None:
            log(f"probe: tunnel down ({st['probes']['fail']} fails so far)")
            time.sleep(args.poll_s)
            continue
        log(f"probe: tunnel UP ({plat}); running {len(pending)} steps")
        dropped = False
        for idx, (name, step_argv, timeout_s, _cap, check) in enumerate(
                pending):
            # never let a step's watchdog carry the watcher much past the
            # deadline: cap the timeout by the time remaining, and don't
            # bother starting a step with <5 min left
            remaining = deadline - time.time()
            if remaining < 300:
                break
            ok = run_step(name, step_argv,
                          min(timeout_s, int(remaining) + 60), st, check)
            last = idx == len(pending) - 1
            if ok:
                # cheap-ish re-probe between steps only (never after the
                # last): a dead tunnel must not burn hours of watchdogs
                if not last and probe(st) is None:
                    log("tunnel dropped mid-pass; back to polling")
                    dropped = True
                    break
                continue
            # failed step: one probe both classifies the failure
            # (transient drop vs deterministic crash) and serves as the
            # between-step check
            if probe(st) is None:
                refund_attempt(st, name)
                log(f"step {name}: failure coincides with tunnel drop; "
                    "attempt refunded, back to polling")
                dropped = True
                break
            log(f"step {name}: failed with tunnel still up "
                "(deterministic attempt recorded)")
        if dropped:
            time.sleep(args.poll_s)
    log("watcher done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
