"""A/B the round-3 dual-fusion claims (VERDICT r3 item 4).

Round 3 fused the lock-step round on every substrate — ONE dual-packed
exchange + ONE table read per round instead of two single-side ones —
justified by a latency model ("half the collectives => half the
latency-bound level cost") that no artifact ever measured. This script
measures it, via the ``sync_unfused`` A/B control mode (the same
schedule with the pre-fusion structure):

- ``dense`` leg: fixed-trip fori_loop of the real while-body at two trip
  counts (the tpu_session ``levels`` protocol) on the ambient platform —
  the slope is the pure per-level cost, fused vs unfused. On the
  tunneled chip this also separates the dispatch intercept.
- ``sharded`` leg: whole-solve forced-execution walls on the 8-device
  virtual CPU mesh (the single_machine_bench.sh fake-cluster
  methodology), fused vs unfused, divided by the level count. The ICI
  regime the fusion targets needs a real multi-chip mesh; the CPU mesh
  measures the op/collective-count effect only.

Appends one JSON line per leg to stdout; paste the table into
PERF_NOTES.md.

Usage: python scripts/ab_fusion.py [--legs dense sharded] [--n 100000]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DENSE_LEG = """
import json, sys, time
from functools import partial
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax, jax.numpy as jnp
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.solvers.dense import (
    DeviceGraph, _init_state, _make_body, solve_dense_graph,
)

n = {n}
edges = gnp_random_graph(n, 2.2 / n, seed=1)
g = DeviceGraph.build(n, edges)
out = dict(item="fusion", leg="dense", n=n,
           platform=jax.devices()[0].platform)

# hop parity first: the control mode must be the same algorithm — and the
# pair must actually CONNECT, or the per-round slope below measures a
# degenerate 2-level search (None == None would pass silently)
r_f = solve_dense_graph(g, 0, n - 1, mode="sync")
r_u = solve_dense_graph(g, 0, n - 1, mode="sync_unfused")
assert r_f.found and r_u.found, "disconnected A/B pair; pick another seed/n"
assert r_f.hops == r_u.hops and r_f.levels == r_u.levels, (r_f, r_u)
out["hops"] = r_f.hops

@partial(jax.jit, static_argnames=("mode", "trips"))
def run(nbr, deg, mode, trips):
    st = _init_state(nbr.shape[0], 1, jnp.int32(0), jnp.int32(n - 1), deg)
    body = _make_body(mode, 0, (), nbr, deg, ())
    st = jax.lax.fori_loop(0, trips, lambda i, s: body(s), st)
    return st["dist_s"].sum() + st["dist_t"].sum()

for mode in ("sync", "sync_unfused"):
    walls = dict()
    for trips in (4, 32):
        vals = []
        for rep in range(6):
            t0 = time.perf_counter()
            v = int(run(g.nbr, g.deg, mode, trips))  # forced readback
            vals.append(time.perf_counter() - t0)
        walls[trips] = float(np.median(vals[1:]))
    per_round = (walls[32] - walls[4]) / 28.0
    out[mode] = dict(
        wall_T4_s=walls[4], wall_T32_s=walls[32],
        device_round_s=per_round, dispatch_s=walls[4] - 4 * per_round,
    )
f, u = out["sync"]["device_round_s"], out["sync_unfused"]["device_round_s"]
out["fused_speedup_per_round"] = (u / f) if f > 0 else None
print("RESULT " + json.dumps(out))
"""

SHARDED_LEG = """
import json, sys
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import force_cpu
force_cpu(8)
import jax
from bibfs_tpu.graph.generate import gnp_random_graph
from bibfs_tpu.parallel.mesh import make_1d_mesh
from bibfs_tpu.solvers.serial import solve_serial
from bibfs_tpu.solvers.sharded import ShardedGraph, time_search

n = {n}
edges = gnp_random_graph(n, 2.2 / n, seed=1)
want = solve_serial(n, edges, 0, n - 1)
assert want.found, "disconnected A/B pair; pick another seed/n"
g = ShardedGraph.build(n, edges, make_1d_mesh(8))
out = dict(leg="sharded", n=n, ndev=8, platform=jax.devices()[0].platform)
for mode in ("sync", "sync_unfused"):
    times, res = time_search(g, 0, n - 1, repeats={repeats}, mode=mode)
    assert res.hops == want.hops, (mode, res.hops, want.hops)
    med = float(np.median(times))
    out[mode] = dict(wall_s=med, levels=res.levels,
                     per_level_s=med / max(res.levels, 1))
out["hops"] = want.hops
f = out["sync"]["per_level_s"]
u = out["sync_unfused"]["per_level_s"]
out["fused_speedup_per_level"] = (u / f) if f > 0 else None
print("RESULT " + json.dumps(out))
"""


# tpu_session.py embeds DENSE_LEG as its 'fusion' item via this template
# (the ONLY placeholder left after substituting n must be {repo!r} —
# run_item formats with repo alone)
FUSION_ITEM_TEMPLATE = DENSE_LEG.replace("{n}", "100000")


def cache_env() -> dict:
    """Measurement-subprocess environment with the persistent JAX
    compilation cache enabled: a retried item (or watcher step) re-uses
    every program a previous — possibly aborted — attempt already
    compiled on the chip instead of re-paying 20-40 s per program.
    setdefault semantics: an operator's own cache configuration wins.
    THE one definition — `tpu_watch.run_step` imports it, so the two
    harnesses can never write to different caches."""
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/bibfs_jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    return env


def run_result_subprocess(name: str, code: str, timeout: int) -> dict:
    """THE bounded measurement-subprocess protocol, shared with
    tpu_session.run_item: run ``python -c code``, scan stdout for the
    one ``RESULT <json>`` line, stamp ``elapsed_s``, and turn timeouts /
    missing results into an ``error`` record instead of an exception."""
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, env=cache_env(),
        )
        for line in r.stdout.splitlines():
            if line.startswith("RESULT "):
                out = json.loads(line[len("RESULT "):])
                out["elapsed_s"] = round(time.time() - t0, 1)
                out["git"] = _git_sha()
                return out
        err = (r.stdout + r.stderr).strip()[-800:] or "no RESULT line"
    except subprocess.TimeoutExpired:
        err = f"timeout after {timeout}s"
    return dict(
        item=name, error=err, elapsed_s=round(time.time() - t0, 1),
        git=_git_sha(),
    )


def _git_sha() -> str | None:
    """Provenance stamp: which code produced a measurement artifact."""
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--legs", nargs="+", default=["dense", "sharded"],
                    choices=["dense", "sharded"])
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--repeats", type=int, default=7)
    args = ap.parse_args(argv)
    rc = 0
    for leg in args.legs:
        if leg == "dense":
            code = DENSE_LEG.format(repo=REPO, n=args.n)
        else:
            code = SHARDED_LEG.format(
                repo=REPO, n=args.n, repeats=args.repeats
            )
        out = run_result_subprocess(leg, code, timeout=1800)
        print(json.dumps(out), flush=True)
        if "error" in out:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
