"""Scale demonstration: RMAT graphs at >=1M nodes, end to end.

The reference never ran beyond 100k nodes — its own limitation note names
the 10M-node graph as the thing that would vindicate parallelism
(/root/reference/README.md:19, full-graph replication on every rank,
SURVEY.md quirk Q6). This script produces the committed evidence that this
framework operates in that regime:

  python scripts/run_scale.py --scales 20          # 1M vertices
  python scripts/run_scale.py --scales 20 23       # + 8.4M vertices

Per scale it generates a Graph500-style RMAT graph (fixed seed), finds a
deep reachable (src, dst) pair with a host BFS, solves with the serial
oracle, then times:

- ``dense``/tiered on the ambient platform (the real TPU chip when run
  under the tunneled backend, else host CPU) — single-device HBM residency;
- ``sharded``/tiered on an 8-device virtual CPU mesh in a subprocess
  (the fake-cluster methodology of the reference's single_machine_bench.sh)
  — proves the 1D vertex-partitioned multi-chip program compiles and agrees
  at this size; its wall-clock is an emulation artifact, not a TPU number.

Rows append to SCALE_RESULTS.csv: wall-clock (median of repeats, search
only), TEPS, hop parity vs the oracle, and peak host RSS.

``--configs`` reruns a subset (e.g. ``--configs dense``) without paying
for the others — the serial oracle still runs (it is the parity gate for
every row) but only emits its own row when selected.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import resource
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CSV_PATH = os.path.join(REPO, "SCALE_RESULTS.csv")
FIELDS = [
    "config",
    "scale",
    "n",
    "m",
    "platform",
    "time_sec",
    "teps",
    "hops",
    "levels",
    "ok",
    "peak_rss_mb",
]
ALL_CONFIGS = ("serial", "native", "dense", "sharded", "sharded2d")


def peak_rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _row(config, scale, n, m, platform, **kw):
    base = dict(
        config=config, scale=scale, n=n, m=m, platform=platform,
        time_sec=None, teps=None, hops=None, levels=None, ok=False,
        peak_rss_mb=None,
    )
    base.update(kw)
    return base


def farthest_reachable(n: int, row_ptr, col_ind, src: int) -> tuple[int, int]:
    """Host BFS from src; returns (vertex at max distance, that distance).
    RMAT graphs leave many vertices isolated, so dst must be picked from
    the giant component rather than the reference's n-1 convention."""
    dist = np.full(n, -1, dtype=np.int64)
    dist[src] = 0
    frontier = np.array([src], dtype=np.int64)
    d = 0
    while frontier.size:
        starts = row_ptr[frontier]
        ends = row_ptr[frontier + 1]
        counts = ends - starts
        idx = np.repeat(starts, counts) + (
            np.arange(counts.sum()) - np.repeat(np.cumsum(counts) - counts, counts)
        )
        nxt = np.unique(col_ind[idx])
        nxt = nxt[dist[nxt] == -1]
        d += 1
        dist[nxt] = d
        frontier = nxt
    far = int(np.argmax(dist))
    return far, int(dist[far])


DENSE_SUB = """
import json, resource, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
from bibfs_tpu.graph.io import read_graph_bin
from bibfs_tpu.solvers.dense import DeviceGraph, solve_dense_graph, time_search_only
n, edges = read_graph_bin({bin_path!r})
g = DeviceGraph.build(n, edges, layout="tiered")
if {chunked}:
    # chunked execution (solvers/checkpoint.py, no snapshot path): bounds
    # live HBM to ONE donated copy of the vertex state per dispatch — the
    # whole-search while_loop program exceeded single-chip HBM at scale 24.
    # Each chunk's termination-scalar read forces execution, so the wall
    # timing protocol is the same forced-execution one as time_search_only.
    from bibfs_tpu.solvers.checkpoint import solve_checkpointed
    # untimed warm-up: jit compile of the chunk kernel must not leak into
    # the timed repeats (the non-chunked branch excludes compile via
    # time_search_only's warm-up; this keeps the rows comparable)
    solve_checkpointed(g, {src}, {dst}, chunk=4)
    times = []
    res = None
    for _ in range({repeats}):
        t0 = time.perf_counter()
        res = solve_checkpointed(g, {src}, {dst}, chunk=4)
        times.append(time.perf_counter() - t0)
else:
    # forced-execution timing (solvers/timing.py); a fresh subprocess per
    # scale keeps compile caches and runtime mode isolated between scales
    times = time_search_only(g, {src}, {dst}, repeats={repeats}, mode="sync")
    res = solve_dense_graph(g, {src}, {dst}, mode="sync")
print(json.dumps(dict(
    time_sec=float(np.median(times)), hops=res.hops, levels=res.levels,
    edges_scanned=res.edges_scanned, platform=jax.devices()[0].platform,
    peak_rss_mb=resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
)))
"""

SHARDED2D_SUB = """
import json, resource, sys
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import force_cpu
force_cpu(8)
from bibfs_tpu.graph.io import read_graph_bin
from bibfs_tpu.solvers.sharded2d import Sharded2DGraph, time_search_2d
n, edges = read_graph_bin({bin_path!r})
g = Sharded2DGraph.build(n, edges, num_devices=8)
times, res = time_search_2d(g, {src}, {dst}, repeats={repeats}, mode="sync")
print(json.dumps(dict(
    time_sec=float(np.median(times)), hops=res.hops, levels=res.levels,
    edges_scanned=res.edges_scanned,
    peak_rss_mb=resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
)))
"""

SHARDED_SUB = """
import json, resource, sys
import numpy as np
sys.path.insert(0, {repo!r})
from bibfs_tpu.utils.platform import force_cpu
force_cpu(8)
from bibfs_tpu.graph.io import read_graph_bin
from bibfs_tpu.parallel.mesh import make_1d_mesh
from bibfs_tpu.solvers.sharded import ShardedGraph, time_search
n, edges = read_graph_bin({bin_path!r})
g = ShardedGraph.build(n, edges, make_1d_mesh(8), layout="tiered")
times, res = time_search(g, {src}, {dst}, repeats={repeats}, mode="sync")
print(json.dumps(dict(
    time_sec=float(np.median(times)), hops=res.hops, levels=res.levels,
    edges_scanned=res.edges_scanned,
    peak_rss_mb=resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
)))
"""


def _run_sub(code: str, timeout: int) -> dict:
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if r.returncode != 0:
        raise RuntimeError(f"subprocess failed: {r.stderr[-500:]}")
    return json.loads(r.stdout.splitlines()[-1])


def _bench_native(scale, n, edges, src, dst, oracle, repeats, out_rows):
    ng = None
    try:
        from bibfs_tpu.solvers.native import NativeGraph, solve_native_graph

        ng = NativeGraph.build(n, edges)
        solve_native_graph(ng, src, dst)  # warm (first touch of scratch)
        nat_times = []
        nat = None
        for _ in range(max(repeats, 3)):
            t0n = time.perf_counter()
            nat = solve_native_graph(ng, src, dst)
            nat_times.append(time.perf_counter() - t0n)
        t_nat = float(np.median(nat_times))
        ok = nat.hops == oracle.hops
        out_rows.append(
            _row(
                "native", scale, n, len(edges), "host-c++",
                time_sec=t_nat,
                teps=nat.edges_scanned / t_nat if t_nat else None,
                hops=nat.hops, levels=nat.levels, ok=ok,
                peak_rss_mb=round(peak_rss_mb(), 1),
            )
        )
        print(
            f"  native [host-c++]: {t_nat:.4f}s {'OK' if ok else 'MISMATCH'}",
            flush=True,
        )
    except Exception as e:  # gated like the device rows: record, continue
        print(f"  native FAILED: {e}", file=sys.stderr, flush=True)
        out_rows.append(_row("native", scale, n, len(edges), "host-c++"))
    finally:
        # ~1.1 GB of CSR + scratch at scale 23 must not stay resident
        # while the dense/sharded subprocess benches run
        del ng


def _bench_dense(scale, n, edges, src, dst, oracle, repeats, timeout,
                 bin_path, out_rows, chunked=False):
    label = "dense/tiered-chunked" if chunked else "dense/tiered"
    try:
        info = _run_sub(
            DENSE_SUB.format(
                repo=REPO, bin_path=bin_path, src=src, dst=dst,
                repeats=repeats, chunked=chunked,
            ),
            timeout,
        )
        t_dense = info["time_sec"]
        ok = info["hops"] == oracle.hops
        out_rows.append(
            _row(
                label, scale, n, len(edges), info["platform"],
                time_sec=t_dense,
                teps=info["edges_scanned"] / t_dense if t_dense else None,
                hops=info["hops"], levels=info["levels"], ok=ok,
                peak_rss_mb=round(info["peak_rss_mb"], 1),
            )
        )
        print(
            f"  {label} [{info['platform']}]: {t_dense:.4f}s "
            f"teps={out_rows[-1]['teps']:.3e} {'OK' if ok else 'MISMATCH'}",
            flush=True,
        )
    except (subprocess.TimeoutExpired, RuntimeError, json.JSONDecodeError,
            IndexError) as e:
        print(f"  {label} FAILED: {e}", file=sys.stderr, flush=True)
        out_rows.append(_row(label, scale, n, len(edges), "?"))


def _bench_sharded2d(scale, n, edges, src, dst, oracle, repeats, timeout,
                     bin_path, out_rows):
    try:
        info = _run_sub(
            SHARDED2D_SUB.format(
                repo=REPO, bin_path=bin_path, src=src, dst=dst,
                repeats=max(2, repeats // 2),
            ),
            timeout,
        )
        ok = info["hops"] == oracle.hops
        out_rows.append(
            _row(
                "sharded2d-2x4", scale, n, len(edges), "cpu-mesh-emulated",
                time_sec=info["time_sec"],
                teps=info["edges_scanned"] / info["time_sec"],
                hops=info["hops"], levels=info["levels"], ok=ok,
                peak_rss_mb=round(info["peak_rss_mb"], 1),
            )
        )
        print(
            f"  sharded2d-2x4 [cpu-emulated]: {info['time_sec']:.4f}s "
            f"{'OK' if ok else 'MISMATCH'}",
            flush=True,
        )
    except (subprocess.TimeoutExpired, RuntimeError, json.JSONDecodeError,
            IndexError) as e:
        print(f"  sharded2d-2x4 FAILED: {e}", file=sys.stderr, flush=True)
        out_rows.append(
            _row("sharded2d-2x4", scale, n, len(edges), "cpu-mesh-emulated")
        )


def _bench_sharded(scale, n, edges, src, dst, oracle, repeats, timeout,
                   bin_path, out_rows):
    try:
        info = _run_sub(
            SHARDED_SUB.format(
                repo=REPO, bin_path=bin_path, src=src, dst=dst,
                repeats=max(2, repeats // 2),
            ),
            timeout,
        )
        ok = info["hops"] == oracle.hops
        out_rows.append(
            _row(
                "sharded8/tiered", scale, n, len(edges), "cpu-mesh-emulated",
                time_sec=info["time_sec"],
                teps=info["edges_scanned"] / info["time_sec"],
                hops=info["hops"], levels=info["levels"], ok=ok,
                peak_rss_mb=round(info["peak_rss_mb"], 1),
            )
        )
        print(
            f"  sharded8/tiered [cpu-emulated]: {info['time_sec']:.4f}s "
            f"{'OK' if ok else 'MISMATCH'}",
            flush=True,
        )
    except (subprocess.TimeoutExpired, RuntimeError, json.JSONDecodeError,
            IndexError) as e:
        print(f"  sharded8/tiered FAILED: {e}", file=sys.stderr, flush=True)
        out_rows.append(
            _row("sharded8/tiered", scale, n, len(edges), "cpu-mesh-emulated")
        )


def run_scale(
    scale: int,
    repeats: int,
    out_rows: list,
    *,
    dense_timeout: int,
    sharded_timeout: int,
    configs: tuple = ALL_CONFIGS,
    dist: str = "rmat",
    avg_deg: float = 8.0,
    dense_chunked: bool | None = None,
):
    from bibfs_tpu.graph.csr import build_csr
    from bibfs_tpu.graph.generate import gnp_random_graph, rmat_graph
    from bibfs_tpu.graph.io import write_graph_bin
    from bibfs_tpu.solvers.serial import solve_serial_csr

    t0 = time.time()
    if dist == "gnp":
        n = 1 << scale
        edges = gnp_random_graph(n, avg_deg / n, seed=7)
    else:
        n, edges = rmat_graph(scale, seed=7)
    row_ptr, col_ind = build_csr(n, edges)
    src = int(np.argmax(np.diff(row_ptr)))  # top hub: always in the giant comp.
    dst, depth = farthest_reachable(n, row_ptr, col_ind, src)
    oracle = solve_serial_csr(n, row_ptr, col_ind, src, dst)
    assert oracle.found and oracle.hops == depth
    print(
        f"scale {scale}: n={n} m={len(edges)} src={src} dst={dst} "
        f"hops={oracle.hops} (gen+oracle {time.time() - t0:.0f}s)",
        flush=True,
    )
    if "serial" in configs:
        out_rows.append(
            _row(
                "serial-oracle", scale, n, len(edges), "host",
                time_sec=oracle.time_s,
                teps=(oracle.edges_scanned / oracle.time_s
                      if oracle.time_s else None),
                hops=oracle.hops, levels=oracle.levels, ok=True,
                peak_rss_mb=round(peak_rss_mb(), 1),
            )
        )

    # native C++ runtime at scale: the framework's host latency backend is
    # not capped at toy sizes — it handles the 10M-node regime the
    # reference's README names as out of reach
    if "native" in configs:
        _bench_native(scale, n, edges, src, dst, oracle, repeats, out_rows)

    if not ({"dense", "sharded", "sharded2d"} & set(configs)):
        return
    bin_path = f"/tmp/rmat{scale}.bin"
    write_graph_bin(bin_path, n, edges)
    try:
        if "dense" in configs:
            # chunked execution by default at scale >= 24: the one-shot
            # while_loop program exceeded single-chip HBM there (round 2)
            chunked = dense_chunked if dense_chunked is not None else scale >= 24
            _bench_dense(scale, n, edges, src, dst, oracle, repeats,
                         dense_timeout, bin_path, out_rows, chunked=chunked)
        if "sharded" in configs:
            _bench_sharded(scale, n, edges, src, dst, oracle, repeats,
                           sharded_timeout, bin_path, out_rows)
        if "sharded2d" in configs:
            _bench_sharded2d(scale, n, edges, src, dst, oracle, repeats,
                             sharded_timeout, bin_path, out_rows)
    finally:
        os.unlink(bin_path)


def _append_rows(rows: list[dict]) -> None:
    exists = os.path.exists(CSV_PATH)
    with open(CSV_PATH, "a", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        if not exists:
            w.writeheader()
        w.writerows(rows)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scales", type=int, nargs="+", default=[20])
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument(
        "--configs", nargs="+", default=list(ALL_CONFIGS),
        choices=list(ALL_CONFIGS),
        help="which rows to (re)measure; the oracle always runs as the gate",
    )
    ap.add_argument(
        "--dist", default="rmat", choices=["rmat", "gnp"],
        help="graph distribution: rmat (Graph500 skew; default) or gnp "
        "(uniform G(2^scale, avg-deg/n) — the distribution the 2D block "
        "layout is sized for)",
    )
    ap.add_argument("--avg-deg", type=float, default=8.0,
                    help="average degree for --dist gnp")
    ap.add_argument(
        "--dense-timeout", type=int, default=1800,
        help="seconds allowed for the single-device (TPU) run per scale",
    )
    ap.add_argument(
        "--dense-chunked", type=int, default=None, choices=[0, 1],
        help="force the dense row through chunked execution (1) or the "
        "one-shot while_loop (0); default: chunked at scale >= 24",
    )
    ap.add_argument(
        "--sharded-timeout", type=int, default=1800,
        help="seconds allowed for the 8-device CPU-mesh emulation per scale",
    )
    args = ap.parse_args(argv)

    from bibfs_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    all_ok = True
    total = 0
    for scale in args.scales:
        # rows flush to the CSV after EVERY scale: a later scale's OOM or
        # crash must not discard completed hours of measurement
        rows: list[dict] = []
        try:
            run_scale(
                scale,
                args.repeats,
                rows,
                dense_timeout=args.dense_timeout,
                sharded_timeout=args.sharded_timeout,
                configs=tuple(args.configs),
                dist=args.dist,
                avg_deg=args.avg_deg,
                dense_chunked=(
                    None if args.dense_chunked is None
                    else bool(args.dense_chunked)
                ),
            )
        finally:
            if args.dist == "gnp":  # distribution is part of the row identity
                for r in rows:
                    r["config"] += f"@gnp-deg{args.avg_deg:g}"
            _append_rows(rows)
            total += len(rows)
        all_ok = all_ok and all(r["ok"] for r in rows)
    print(f"appended {total} rows to {CSV_PATH}")
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
