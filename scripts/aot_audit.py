"""Deviceless TPU compile audit of every kernel-bearing program.

Runs the full XLA:TPU + Mosaic pipeline (utils/tpu_aot.py; libtpu, no
chip, no tunnel) over the solver program matrix and writes one record
per program to ``AOT_AUDIT.json`` — the truthful, locally-reproducible
answer to "which of this framework's programs compile for TPU", which
rounds 2-4 could otherwise only ask through the tunnel lottery.

Dense programs compile against a single abstract v5e device; the 1D
sharded collective programs compile against an abstract 4-device v5e
2x2 mesh (collectives and shard_map included). The 2D block programs
and the tiered sharded aux pytree need constructed device graphs
(device_put — impossible deviceless) and are covered by the virtual-CPU
mesh tests plus the on-chip mesh1 session item instead; the audit
records them as "not-auditable-deviceless" rather than silently
omitting them.

Usage: python scripts/aot_audit.py [--out AOT_AUDIT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "AOT_AUDIT.json"))
    ap.add_argument("--n", type=int, default=100_000)
    args = ap.parse_args(argv)

    from bibfs_tpu.utils.platform import force_cpu

    force_cpu()

    import numpy as np
    from unittest import mock

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from bibfs_tpu.graph.csr import build_ell, build_tiered
    from bibfs_tpu.graph.generate import gnp_random_graph, rmat_graph
    from bibfs_tpu.parallel.mesh import VERTEX_AXIS
    from bibfs_tpu.utils.tpu_aot import aot_available, aot_compile_tpu, tpu_topology

    records: list[dict] = []
    t_all = time.time()

    def record(program: str, ok, err, t0):
        rec = dict(
            program=program, ok=bool(ok),
            error=(err or "")[:300] or None,
            elapsed_s=round(time.time() - t0, 1),
        )
        records.append(rec)
        print(("OK  " if ok else "FAIL"), program,
              "" if ok else f"-> {rec['error']}", flush=True)

    if not aot_available():
        record("topology", False, "TPU topology API unavailable", t_all)
    else:
        n = args.n
        edges = gnp_random_graph(n, 2.2 / n, seed=1)

        # ---- dense matrix (single abstract device) ----
        from bibfs_tpu.solvers.dense import _build_kernel, kernel_cap

        gell = build_ell(n, edges)
        nt, et = rmat_graph(14, edge_factor=8, seed=1)
        gt = build_tiered(nt, et)
        t_aux = (np.asarray(gt.hub_rank),
                 tuple((np.asarray(t.nbr),
                        np.asarray(gt.hub_ids[: t.nbr.shape[0]]))
                       for t in gt.tiers))
        tier_meta = tuple((t.start, t.count, t.nbr.shape[1]) for t in gt.tiers)
        dense_cases = [
            ("dense/sync/ell", "sync", gell, (), ()),
            ("dense/sync_unfused/ell", "sync_unfused", gell, (), ()),
            ("dense/alt/ell", "alt", gell, (), ()),
            ("dense/beamer/ell", "beamer", gell, (), ()),
            ("dense/fused/ell", "fused", gell, (), ()),
            ("dense/fused_alt/ell", "fused_alt", gell, (), ()),
            ("dense/pallas/ell", "pallas", gell, (), ()),
            ("dense/sync/tiered", "sync", gt, t_aux, tier_meta),
            ("dense/beamer/tiered", "beamer", gt, t_aux, tier_meta),
            ("dense/pallas/tiered", "pallas", gt, t_aux, tier_meta),
        ]
        for name, mode, g, aux, tm in dense_cases:
            t0 = time.time()
            fn = _build_kernel(mode, kernel_cap(mode, g.n_pad), tm)
            ok, err = aot_compile_tpu(
                fn, np.asarray(g.nbr), np.asarray(g.deg), aux,
                np.int32(0), np.int32(g.n - 1),
            )
            record(name, ok, err, t0)

        # round-5 unrolled programs (k rounds per while iteration via
        # lax.cond re-gating — dense._unrolled): the on-chip unroll A/B
        # must never be the first place these compile for TPU
        for name, mode in (("dense/fused/ell/u8", "fused"),
                           ("dense/sync/ell/u8", "sync")):
            t0 = time.time()
            fn = _build_kernel(mode, kernel_cap(mode, gell.n_pad), (), 8)
            ok, err = aot_compile_tpu(
                fn, np.asarray(gell.nbr), np.asarray(gell.deg), (),
                np.int32(0), np.int32(gell.n - 1),
            )
            record(name, ok, err, t0)

        # dense batch kernel (vmapped search, B=4)
        t0 = time.time()
        batch_fn = jax.vmap(
            _build_kernel("sync", 0, ()), in_axes=(None, None, None, 0, 0)
        )
        ok, err = aot_compile_tpu(
            batch_fn, np.asarray(gell.nbr), np.asarray(gell.deg), (),
            np.zeros(4, np.int32), np.full(4, n - 1, np.int32),
        )
        record("dense/batch4/sync/ell", ok, err, t0)

        # batch-MINOR kernels ([n_pad, B] planes, contiguous-row gather;
        # multi-chunk scan geometry so the audited programs include the
        # dynamic_slice/update plumbing the big-graph path uses). The
        # tiered case carries the lowering-riskiest new program (scatter
        # .at[].min/max inside a scan inside the while_loop). Geometry
        # comes from the EXACT shared derivation the dispatch runs
        # (incl. its fit + post-rounding key-overflow checks); imports
        # stay inside the per-program try so an import failure records
        # a FAIL row instead of aborting the whole audit
        minor_cases = [
            ("dense/batch256/minor/ell", gell, (), (), False),
            ("dense/batch256/minor8/ell", gell, (), (), True),
            ("dense/batch256/minor/tiered", gt, t_aux[1], tier_meta,
             False),
        ]
        for name_m, gm, aux_m, tm, dt8 in minor_cases:
            t0 = time.time()
            try:
                from types import SimpleNamespace

                from bibfs_tpu.solvers.batch_minor import (
                    _build_minor_kernel,
                    _minor_geometry,
                )

                gshape = SimpleNamespace(
                    n=gm.n, n_pad=gm.n_pad, width=gm.width, tier_meta=tm
                )
                n_pad2, wp, tc, b_pad = _minor_geometry(gshape, 256, dt8)
                mfn = _build_minor_kernel(
                    gm.n, n_pad2, wp, tc, b_pad, dt8, tm
                )
                ok, err = aot_compile_tpu(
                    mfn, np.asarray(gm.nbr), np.asarray(gm.deg), aux_m,
                    np.zeros(b_pad, np.int32),
                    np.full(b_pad, gm.n - 1, np.int32),
                )
            except Exception as e:
                ok, err = False, f"{type(e).__name__}: {e}"
            record(name_m, ok, err, t0)

        # checkpoint chunk kernel (chunked dense execution)
        t0 = time.time()
        try:
            from bibfs_tpu.solvers.checkpoint import _dense_chunk_kernel

            kern = _dense_chunk_kernel("sync", 0, (), 8)
            from bibfs_tpu.solvers.dense import _init_state

            def chunk_prog(nbr, deg, src, dst):
                from bibfs_tpu.solvers.checkpoint import _strip

                st = _init_state(nbr.shape[0], 1, src, dst, deg)
                return kern(nbr, deg, (), _strip(st))

            ok, err = aot_compile_tpu(
                chunk_prog, np.asarray(gell.nbr), np.asarray(gell.deg),
                np.int32(0), np.int32(n - 1),
            )
        except Exception as e:
            ok, err = False, f"{type(e).__name__}: {e}"
        record("dense/chunked/sync/ell", ok, err, t0)

        # ---- 1D sharded collective programs (abstract 4-device mesh) ----
        topo = tpu_topology()
        mesh = Mesh(np.array(topo.devices).reshape(4), (VERTEX_AXIS,))
        sh = NamedSharding(mesh, P(VERTEX_AXIS))
        rep = NamedSharding(mesh, P())
        g4 = build_ell(n, edges, pad_multiple=8 * 4)
        geom = (g4.n_pad // 4, g4.n_pad, g4.width)

        def sd(shape, sharding):
            return jax.ShapeDtypeStruct(shape, jnp.int32, sharding=sharding)

        from bibfs_tpu.solvers.sharded import _sharded_fn

        for mode in ("sync", "sync_unfused", "alt", "beamer", "fused",
                     "pallas"):
            t0 = time.time()
            cap = kernel_cap(mode, g4.n_pad)
            try:
                fn = _sharded_fn(mesh, VERTEX_AXIS, mode, cap, (), geom)
                with mock.patch.object(jax, "default_backend", lambda: "tpu"):
                    jax.jit(fn).lower(
                        sd((g4.n_pad, g4.width), sh), sd((g4.n_pad,), sh),
                        (), sd((), rep), sd((), rep),
                    ).compile()
                ok, err = True, None
            except Exception as e:
                ok, err = False, f"{type(e).__name__}: {e}"
            record(f"sharded4/{mode}/ell", ok, err, t0)

        for name in ("sharded/tiered (aux pytree needs device_put)",
                     "sharded2d (block build needs device_put)"):
            records.append(dict(program=name, ok=None,
                                error="not-auditable-deviceless; covered "
                                      "by the CPU-mesh tests + mesh1 "
                                      "session item", elapsed_s=0))

    sha = subprocess.run(
        ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
        capture_output=True, text=True,
    ).stdout.strip()
    out = dict(
        recorded=time.strftime("%Y-%m-%dT%H:%M:%S"),
        git=sha or None,
        jax=jax.__version__,
        topology="v5e:2x2 (abstract, deviceless)",
        total_s=round(time.time() - t_all, 1),
        programs=records,
    )
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    n_ok = sum(1 for r in records if r["ok"])
    n_fail = sum(1 for r in records if r["ok"] is False)
    print(f"\n{n_ok} compile, {n_fail} fail, "
          f"{len(records) - n_ok - n_fail} not auditable -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
