"""Canonical metric-name registry — the ONE list every surface derives.

Before this module, the stable metric names lived in five places with
nothing holding them together: the mint sites (``REGISTRY.counter(...)``
calls scattered across the serving stack), the README "Observability"
tables, the soak gates' family lists (``fleet.FLEET_METRIC_FAMILIES``,
``store/wal.DURABLE_METRIC_FAMILIES``, three inline tuples in
``bench.py``), and the live-endpoint CI probe
(``scripts/check_metrics_endpoint.py``). Every PR that added a family
had to update them in lockstep by hand — and the ``bibfs-lint``
``metric-mint`` rule (``bibfs_tpu/analysis/rules/metric_mint.py``) now
machine-checks exactly that lockstep:

- every name minted anywhere in ``bibfs_tpu/`` must appear here;
- every name here must be minted somewhere (no dead documentation);
- every ``bibfs_*`` string literal in the package must resolve to a
  name here (modulo the Prometheus histogram ``_bucket``/``_count``/
  ``_sum`` exposition suffixes);
- the README metric tables must list exactly these names.

This module is deliberately import-light (stdlib-free, data only):
``bench.py``, CI scripts and the lint all import it without pulling the
serving stack.

Adding a metric: mint it at component construction (so it renders at
zero — the soak gates scrape families before traffic), add it to its
group below, and add a README table row. The lint fails until all
three agree.
"""

from __future__ import annotations

#: sync/pipelined engine query accounting (serve/engine.py)
ENGINE_METRIC_FAMILIES = (
    "bibfs_queries_total",
    "bibfs_queries_routed_total",
    "bibfs_device_batches_total",
    "bibfs_cache_inserts_skipped_total",
)

#: pipelined-engine flusher/queue instrumentation (serve/pipeline.py)
PIPELINE_METRIC_FAMILIES = (
    "bibfs_flushes_total",
    "bibfs_flush_cause_total",
    "bibfs_submit_blocked_total",
    "bibfs_serve_queue_depth",
    "bibfs_serve_queue_depth_max",
    "bibfs_queue_wait_max_ms",
    "bibfs_batch_service_max_ms",
    "bibfs_query_latency_seconds",
)

#: distance/executable cache accounting (serve/cache.py, serve/buckets.py)
CACHE_METRIC_FAMILIES = (
    "bibfs_dist_cache_events_total",
    "bibfs_dist_cache_entries",
    "bibfs_exec_cache_events_total",
    "bibfs_exec_programs",
    "bibfs_exec_program_dispatches_total",
    "bibfs_exec_compiles_total",
)

#: failure-handling telemetry (serve/resilience threading + serve/faults);
#: all minted at engine construction, so the chaos gate asserts the FULL
#: group renders — not the hand-picked subset it used to
RESILIENCE_METRIC_FAMILIES = (
    "bibfs_errors_total",
    "bibfs_route_fallbacks_total",
    "bibfs_retries_total",
    "bibfs_batch_bisections_total",
    "bibfs_breaker_state",
    "bibfs_breaker_transitions_total",
    "bibfs_health_state",
    "bibfs_faults_injected_total",
)

#: versioned graph store (store/registry.py); the memory-tier trio
#: (mmap_bytes / tier / remap) is minted at store construction and
#: per-graph registration like the rest, so every group member renders
#: at zero before the first checkpoint or recovery
STORE_METRIC_FAMILIES = (
    "bibfs_store_graphs",
    "bibfs_store_swaps_total",
    "bibfs_store_delta_edges",
    "bibfs_store_compactions_total",
    "bibfs_store_compact_failures_total",
    "bibfs_store_mmap_bytes",
    "bibfs_store_tier",
    "bibfs_store_remap_total",
)

#: WAL durability layer (store/wal.py + store/registry.py); the crash
#: soak's render gate and the bench CI gate share this exact tuple
DURABLE_METRIC_FAMILIES = (
    "bibfs_wal_records_total",
    "bibfs_wal_fsyncs_total",
    "bibfs_checkpoints_total",
    "bibfs_recovery_replayed_records",
    "bibfs_recovery_seconds",
)

#: landmark distance-oracle tier (oracle/oracle.py + store/registry.py)
ORACLE_METRIC_FAMILIES = (
    "bibfs_oracle_hits_total",
    "bibfs_oracle_index_builds_total",
    "bibfs_oracle_index_age_seconds",
)

#: mesh-sharded serving route (serve/routes/mesh.py); minted at route
#: construction (engines configured with ``mesh=``), so a mesh-enabled
#: process renders the whole group at zero before any mesh traffic
MESH_METRIC_FAMILIES = (
    "bibfs_mesh_shards",
    "bibfs_mesh_batches_total",
    "bibfs_mesh_exchange_bytes_total",
    "bibfs_mesh_breaker_state",
    "bibfs_mesh_crossover_reroutes_total",
)

#: blocked (MXU-tile) serving route (serve/routes/blocked.py); minted
#: at route construction (engines configured with ``blocked=``), so a
#: blocked-enabled process renders the group at zero before any traffic
BLOCKED_METRIC_FAMILIES = (
    "bibfs_blocked_batches_total",
    "bibfs_blocked_breaker_state",
)

#: telemetry-driven adaptive routing (serve/policy.py; the frontier
#: histogram is fed by every telemetry-enabled solve, obs/telemetry.py)
ADAPTIVE_METRIC_FAMILIES = (
    "bibfs_routes_adaptive_total",
    "bibfs_level_frontier_fraction",
)

#: query taxonomy routes (serve/routes/taxonomy.py); minted at
#: route-set construction on EVERY engine, so any serving process
#: renders the group at zero before the first taxonomy query
QUERY_METRIC_FAMILIES = (
    "bibfs_query_total",
    "bibfs_query_asof_replay_seconds",
    "bibfs_msbfs_breaker_state",
    "bibfs_query_device_breaker_state",
)

#: whole-graph analytics tier (serve/routes/analytics.py +
#: analytics/results.py): the rounds counter and blocked-rung breaker
#: gauges mint at route-set construction on EVERY engine, the result-
#: store event/entry families at store construction — all render at
#: zero before the first analytics query
ANALYTICS_METRIC_FAMILIES = (
    "bibfs_analytics_rounds_total",
    "bibfs_analytics_breaker_state",
    "bibfs_analytics_store_events_total",
    "bibfs_analytics_store_entries",
)

#: network front door (serve/net.py); minted at NetServer construction
#: so a ``bibfs-serve --port`` process renders the whole group at zero
#: before the first connection. Rejection reasons are tenant-less
#: labels (reason= only — tenant ids are unbounded cardinality)
NET_METRIC_FAMILIES = (
    "bibfs_net_connections",
    "bibfs_net_requests_total",
    "bibfs_net_rejections_total",
    "bibfs_net_bytes_total",
    "bibfs_net_deadline_misses_total",
)

#: self-healing elastic layer (fleet/supervisor.py + fleet/router.py +
#: parallel/podmesh.py + serve/net.py): scale events and the replica
#: target mint at Supervisor construction, the catchup-stuck gauge at
#: Router construction (per replica, zero when healthy), the worker
#: epoch gauge at PodPrimary construction, and the admission-shed
#: counter (brownout ladder + deadline-feasibility, reason-labeled)
#: at NetServer construction — the elastic soak's render gate scrapes
#: exactly this tuple, so every family must render at zero before the
#: first scale event
ELASTIC_METRIC_FAMILIES = (
    "bibfs_fleet_scale_events_total",
    "bibfs_fleet_replicas_target",
    "bibfs_fleet_catchup_stuck",
    "bibfs_pod_worker_epoch",
    "bibfs_admission_shed_total",
)

#: distributed tracing + per-query cost attribution (obs/dtrace.py):
#: the span-spool counter mints at DTracer construction, the
#: flight-recorder dump counter at module import (process-singleton
#: recorder), and the stage histogram at engine / front-door
#: construction via ``dtrace.stage_histogram()`` — all render at zero
#: before the first sampled query
DTRACE_METRIC_FAMILIES = (
    "bibfs_stage_seconds",
    "bibfs_trace_spans_total",
    "bibfs_flightrec_dumps_total",
)

#: build identity (obs/metrics.py; minted at every registry init)
BUILD_INFO_METRIC = "bibfs_build_info"

#: fleet router (fleet/router.py) — bibfs_build_info rides along in the
#: gate tuple below because "which build is this replica" is the fleet
#: question a rolling restart asks
_FLEET_ONLY = (
    "bibfs_fleet_replicas",
    "bibfs_fleet_routed_total",
    "bibfs_fleet_reroutes_total",
    "bibfs_fleet_rolls_total",
    "bibfs_fleet_spills_total",
    "bibfs_fleet_catchups_total",
)
FLEET_METRIC_FAMILIES = _FLEET_ONLY + (BUILD_INFO_METRIC,)

#: every metric family the process can mint, grouped — the metric-mint
#: lint rule's ground truth
ALL_METRIC_NAMES = frozenset(
    ENGINE_METRIC_FAMILIES
    + PIPELINE_METRIC_FAMILIES
    + CACHE_METRIC_FAMILIES
    + RESILIENCE_METRIC_FAMILIES
    + STORE_METRIC_FAMILIES
    + DURABLE_METRIC_FAMILIES
    + ORACLE_METRIC_FAMILIES
    + MESH_METRIC_FAMILIES
    + BLOCKED_METRIC_FAMILIES
    + ADAPTIVE_METRIC_FAMILIES
    + QUERY_METRIC_FAMILIES
    + ANALYTICS_METRIC_FAMILIES
    + NET_METRIC_FAMILIES
    + ELASTIC_METRIC_FAMILIES
    + DTRACE_METRIC_FAMILIES
    + _FLEET_ONLY
    + (BUILD_INFO_METRIC,)
)

#: families rendered with Prometheus histogram exposition (each also
#: renders ``<name>_bucket{le=}`` / ``<name>_count`` / ``<name>_sum``
#: series — :func:`exposition_names`)
HISTOGRAM_METRIC_NAMES = frozenset((
    "bibfs_query_latency_seconds",
    "bibfs_level_frontier_fraction",
    "bibfs_stage_seconds",
))

#: ``bibfs_``-prefixed tokens that are NOT metric names (package paths,
#: reference source files) — the lint's literal/README scans skip these
NON_METRIC_TOKENS = frozenset((
    "bibfs_tpu",        # the package itself (paths in prose)
    "bibfs_cuda_only",  # the reference's v3 CUDA source file
))

#: the names the live-endpoint CI probe
#: (scripts/check_metrics_endpoint.py) asserts on a real
#: ``bibfs-serve --metrics-port`` scrape — the minimal always-on
#: pipelined-serving surface (store/fleet/oracle families need those
#: subsystems attached and are gated by their own soaks)
SERVE_ENDPOINT_METRICS = (
    "bibfs_queries_total",
    "bibfs_queries_routed_total",
    "bibfs_query_total",
    "bibfs_dist_cache_events_total",
    "bibfs_flush_cause_total",
    "bibfs_flushes_total",
    "bibfs_query_latency_seconds",
    "bibfs_serve_queue_depth",
    # per-query cost attribution: pre-labeled at engine construction,
    # so a live /metrics renders every stage cell at zero
    "bibfs_stage_seconds",
)


def exposition_names(name: str) -> tuple:
    """The text-exposition series one family renders: the family name
    itself for counters/gauges, the ``_bucket``/``_count``/``_sum``
    triple for histograms."""
    if name in HISTOGRAM_METRIC_NAMES:
        return (f"{name}_bucket", f"{name}_count", f"{name}_sum")
    return (name,)


def canonical_family(token: str) -> str | None:
    """Resolve a ``bibfs_*`` token to its canonical family name: the
    name itself, or the histogram family a ``_bucket``/``_count``/
    ``_sum`` exposition series belongs to. None if the token is not a
    known metric."""
    if token in ALL_METRIC_NAMES:
        return token
    for suffix in ("_bucket", "_count", "_sum"):
        if token.endswith(suffix):
            base = token[: -len(suffix)]
            if base in HISTOGRAM_METRIC_NAMES:
                return base
    return None
