"""Tracing spans exported as Chrome-trace / Perfetto JSON.

The pipelined serving layer's whole value proposition is *overlap* —
batch k+1's device dispatch in flight while batch k's host-side finish
runs on another thread — but until now the only evidence was the
aggregate ``overlap`` occupancy block in ``stats()``. This module makes
the overlap (and everything else phase-shaped: flushes, host batches,
cache banking, per-query solves) *visible*: context-manager spans
recorded per thread and written in the Chrome Trace Event format, which
Perfetto (https://ui.perfetto.dev) and ``chrome://tracing`` open
directly.

Zero-cost when off: the module-level :func:`span` checks one global and
returns a shared no-op context manager — no dict, no timestamps, no
allocation — so instrumented hot paths (`serve/engine.py` flushes,
cache ops) cost one attribute load per call until someone passes
``--trace`` to ``bibfs-serve`` or ``bench.py --serve``.

File format: the *JSON Array Format* of the Trace Event spec, written
one event per line (line-parseable like JSONL, and still a valid JSON
document — the spec also explicitly permits a missing ``]``, so even a
truncated file from a crashed process loads). Each event is a complete
``"ph": "X"`` (duration) record with microsecond ``ts``/``dur``;
thread-name metadata events label the flusher/finish/main lanes.
"""

from __future__ import annotations

import json
import os
import threading
import time


class _NullSpan:
    """The disabled path: one shared, reentrant, no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if exc_type is not None:
            self.args = {**self.args, "error": exc_type.__name__}
        self._tracer._complete(
            self.name, self.cat, self._t0, t1 - self._t0, self.args
        )
        return False


class Tracer:
    """Collects trace events in memory; ``save()`` writes the file.

    Bounded: past ``max_events`` new events are counted as dropped
    instead of growing without limit (a serving process can run for
    days with tracing accidentally left on). Thread-safe throughout —
    the flusher, finish worker, and any number of submitters record
    into one tracer.
    """

    def __init__(self, max_events: int = 500_000):
        self.max_events = int(max_events)
        self.dropped = 0
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._named_tids: set[int] = set()
        self._t0 = time.perf_counter()
        self._pid = os.getpid()

    # ---- recording ---------------------------------------------------
    def span(self, name: str, cat: str = "bibfs", **args) -> _Span:
        """A context manager recording one complete ("X") event over
        its ``with`` body."""
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "bibfs", **args) -> None:
        """A zero-duration marker ("i" event)."""
        self._append({
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": self._ts(time.perf_counter()),
            "pid": self._pid, "tid": self._tid(), "args": args,
        })

    def _ts(self, t: float) -> float:
        return round((t - self._t0) * 1e6, 3)  # µs, Chrome-trace unit

    def _tid(self) -> int:
        tid = threading.get_ident()
        if tid not in self._named_tids:
            # first event from this thread: label its lane (Perfetto
            # shows the name instead of a bare ident)
            with self._lock:
                if tid not in self._named_tids:
                    self._named_tids.add(tid)
                    self._events.append({
                        "name": "thread_name", "ph": "M",
                        "pid": self._pid, "tid": tid,
                        "args": {
                            "name": threading.current_thread().name
                        },
                    })
        return tid

    def _complete(self, name, cat, t0, dur, args) -> None:
        self._append({
            "name": name, "cat": cat, "ph": "X",
            "ts": self._ts(t0), "dur": round(dur * 1e6, 3),
            "pid": self._pid, "tid": self._tid(), "args": args,
        })

    def _append(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    # ---- reading / export --------------------------------------------
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def save(self, path: str) -> int:
        """Write the Chrome-trace JSON array, one event per line,
        committed atomically (same-dir tmp + fsync + rename — a crash
        mid-save leaves the previous trace, never a torn one). Returns
        the number of events written."""
        from bibfs_tpu.graph.io import _atomic_replace

        evs = self.events()

        def _payload(f):
            f.write("[\n")
            for i, ev in enumerate(evs):
                comma = "," if i < len(evs) - 1 else ""
                f.write(json.dumps(ev, separators=(",", ":")) + comma + "\n")
            f.write("]\n")

        _atomic_replace(path, _payload, mode="w")
        return len(evs)


# ---- the process-global tracer hookpoint ----------------------------
_GLOBAL: Tracer | None = None


def set_tracer(tracer: Tracer | None) -> Tracer | None:
    """Install (or clear, with None) the process-global tracer that
    :func:`span` records into; returns the previous one."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = tracer
    return prev


def get_tracer() -> Tracer | None:
    return _GLOBAL


def span(name: str, cat: str = "bibfs", **args):
    """Record a span on the global tracer, or do nothing (one global
    load + one comparison) when tracing is off — the form every
    instrumented hot path uses."""
    t = _GLOBAL
    if t is None:
        return _NULL_SPAN
    return t.span(name, cat, **args)


def instant(name: str, cat: str = "bibfs", **args) -> None:
    t = _GLOBAL
    if t is not None:
        t.instant(name, cat, **args)


def uninstall_and_save(tracer: Tracer, path: str, stream=None) -> int | None:
    """The CLI/bench teardown sequence, in one place: clear the global
    hook, write the Chrome-trace file, report to ``stream`` (default
    stderr). A bad path must never discard the work that was traced —
    the OSError is reported, not raised. Returns the event count, or
    None when the save failed."""
    import sys

    stream = sys.stderr if stream is None else stream
    set_tracer(None)
    try:
        nev = tracer.save(path)
    except OSError as e:
        print(f"warning: could not write trace to {path}: {e}",
              file=stream)
        return None
    print(f"[Obs] wrote {nev} trace events to {path} "
          "(open in https://ui.perfetto.dev)", file=stream)
    return nev


def overlapping_pairs(events, name_a: str, name_b: str) -> list:
    """(a, b) pairs of ``name_a``/``name_b`` complete-events whose time
    intervals intersect while running on DIFFERENT threads — the
    machine-checkable form of "dispatch overlapped finish" that the
    trace tests (and curious notebook users) ask of a pipelined run."""
    a_evs = [e for e in events if e.get("ph") == "X" and e["name"] == name_a]
    b_evs = [e for e in events if e.get("ph") == "X" and e["name"] == name_b]
    out = []
    for a in a_evs:
        a0, a1 = a["ts"], a["ts"] + a["dur"]
        for b in b_evs:
            if a.get("tid") == b.get("tid"):
                continue
            b0, b1 = b["ts"], b["ts"] + b["dur"]
            if a0 < b1 and b0 < a1:
                out.append((a, b))
    return out
