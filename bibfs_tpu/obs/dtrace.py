"""Distributed tracing + per-query cost attribution across processes.

PR 3's tracer (:mod:`bibfs_tpu.obs.trace`) answers "what overlapped
inside THIS process"; the serving plane now spans processes — the TCP
front door, subprocess/net fleet replicas, pod workers lockstepped over
a ``jax.distributed`` mesh — and no single-process trace can show one
query's life across the wire. This module is the cross-process spine:

- **Propagated context.** A :class:`TraceContext` is a 128-bit trace id
  plus the current span id. The sampling decision is made ONCE at
  ingress (:meth:`DTracer.sample`); an unsampled query carries
  ``ctx=None`` on every hop, so the disabled path stays the PR 3
  contract: one global load, one ``is None`` check, zero allocation.
  The context rides every cross-process protocol as two fields —
  ``trace``/``span`` keys on net frames and pod ``solve`` descriptors,
  and an ``@t:<trace>:<span>`` token appended to stdin REPL query lines
  (:func:`ctx_token` / :func:`parse_token`).
- **Per-process spool.** Each sampled span appends ONE complete JSON
  line to ``<spool>/<proc>.<pid>.jsonl`` and flushes — crash-tolerant
  by construction: a SIGKILLed replica's spool is readable up to the
  last complete line, which is exactly how the merger reads it. Spool
  writes are resilient to a closed file on interpreter teardown
  (dropped, never raised) and carry the ``trace_flush`` chaos seam.
- **Merger.** ``bibfs-trace merge SPOOL_DIR -o out.json`` assembles one
  Perfetto-loadable Chrome-trace JSON across every spool file, emits
  ``process_name`` metadata per pid, and validates parentage (every
  non-root parent id must resolve to a recorded span in the same
  trace). Timestamps are wall-clock microseconds, so spans from
  different hosts' processes land on one timeline (clock skew bounds
  the alignment; the wire-stage bookkeeping below measures it).
- **Flight recorder.** Always-on and bounded: a per-process ring of the
  last N query timelines, route decisions and fault trips
  (:class:`FlightRecorder`), dumped atomically to a
  ``*.flightrec.json`` on fault-site trips (rate-limited) and on
  demand via the ``flightrec`` control op on both the stdin REPL and
  the net protocol — the post-mortem the chaos/crash soaks gate on.

Metric families minted here (canonical list ``obs/names.py``):
``bibfs_trace_spans_total{proc}`` at :class:`DTracer` construction and
``bibfs_flightrec_dumps_total{reason}`` at module import (the recorder
is a process singleton). The per-stage cost histogram
``bibfs_stage_seconds{stage}`` is minted by the engines/front door via
:func:`stage_histogram` at THEIR construction.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from collections import deque

from bibfs_tpu.obs.metrics import REGISTRY

#: env vars spawned children inherit (fleet subprocess replicas and pod
#: workers re-exec ``bibfs-serve`` with the parent's environ): set the
#: spool dir + sample rate once in the driver and every process of the
#: job traces into the same directory
ENV_SPOOL = "BIBFS_TRACE_SPOOL"
ENV_SAMPLE = "BIBFS_TRACE_SAMPLE"
ENV_FLIGHTREC = "BIBFS_FLIGHTREC"

#: the per-query stage timeline (ingress -> queue -> launch -> finish ->
#: resolve, plus the wire stage measured from both sides' clocks)
STAGES = ("ingress", "queue", "launch", "finish", "resolve", "wire")

#: wall-clock epoch of perf_counter()'s zero, measured once at import:
#: spans time themselves on the monotonic clock and STAMP themselves on
#: the wall clock, so cross-process merge aligns without per-span
#: time.time() calls on hot paths
_PERF_EPOCH = time.time() - time.perf_counter()


def wall_us(t_perf: float) -> float:
    """A perf_counter() reading as wall-clock microseconds."""
    return (t_perf + _PERF_EPOCH) * 1e6


class TraceContext:
    """One hop's worth of trace identity: which trace, which span to
    parent under. ``span_id == ""`` marks a root context (the ingress
    sampling decision before any span exists)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


# ---- wire encoding ---------------------------------------------------
def ctx_fields(ctx: TraceContext | None) -> dict:
    """The two JSON fields a net frame / pod descriptor carries."""
    if ctx is None:
        return {}
    return {"trace": ctx.trace_id, "span": ctx.span_id}


def ctx_from_fields(msg: dict) -> TraceContext | None:
    """Adopt a frame/descriptor's context, or None when it carries
    none (or carries garbage — a malformed trace id from a foreign
    client must not kill the query it rode in on)."""
    trace = msg.get("trace")
    if not isinstance(trace, str) or not trace:
        return None
    span = msg.get("span")
    return TraceContext(trace, span if isinstance(span, str) else "")


TOKEN_PREFIX = "@t:"


def ctx_token(ctx: TraceContext) -> str:
    """The REPL line-protocol form: ``@t:<trace>:<span>`` appended to a
    ``src dst`` query line."""
    return f"{TOKEN_PREFIX}{ctx.trace_id}:{ctx.span_id}"


def parse_token(tok: str) -> TraceContext | None:
    """Inverse of :func:`ctx_token`; None on anything malformed."""
    if not tok.startswith(TOKEN_PREFIX):
        return None
    trace, _, span = tok[len(TOKEN_PREFIX):].partition(":")
    if not trace:
        return None
    return TraceContext(trace, span)


# ---- spans -----------------------------------------------------------
class _NullDSpan:
    """The disabled path: one shared, reentrant no-op (PR 3 contract).
    ``ctx`` is None so propagation sites can read ``sp.ctx``
    unconditionally."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def finish(self, **args):
        pass


_NULL_DSPAN = _NullDSpan()


class DSpan:
    """One sampled span: starts at construction (so its id can ride a
    frame BEFORE the work completes), records on ``finish()`` or
    ``with``-exit. ``.ctx`` is the child context downstream hops parent
    under."""

    __slots__ = ("_tracer", "name", "ctx", "parent", "_t0", "_args",
                 "_done")

    def __init__(self, tracer: "DTracer", name: str,
                 parent: TraceContext, args: dict):
        self._tracer = tracer
        self.name = name
        self.parent = parent.span_id
        self.ctx = TraceContext(parent.trace_id, _span_id())
        self._args = args
        self._t0 = time.perf_counter()
        self._done = False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._args = {**self._args, "error": exc_type.__name__}
        self.finish()
        return False

    def finish(self, **args) -> None:
        """Record the span (idempotent — a reply path and a teardown
        path may both try to close it)."""
        if self._done:
            return
        self._done = True
        if args:
            self._args = {**self._args, **args}
        dur = time.perf_counter() - self._t0
        self._tracer._record(
            self.name, self.ctx, self.parent, self._t0, dur, self._args
        )


def _span_id() -> str:
    return os.urandom(8).hex()


class DTracer:
    """The per-process distributed-trace spool writer (module
    docstring). One instance per process, installed via
    :func:`set_dtracer` (or :func:`install_from_env` in spawned
    children); every sampled span appends one JSON line to
    ``<spool>/<proc>.<pid>.jsonl`` and flushes."""

    def __init__(self, spool_dir: str, proc: str, *,
                 sample: float = 1.0, faults=None):
        os.makedirs(spool_dir, exist_ok=True)
        self.spool_dir = spool_dir
        self.proc = proc
        self.sample_rate = max(0.0, min(1.0, float(sample)))
        self.faults = faults
        self._pid = os.getpid()
        self.path = os.path.join(spool_dir, f"{proc}.{self._pid}.jsonl")
        self._lock = threading.Lock()
        self._f = open(self.path, "a")
        self.dropped = 0
        # minted at construction (render-at-zero before the first span)
        self._spans = REGISTRY.counter(
            "bibfs_trace_spans_total",
            "Distributed-trace spans spooled, per process name",
            ("proc",),
        ).labels(proc=proc)
        # sampling uses os.urandom-derived ids, but the RATE decision
        # wants a cheap PRNG; seedable would couple runs across
        # processes, so module random is fine here
        import random

        self._rng = random.Random()

    # ---- ingress -----------------------------------------------------
    def sample(self) -> TraceContext | None:
        """The once-per-query ingress decision: a fresh root context
        when this query is sampled, else None (which then rides every
        hop as the no-op marker)."""
        if self.sample_rate <= 0.0:
            return None
        if self.sample_rate < 1.0 and self._rng.random() >= self.sample_rate:
            return None
        return TraceContext(os.urandom(16).hex(), "")

    # ---- recording ---------------------------------------------------
    def span(self, name: str, ctx: TraceContext, **args) -> DSpan:
        """A live span under ``ctx`` (context manager, or explicit
        ``finish()``); its ``.ctx`` is what downstream hops carry."""
        return DSpan(self, name, ctx, args)

    def emit(self, name: str, ctx: TraceContext, t0_perf: float,
             dur_s: float, **args) -> None:
        """A retrospective span under ``ctx`` from already-measured
        perf_counter() endpoints — how ticket stage timelines become
        spans at resolve time without wrapping the hot path in context
        managers."""
        self._record(name, TraceContext(ctx.trace_id, _span_id()),
                     ctx.span_id, t0_perf, dur_s, args)

    def _record(self, name, ctx, parent, t0_perf, dur_s, args) -> None:
        rec = {
            "t": ctx.trace_id, "s": ctx.span_id, "n": name,
            "ts": round(wall_us(t0_perf), 3),
            "d": round(dur_s * 1e6, 3),
            "pid": self._pid, "tid": threading.get_ident(),
            "pr": self.proc,
        }
        if parent:
            rec["p"] = parent
        if args:
            rec["a"] = args
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        try:
            if self.faults is not None:
                self.faults.fire("trace_flush")
            with self._lock:
                self._f.write(line)
                self._f.flush()
        except (ValueError, OSError, RuntimeError):
            # closed spool on interpreter teardown, full disk, or an
            # injected trace_flush fault: tracing must never take the
            # serving path down — drop the span and keep serving
            self.dropped += 1
            return
        self._spans.inc()

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


# ---- the process-global hookpoint ------------------------------------
_GLOBAL: DTracer | None = None


def set_dtracer(tracer: DTracer | None) -> DTracer | None:
    """Install (or clear) the process-global distributed tracer;
    returns the previous one."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = tracer
    return prev


def get_dtracer() -> DTracer | None:
    return _GLOBAL


def dspan(name: str, ctx: TraceContext | None, **args):
    """A span under ``ctx`` on the global tracer — or the shared no-op
    when tracing is off OR this query is unsampled (``ctx is None``):
    one global load + two ``is None`` checks, no allocation."""
    t = _GLOBAL
    if t is None or ctx is None:
        return _NULL_DSPAN
    return t.span(name, ctx, **args)


def emit_span(name: str, ctx: TraceContext | None, t0_perf: float,
              dur_s: float, **args) -> None:
    """Retrospective-span form of :func:`dspan` (same gating)."""
    t = _GLOBAL
    if t is not None and ctx is not None:
        t.emit(name, ctx, t0_perf, dur_s, **args)


def sample_ctx() -> TraceContext | None:
    """The module-level ingress decision: None when tracing is off."""
    t = _GLOBAL
    if t is None:
        return None
    return t.sample()


def install_from_env(proc: str, environ=None) -> DTracer | None:
    """Install a :class:`DTracer` (and arm the flight recorder's dump
    path) from ``BIBFS_TRACE_SPOOL`` / ``BIBFS_TRACE_SAMPLE`` — how
    spawned replicas and pod workers join the driver's trace job
    without new argv. No spool var set: returns None, changes
    nothing."""
    environ = os.environ if environ is None else environ
    spool = environ.get(ENV_SPOOL, "").strip()
    if not spool:
        return None
    try:
        sample = float(environ.get(ENV_SAMPLE, "1") or "1")
    except ValueError:
        sample = 1.0
    tracer = DTracer(spool, proc, sample=sample)
    set_dtracer(tracer)
    FLIGHT.configure(dump_path=os.path.join(
        spool, f"{proc}.{os.getpid()}.flightrec.json"
    ))
    return tracer


def stage_histogram():
    """The per-query cost-attribution histogram, pre-labeled so serving
    never allocates a label cell per query. Engines and the net front
    door mint it at construction (render-at-zero)."""
    fam = REGISTRY.histogram(
        "bibfs_stage_seconds",
        "Per-query time in each serving stage "
        "(ingress/queue/launch/finish/resolve/wire)",
        ("stage",),
    )
    return {stage: fam.labels(stage=stage) for stage in STAGES}


# ---- flight recorder -------------------------------------------------
class FlightRecorder:
    """Always-on bounded post-mortem buffer: the last ``capacity``
    query timelines, route decisions and fault trips this process saw.
    ``dump()`` writes the ring atomically
    (:func:`~bibfs_tpu.graph.io._atomic_replace`); ``on_fault`` dumps
    rate-limited when a dump path is configured (the chaos soaks' crash
    sites), and the ``flightrec`` control op dumps on demand."""

    def __init__(self, capacity: int = 128):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dump_path: str | None = None
        self._last_fault_dump = 0.0
        self.fault_dump_interval_s = 5.0
        self._dumps = REGISTRY.counter(
            "bibfs_flightrec_dumps_total",
            "Flight-recorder ring dumps, by trigger",
            ("reason",),
        )

    def configure(self, *, dump_path: str | None = None,
                  capacity: int | None = None) -> None:
        with self._lock:
            if dump_path is not None:
                self._dump_path = dump_path
            if capacity is not None and int(capacity) != self.capacity:
                self.capacity = int(capacity)
                self._ring = deque(self._ring, maxlen=self.capacity)

    def note(self, kind: str, **fields) -> None:
        """Append one entry (``kind`` in query/route/fault); O(1),
        bounded, never raises into the serving path."""
        fields["kind"] = kind
        fields["at"] = round(time.time(), 6)
        with self._lock:
            self._ring.append(fields)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "pid": os.getpid(),
                "capacity": self.capacity,
                "entries": list(self._ring),
                "dump_path": self._dump_path,
            }

    def dump(self, path: str | None = None, *,
             reason: str = "demand") -> str | None:
        """Atomically write the ring to ``path`` (default: the
        configured dump path). Returns the path written, or None when
        no path is known or the write failed — a post-mortem helper
        must never add a second failure to the one being recorded."""
        from bibfs_tpu.graph.io import _atomic_replace

        path = path or self._dump_path
        if path is None:
            return None
        snap = self.snapshot()
        snap["reason"] = reason
        try:
            _atomic_replace(
                path,
                lambda f: json.dump(snap, f, sort_keys=True, default=str),
                mode="w",
            )
        except OSError:
            return None
        self._dumps.labels(reason=reason).inc()
        return path

    def on_fault(self, site: str) -> None:
        """The fault-site hook (``serve/faults`` calls this as a rule
        fires): record the trip, and dump the ring if a path is armed —
        rate-limited so a fault storm costs one file write per
        interval, not one per injection."""
        self.note("fault", site=site)
        with self._lock:
            path = self._dump_path
            now = time.monotonic()
            if path is None \
                    or now - self._last_fault_dump < self.fault_dump_interval_s:
                return
            self._last_fault_dump = now
        self.dump(path, reason="fault")


#: the per-process recorder every engine/front door notes into
FLIGHT = FlightRecorder()


def flight_on_fault(site: str) -> None:
    """Module-level indirection for ``serve/faults`` (lazy import
    there keeps the faults module free of obs dependencies at parse
    time)."""
    FLIGHT.on_fault(site)


# ---- merger ----------------------------------------------------------
def read_spool(path: str) -> tuple[list, int]:
    """Parse one spool file: complete JSON lines become records; a torn
    tail (the SIGKILL case) or a corrupt line is counted, not raised.
    Returns ``(records, bad_lines)``."""
    records, bad = [], 0
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                if not line.endswith("\n"):
                    bad += 1  # torn tail: the process died mid-write
                    continue
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if isinstance(rec, dict) and "t" in rec and "s" in rec:
                    records.append(rec)
                else:
                    bad += 1
    except OSError:
        return [], 0
    return records, bad


def merge_spools(spool_dir: str, out_path: str | None = None) -> dict:
    """Assemble every ``*.jsonl`` spool under ``spool_dir`` into one
    Chrome-trace event array with per-pid ``process_name`` metadata,
    and validate parentage per trace. Returns the report dict
    (``events``, per-trace summaries, orphan list); with ``out_path``
    the event array is also written atomically as Perfetto-loadable
    JSON."""
    records: list[dict] = []
    files = 0
    truncated = 0
    for name in sorted(os.listdir(spool_dir)):
        if not name.endswith(".jsonl"):
            continue
        recs, bad = read_spool(os.path.join(spool_dir, name))
        files += 1
        truncated += bad
        records.extend(recs)

    # parentage: every non-root parent id resolves to a span recorded
    # in the SAME trace (the cross-process causality check)
    by_trace: dict[str, list[dict]] = {}
    for rec in records:
        by_trace.setdefault(rec["t"], []).append(rec)
    traces = []
    orphans = []
    for tid, recs in sorted(by_trace.items()):
        ids = {r["s"] for r in recs}
        torn = [r for r in recs if r.get("p") and r["p"] not in ids]
        orphans.extend(torn)
        traces.append({
            "trace": tid,
            "spans": len(recs),
            "pids": sorted({r["pid"] for r in recs}),
            "procs": sorted({r["pr"] for r in recs}),
            "orphan_parents": len(torn),
        })

    # Chrome-trace events: normalize ts to the earliest span so the
    # Perfetto timeline starts at ~0 instead of the wall-clock epoch
    t0 = min((r["ts"] for r in records), default=0.0)
    events: list[dict] = []
    seen_pids: dict[int, str] = {}
    for rec in records:
        if rec["pid"] not in seen_pids:
            seen_pids[rec["pid"]] = rec["pr"]
            events.append({
                "name": "process_name", "ph": "M", "pid": rec["pid"],
                "tid": 0, "args": {"name": rec["pr"]},
            })
    for rec in sorted(records, key=lambda r: r["ts"]):
        args = dict(rec.get("a") or {})
        args["trace"] = rec["t"]
        args["span"] = rec["s"]
        if rec.get("p"):
            args["parent"] = rec["p"]
        events.append({
            "name": rec["n"], "cat": "dtrace", "ph": "X",
            "ts": round(rec["ts"] - t0, 3), "dur": rec["d"],
            "pid": rec["pid"], "tid": rec["tid"], "args": args,
        })

    report = {
        "files": files,
        "spans": len(records),
        "truncated_lines": truncated,
        "traces": traces,
        "orphan_parents": len(orphans),
        "events": events,
    }
    if out_path is not None:
        from bibfs_tpu.graph.io import _atomic_replace

        def _payload(f):
            f.write("[\n")
            for i, ev in enumerate(events):
                comma = "," if i < len(events) - 1 else ""
                f.write(json.dumps(ev, separators=(",", ":")) + comma
                        + "\n")
            f.write("]\n")

        _atomic_replace(out_path, _payload, mode="w")
    return report


def cross_process_traces(report: dict, min_procs: int = 2) -> list:
    """The smoke-gate predicate: traces whose spans cover at least
    ``min_procs`` distinct OS processes with zero orphan parents."""
    return [
        t for t in report["traces"]
        if len(t["pids"]) >= min_procs and t["orphan_parents"] == 0
    ]


def main(argv=None) -> int:
    """``bibfs-trace`` — merge per-process spool files into one
    Perfetto-loadable trace."""
    ap = argparse.ArgumentParser(
        description="Merge bibfs distributed-trace spools "
        "(<proc>.<pid>.jsonl) into one Chrome-trace JSON"
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser(
        "merge", help="merge every *.jsonl spool in SPOOL_DIR"
    )
    mp.add_argument("spool_dir", help="directory of per-process spools")
    mp.add_argument("-o", "--out", default=None, metavar="FILE",
                    help="write the merged Chrome-trace JSON here "
                    "(default: SPOOL_DIR/merged_trace.json)")
    mp.add_argument("--min-procs", type=int, default=1, metavar="N",
                    help="exit 1 unless >= 1 trace spans N processes "
                    "with fully-resolved parentage (default 1)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.spool_dir):
        print(f"Error: {args.spool_dir} is not a directory",
              file=sys.stderr)
        return 2
    out = args.out or os.path.join(args.spool_dir, "merged_trace.json")
    report = merge_spools(args.spool_dir, out_path=out)
    good = cross_process_traces(report, min_procs=args.min_procs)
    print(
        "[Trace] merged {f} spool(s): {s} spans, {t} trace(s), "
        "{o} orphan parent(s), {tr} truncated line(s) -> {out}".format(
            f=report["files"], s=report["spans"],
            t=len(report["traces"]), o=report["orphan_parents"],
            tr=report["truncated_lines"], out=out,
        ),
        file=sys.stderr,
    )
    for t in report["traces"][:10]:
        print(
            "[Trace]   {id}: {n} span(s) across pids {p} ({pr})".format(
                id=t["trace"][:16], n=t["spans"],
                p=",".join(str(x) for x in t["pids"]),
                pr=",".join(t["procs"]),
            ),
            file=sys.stderr,
        )
    if not good:
        print(
            f"Error: no trace spans >= {args.min_procs} process(es) "
            "with resolved parentage", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
