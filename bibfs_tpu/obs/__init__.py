"""Unified observability: metrics registry, tracing spans, per-level
solver telemetry.

Three layers, one subsystem (each documented in its module):

- :mod:`bibfs_tpu.obs.metrics` — process-wide registry of counters /
  gauges / log-bucket histograms with labels and a Prometheus text
  renderer; :data:`~bibfs_tpu.obs.metrics.REGISTRY` is the default
  every serving component lands in.
- :mod:`bibfs_tpu.obs.http` — the stdlib ``/metrics`` endpoint
  (``bibfs-serve --metrics-port``).
- :mod:`bibfs_tpu.obs.trace` — context-manager spans exported as
  Chrome-trace/Perfetto JSON (``--trace out.json``).
- :mod:`bibfs_tpu.obs.telemetry` — the opt-in ``telemetry=`` hook
  recording per-level frontier/edge/direction stats onto
  ``BFSResult.level_stats``.

No JAX import anywhere in this package: observability must load (and
serve ``/metrics``) even on hosts where only the native/serial
backends run.
"""

from bibfs_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    LogHistogram,
    MetricsRegistry,
    next_instance_label,
)
from bibfs_tpu.obs.telemetry import LevelTelemetry  # noqa: F401
from bibfs_tpu.obs.trace import (  # noqa: F401
    Tracer,
    get_tracer,
    set_tracer,
    span,
)
