"""Process-wide metrics registry with Prometheus text exposition.

Before this module, the serving stack's operational numbers lived in
four ad-hoc places with four naming schemes: ``QueryEngine.counters``,
``PipelinedQueryEngine.pipe_counters``, the :class:`ExecutableCache`
hit/miss pair, and the :class:`DistanceCache` eviction ledger — all
snapshot dicts with no time dimension and no way to watch a running
``bibfs-serve`` process. This registry is the one place they now land:

- **Counters** — monotonically increasing event counts
  (``bibfs_queries_total``). Prometheus derives rates (qps) from the
  scrape-time series, which is exactly the time dimension the dicts
  lacked.
- **Gauges** — point-in-time values and watermarks
  (``bibfs_serve_queue_depth``, ``bibfs_exec_programs``).
- **Histograms** — :class:`LogHistogram`, the log-bucketed
  O(1)-memory histogram the pipelined engine's latency tracking
  introduced, generalized: same 2^(1/4) geometric buckets, same
  upper-edge percentile reads, now also rendered as cumulative
  Prometheus ``_bucket{le=...}`` series.

Every metric family carries **labels** (engine, route, cause, cache,
program): one family, many children, each child a cheap lock-guarded
cell. Children are created once (at engine/cache construction or first
label use) — the serving hot path only increments existing cells, never
allocates registry objects per query.

The process-wide default registry is :data:`REGISTRY`;
:func:`bibfs_tpu.obs.http.start_metrics_server` serves its
:meth:`~MetricsRegistry.render` at ``/metrics``. Component ``stats()``
dicts are kept backwards-compatible as snapshot views over these cells
(see ``serve/engine.py``'s :class:`MetricBank` usage).
"""

from __future__ import annotations

import itertools
import math
import os
import sys
import threading

# one shared label used by components constructed without an explicit
# instance label (the common serving-process case: one engine, one cache)
_SEQ = itertools.count()


def next_instance_label(prefix: str) -> str:
    """A process-unique label value for one component instance
    (``engine-3``, ``exec-0``): keeps per-instance ``stats()`` exact
    while every instance still lands in the one process registry.

    Callers passing an EXPLICIT label instead must keep it unique per
    instance of a component class — two same-class instances sharing a
    label share cells, which merges their stats and (cells being
    lock-free) races their increments across the two instances' locks.

    The flip side of per-instance labels: cells are never removed, so
    a process that constructs engines per request grows its registry
    (and ``/metrics`` payload) by a few dozen small cells per engine.
    That is the intended trade for a serving process (one or two
    long-lived engines); bench harnesses that churn engines per rate
    point accept a bounded, run-scoped accumulation."""
    return f"{prefix}-{next(_SEQ)}"


_BUILD_INFO: dict | None = None


def _read_git_rev() -> str:
    """The working tree's HEAD commit (12 hex chars), read straight
    from ``.git`` — no subprocess: this runs at registry construction,
    which sits on every serving process's import path, and forking git
    there would tax exactly the processes (replica fleets) the gauge
    exists to identify."""
    try:
        from pathlib import Path

        root = Path(__file__).resolve().parents[2]
        head = (root / ".git" / "HEAD").read_text().strip()
        if not head.startswith("ref:"):
            return head[:12] or "unknown"
        ref = head.split(None, 1)[1]
        ref_file = root / ".git" / ref
        if ref_file.exists():
            return ref_file.read_text().strip()[:12] or "unknown"
        packed = root / ".git" / "packed-refs"
        if packed.exists():
            for ln in packed.read_text().splitlines():
                if ln.endswith(" " + ref):
                    return ln.split()[0][:12]
    except Exception:
        pass
    return "unknown"


def build_info_fields() -> dict:
    """The build-identity labels ``bibfs_build_info`` carries — the
    same fields every ``bench_*.json`` artifact's ``meta`` block stamps
    (git rev, os, machine, python, jax, numpy; the meta block's
    timestamp is run provenance, not build identity, so it stays out).
    Versions come from package metadata, NOT imports: minting a gauge
    must never pull jax into a process that wasn't going to use it.
    Computed once per process."""
    global _BUILD_INFO
    if _BUILD_INFO is None:
        from importlib import metadata

        def _ver(pkg: str) -> str:
            try:
                return metadata.version(pkg)
            except Exception:
                return "unknown"

        uname = os.uname()
        _BUILD_INFO = {
            "git_rev": _read_git_rev(),
            "os": f"{uname.sysname} {uname.release}",
            "machine": uname.machine,
            "python": sys.version.split()[0],
            "jax": _ver("jax"),
            "numpy": _ver("numpy"),
        }
    return _BUILD_INFO


def _validate_name(name: str) -> str:
    if not name or not all(c.isalnum() or c in "_:" for c in name):
        raise ValueError(f"bad metric name {name!r}")
    return name


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n"
    )


def _fmt_value(v) -> str:
    if isinstance(v, float):
        if v == math.inf:
            return "+Inf"
        if v != v:  # NaN
            return "NaN"
        return repr(v)
    return str(v)


def _labels_suffix(labelnames, labelvalues) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"'
        for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


# Counter/Gauge cells are deliberately LOCK-FREE: every serving-path
# mutation site was already externally serialized before the registry
# migration (the engine lock / condition variable, the caches' own
# locks, the single finish worker), and the cells inherit exactly that
# contract — concurrent mutators of ONE cell must hold the component's
# lock, reads are GIL-atomic snapshots. A per-cell lock would put two
# lock handoffs on every hot-path increment; on the measured serving
# box the whole cold 256-query flush is ~9 ms, so that tax is the
# difference between "free" and a visible qps regression.


class Counter:
    """One monotonically increasing cell (a family child)."""

    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError(f"counters only go up (inc {amount})")
        self._value += amount

    def set(self, value):
        """Direct assignment — exists so dict-style back-compat views
        (``bank[k] = bank[k] + 1``) keep working; still monotonic."""
        if value < self._value:
            raise ValueError(
                f"counters only go up ({self._value} -> {value})"
            )
        self._value = value

    @property
    def value(self):
        return self._value


class Gauge:
    """One point-in-time cell: settable up or down, plus a watermark
    helper for the engines' ``*_max_ms`` counters."""

    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0

    def set(self, value):
        self._value = value

    def inc(self, amount=1):
        self._value += amount

    def dec(self, amount=1):
        self._value -= amount

    def set_max(self, value):
        """Watermark update: keep the larger of current and ``value``."""
        if value > self._value:
            self._value = value

    @property
    def value(self):
        return self._value


class LogHistogram:
    """Thread-safe log-bucketed histogram (seconds by default).

    O(1) memory at any traffic volume: samples land in geometric buckets
    (ratio 2^1/4 ≈ 19% resolution, 1 µs .. ~100 s) and percentiles read
    the bucket upper edge where the cumulative count crosses the rank —
    a ~19% overestimate bound, which is plenty for an SLO dashboard and
    never samples away tail events (exact ``max`` is tracked aside).

    This is the one histogram type in the codebase: the pipelined
    engine's per-query latency (``serve.pipeline.LatencyHistogram`` is
    an alias), the registry's Prometheus histograms, and the load
    harness's per-rate artifacts all share it, so their buckets line up
    across every surface.
    """

    _BASE = 1e-6  # 1 µs
    _RATIO = 2 ** 0.25
    _NBUCKETS = 108  # last edge ~ 1e-6 * 2^(107/4) ≈ 127 s

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * self._NBUCKETS
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    @classmethod
    def bucket_edge(cls, i: int) -> float:
        """Upper edge (inclusive) of bucket ``i``, in seconds."""
        return cls._BASE * cls._RATIO ** i

    def _bucket(self, s: float) -> int:
        if s <= self._BASE:
            return 0
        return min(
            int(math.log(s / self._BASE, self._RATIO)) + 1,
            self._NBUCKETS - 1,
        )

    def record(self, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        i = self._bucket(s)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.total_s += s
            if s > self.max_s:
                self.max_s = s

    # Counter-cell protocol alias so histograms can live in a
    # MetricBank next to counters if ever needed.
    observe = record

    def record_many(self, seconds_list) -> None:
        """One lock acquisition for a whole batch of samples — the
        per-query histogram cost in the serving hot loop is the bucket
        index, not a lock handoff."""
        if not seconds_list:
            return
        samples = [(max(float(s), 0.0)) for s in seconds_list]
        with self._lock:
            for s in samples:
                self._counts[self._bucket(s)] += 1
                self.total_s += s
                if s > self.max_s:
                    self.max_s = s
            self.count += len(samples)

    def percentile(self, q: float) -> float:
        """Upper-edge estimate of the ``q``-quantile (0 < q <= 1), in
        seconds; 0.0 when empty."""
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = q * self.count
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if seen >= rank:
                    return min(self._BASE * self._RATIO ** i, self.max_s)
            return self.max_s

    def summary_ms(self) -> dict:
        """The stats() block: count/mean plus the SLO percentiles."""
        p50, p95, p99 = (self.percentile(q) for q in (0.5, 0.95, 0.99))
        with self._lock:
            mean = self.total_s / self.count if self.count else 0.0
            return {
                "count": self.count,
                "mean_ms": round(mean * 1e3, 4),
                "p50_ms": round(p50 * 1e3, 4),
                "p95_ms": round(p95 * 1e3, 4),
                "p99_ms": round(p99 * 1e3, 4),
                "max_ms": round(self.max_s * 1e3, 4),
            }

    def to_dict(self) -> dict:
        """Full-fidelity JSON export (the load harness's per-rate
        artifact): sparse ``[bucket_index, count]`` pairs plus the
        bucket geometry, so any consumer can reconstruct edges with
        ``base * ratio**i`` and re-plot quantiles."""
        with self._lock:
            buckets = [
                [i, c] for i, c in enumerate(self._counts) if c
            ]
            return {
                "base_s": self._BASE,
                "ratio": round(self._RATIO, 6),
                "nbuckets": self._NBUCKETS,
                "buckets": buckets,
                "count": self.count,
                "sum_s": round(self.total_s, 6),
                "max_s": round(self.max_s, 6),
            }

    def cumulative(self) -> list:
        """(upper_edge_seconds, cumulative_count) pairs for Prometheus
        rendering; empty trailing buckets are collapsed into +Inf."""
        with self._lock:
            out = []
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if c:
                    out.append((self.bucket_edge(i), seen))
            return out

    @property
    def value(self):  # MetricBank read protocol
        return self.count


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": LogHistogram}


class MetricFamily:
    """One named metric + its labeled children.

    ``labels(**kv)`` returns (creating on first use) the child cell for
    one label-value combination; a zero-label family proxies the cell
    methods directly (``family.inc()``)."""

    def __init__(self, name: str, help: str, kind: str, labelnames=()):
        self.name = _validate_name(name)
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict = {}
        if not self.labelnames:
            self._children[()] = _KINDS[kind]()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != "
                f"declared {sorted(self.labelnames)}"
            )
        key = tuple(str(kv[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _KINDS[self.kind]()
                self._children[key] = child
            return child

    def children(self) -> dict:
        with self._lock:
            return dict(self._children)

    # zero-label convenience: the family IS its only child
    def _solo(self):
        return self._children[()]

    def inc(self, amount=1):
        self._solo().inc(amount)

    def set(self, value):
        self._solo().set(value)

    def set_max(self, value):
        self._solo().set_max(value)

    def dec(self, amount=1):
        self._solo().dec(amount)

    def observe(self, value):
        self._solo().observe(value)

    @property
    def value(self):
        return self._solo().value

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for key, child in sorted(self.children().items()):
            suffix = _labels_suffix(self.labelnames, key)
            if self.kind == "histogram":
                cum = child.cumulative()
                base = list(zip(self.labelnames, key))
                for edge, count in cum:
                    le = ",".join(
                        [f'{k}="{_escape_label(v)}"' for k, v in base]
                        + [f'le="{_fmt_value(float(edge))}"']
                    )
                    lines.append(f"{self.name}_bucket{{{le}}} {count}")
                inf = ",".join(
                    [f'{k}="{_escape_label(v)}"' for k, v in base]
                    + ['le="+Inf"']
                )
                lines.append(f"{self.name}_bucket{{{inf}}} {child.count}")
                lines.append(
                    f"{self.name}_sum{suffix} {_fmt_value(child.total_s)}"
                )
                lines.append(f"{self.name}_count{suffix} {child.count}")
            else:
                lines.append(
                    f"{self.name}{suffix} {_fmt_value(child.value)}"
                )
        return "\n".join(lines)


class MetricBank:
    """Dict-style view over named registry cells.

    The serving engines' ``counters`` / ``pipe_counters`` dicts predate
    the registry and are read (and ``bank[k] += 1``-mutated) all over
    the engines, the bench harness, and the tests. A bank keeps that
    exact surface — ``bank["queries"] += 1``, ``dict(bank)``,
    ``bank["queries"]`` — while every value lives in a registry cell,
    so ``stats()`` dicts ARE registry snapshots and ``/metrics`` sees
    the same numbers. Cells are created once at component construction;
    the bank itself never allocates afterwards."""

    __slots__ = ("_cells",)

    def __init__(self, cells: dict):
        self._cells = dict(cells)

    def __getitem__(self, key):
        return self._cells[key].value

    def __setitem__(self, key, value):
        self._cells[key].set(value)

    def __contains__(self, key):
        return key in self._cells

    def __iter__(self):
        return iter(self._cells)

    def __len__(self):
        return len(self._cells)

    def keys(self):
        return self._cells.keys()

    def items(self):
        return [(k, c.value) for k, c in self._cells.items()]

    def inc(self, key, amount=1):
        """Atomic increment (the read-modify-write ``bank[k] += 1`` form
        is kept for call-site compatibility but takes two cell locks)."""
        self._cells[key].inc(amount)

    def cell(self, key):
        return self._cells[key]


class MetricsRegistry:
    """Named metric families, one namespace per process.

    ``counter/gauge/histogram`` are get-or-create and idempotent: the
    serving layer's components all ask for the same family names and
    share them; asking again with a different kind or label set is a
    bug and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, MetricFamily] = {}
        self._collectors: list = []
        # bibfs_build_info: minted at registry init so EVERY /metrics
        # render identifies its build (a fleet of replicas mid-rolling-
        # restart is exactly when "which build is this node" matters).
        # Prometheus convention: value is always 1, the labels carry
        # the identity — join other series against it by instance.
        try:
            fields = build_info_fields()
            self.gauge(
                "bibfs_build_info",
                "Build identity of this process (value is always 1; "
                "labels carry the bench_*.json meta fields)",
                tuple(sorted(fields)),
            ).labels(**fields).set(1)
        except Exception:
            pass  # provenance must never break metrics

    def add_collector(self, fn) -> None:
        """Register a render-time hook: ``fn()`` runs at the top of
        every :meth:`render` (and :meth:`snapshot`), refreshing gauges
        whose truth is computed on demand rather than event-driven —
        the health state machine's ``bibfs_health_state`` is the
        motivating case (breaker windows elapse and error windows age
        out with NO event; a /metrics-only scraper must still see the
        current state). A hook that returns ``False`` is UNREGISTERED —
        how weakly-bound hooks prune themselves once their component is
        gone, so engine-churning processes don't accumulate dead hooks
        on every scrape. Hook failures are swallowed: a broken
        collector must not take down the scrape that would reveal
        it."""
        with self._lock:
            self._collectors.append(fn)

    def _collect(self) -> None:
        with self._lock:
            hooks = list(self._collectors)
        dead = []
        for fn in hooks:
            try:
                if fn() is False:
                    dead.append(fn)
            except Exception:
                pass
        if dead:
            with self._lock:
                self._collectors = [
                    f for f in self._collectors if f not in dead
                ]

    def _get_or_create(self, name, help, kind, labelnames):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered as {kind}"
                        f"{tuple(labelnames)} (was {fam.kind}"
                        f"{fam.labelnames})"
                    )
                return fam
            fam = MetricFamily(name, help, kind, labelnames)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labelnames=()) -> MetricFamily:
        return self._get_or_create(name, help, "counter", labelnames)

    def gauge(self, name, help="", labelnames=()) -> MetricFamily:
        return self._get_or_create(name, help, "gauge", labelnames)

    def histogram(self, name, help="", labelnames=()) -> MetricFamily:
        return self._get_or_create(name, help, "histogram", labelnames)

    def get(self, name) -> MetricFamily | None:
        with self._lock:
            return self._families.get(name)

    def families(self) -> list:
        with self._lock:
            return list(self._families.values())

    def child_count(self) -> int:
        """Total labeled cells across every family — the allocation
        meter the disabled-telemetry overhead test pins (queries must
        not mint registry objects)."""
        return sum(len(f.children()) for f in self.families())

    def render(self) -> str:
        """The whole registry in Prometheus text exposition format
        (version 0.0.4) — the ``/metrics`` payload."""
        self._collect()
        out = [f.render() for f in sorted(
            self.families(), key=lambda f: f.name
        )]
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> dict:
        """JSON-friendly dump: {family: {label_tuple_str: value}} for
        counters/gauges, histogram summaries for histograms."""
        self._collect()
        snap = {}
        for fam in self.families():
            entry = {}
            for key, child in fam.children().items():
                label = ",".join(
                    f"{k}={v}" for k, v in zip(fam.labelnames, key)
                )
                entry[label] = (
                    child.summary_ms() if fam.kind == "histogram"
                    else child.value
                )
            snap[fam.name] = entry
        return snap


#: the process-wide default registry every serving component lands in
REGISTRY = MetricsRegistry()
