"""Per-level solver telemetry — the BFS-phase diagnostic record.

BFS work is famously phase-structured: per-level frontier sizes and
edge scans are the signal every scalable-BFS analysis leans on
(ScalaBFS's per-level pipeline utilization, "Compression and Sieve"'s
per-level communication accounting), yet :class:`BFSResult` only
carried aggregate ``levels``/``edges_scanned``. This module is the
opt-in ``telemetry=`` hook the dense/serial/native solvers accept: when
passed, each expansion round records its side, direction (push/pull),
post-expansion frontier size and edges scanned, plus the round at which
the best meet candidate was found, onto
``BFSResult.level_stats`` — and when NOT passed (the default), the hot
paths run the exact pre-telemetry code (results bit-identical, no
allocation per query).

``level_stats`` shape::

    {"levels": [{"level": 1, "side": "s", "dir": "pull",
                 "frontier": 412, "edges": 3310}, ...],
     "meet_level": 5, "meet": 1234}

``level`` is the solver's global round index (1-based); ``side`` is
"s"/"t"; ``dir`` is "push" or "pull" (serial/native frontier-driven
expansion is push-shaped by construction; the dense solver reports its
Beamer gate's actual choice).
"""

from __future__ import annotations

_FRACTION_HIST = None


def frontier_fraction_hist():
    """The process-wide ``bibfs_level_frontier_fraction`` histogram:
    per-level frontier size as a fraction of ``n``, fed by every
    telemetry-enabled solve that knows its graph size (``n`` set on the
    collector). The adaptive routing layer (``serve/policy.py``) mints
    it at construction so it renders at zero; solves that record into
    it share the same cell."""
    global _FRACTION_HIST
    if _FRACTION_HIST is None:
        from bibfs_tpu.obs.metrics import REGISTRY

        _FRACTION_HIST = REGISTRY.histogram(
            "bibfs_level_frontier_fraction",
            "Per-level frontier size / n of telemetry-enabled solves "
            "(the push/pull and route-shape signal the adaptive "
            "routing policy learns from)",
        )
    return _FRACTION_HIST


class LevelTelemetry:
    """Collector one solve fills. Pass an instance (or ``telemetry=True``,
    which the solvers turn into one) to ``solve_serial_csr`` /
    ``solve_native_graph`` / ``solve_dense_graph`` / ``api.solve``.

    ``n`` (the solved graph's vertex count; the solvers re-stamp it at
    every solve, so a collector reused across graphs records each
    solve against the RIGHT n) additionally lands each level's
    frontier/n in the process ``bibfs_level_frontier_fraction``
    histogram — the observable shape signal
    ``serve/policy.AdaptiveRouter`` learns push/pull behavior from.
    Pass ``n=0`` to opt out of the registry traffic entirely (the
    solvers never overwrite 0): levels then record exactly as before
    this histogram existed."""

    __slots__ = ("levels", "meet_level", "meet", "n")

    def __init__(self, n: int | None = None):
        self.levels: list[dict] = []
        self.meet_level: int | None = None
        self.meet: int | None = None
        self.n = n

    def record_level(
        self, level: int, side: str, direction: str,
        frontier: int, edges: int,
    ) -> None:
        self.levels.append({
            "level": int(level),
            "side": side,
            "dir": direction,
            "frontier": int(frontier),
            "edges": int(edges),
        })
        if self.n:
            frontier_fraction_hist().observe(frontier / self.n)

    def note_meet(self, level: int, meet: int | None = None) -> None:
        """Record the round where the best meet candidate (so far) was
        found; later improvements overwrite — the final value is the
        round that produced the answer's meet vertex."""
        self.meet_level = int(level)
        if meet is not None:
            self.meet = int(meet)

    def as_dict(self) -> dict:
        return {
            "levels": self.levels,
            "meet_level": self.meet_level,
            "meet": self.meet,
        }


def coerce(telemetry) -> "LevelTelemetry | None":
    """The solvers' shared argument handling: ``None``/falsy -> None
    (telemetry fully off), ``True`` -> a fresh collector, an existing
    collector passes through."""
    if not telemetry:
        return None
    if telemetry is True:
        return LevelTelemetry()
    return telemetry
