"""Tiny stdlib HTTP endpoint serving the metrics registry.

``bibfs-serve --metrics-port N`` starts this next to the engine:
``GET /metrics`` renders :data:`bibfs_tpu.obs.metrics.REGISTRY` in
Prometheus text exposition format (content type
``text/plain; version=0.0.4``), ``GET /healthz`` answers ``ok`` — the
two endpoints a scraper and a liveness probe need, and nothing else.

Stdlib only (``http.server`` on a daemon thread), by design: the
serving process must not grow a web-framework dependency to be
observable, and a ThreadingHTTPServer is plenty for scrape traffic
(one request per Prometheus interval). Port 0 binds an ephemeral port;
the chosen one is on ``server.port`` (and in the startup line the CLI
prints), which is what the CI endpoint probe parses.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bibfs_tpu.obs.metrics import REGISTRY, MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _make_handler(registry: MetricsRegistry):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                body = b"ok\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def log_message(self, *a):  # scrapes must not spam stderr
            pass

    return Handler


class MetricsServer:
    """A running ``/metrics`` endpoint; ``close()`` tears it down."""

    def __init__(
        self,
        port: int = 0,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
    ):
        registry = REGISTRY if registry is None else registry
        self._httpd = ThreadingHTTPServer(
            (host, int(port)), _make_handler(registry)
        )
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="bibfs-metrics-http",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_metrics_server(
    port: int = 0,
    registry: MetricsRegistry | None = None,
    host: str = "127.0.0.1",
) -> MetricsServer:
    """Start serving ``registry`` (default: the process-wide one) on
    ``host:port`` (port 0 = ephemeral); returns the running server."""
    return MetricsServer(port=port, registry=registry, host=host)
