"""Tiny stdlib HTTP endpoint serving the metrics registry + health.

``bibfs-serve --metrics-port N`` starts this next to the engine:
``GET /metrics`` renders :data:`bibfs_tpu.obs.metrics.REGISTRY` in
Prometheus text exposition format (content type
``text/plain; version=0.0.4``); ``GET /healthz`` answers from the
engine's health state machine
(:class:`bibfs_tpu.serve.resilience.HealthMonitor`) once one is
attached via :meth:`MetricsServer.set_health`:

- ``ready`` — 200, body ``ok``;
- ``degraded`` — 200, body ``degraded <reasons>`` (the node still
  SERVES; a load balancer must not eject an answering node);
- ``live`` / ``draining`` — 503 (not ready: do not route traffic);
- no health callback attached (standalone registry server, or the
  window before the engine finishes constructing) — 200 ``ok``, the
  pre-resilience behavior.

The body's first token is always the state; the JSON detail (breaker
state, recent errors, queue depth) follows on the next line for humans
and probes that want the why.

Stdlib only (``http.server`` on a daemon thread), by design: the
serving process must not grow a web-framework dependency to be
observable, and a ThreadingHTTPServer is plenty for scrape traffic
(one request per Prometheus interval). Port 0 binds an ephemeral port;
the chosen one is on ``server.port`` (and in the startup line the CLI
prints), which is what the CI endpoint probe parses.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bibfs_tpu.obs.metrics import REGISTRY, MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _health_body(health_cb) -> tuple[int, bytes]:
    """(status, body) for /healthz. A crashing health callback is
    itself a health signal: 503, not a traceback through the scrape."""
    if health_cb is None:
        return 200, b"ok\n"
    try:
        snap = health_cb()
        state = snap.get("state", "live")
    except Exception as e:  # pragma: no cover - defensive
        return 503, f"error {type(e).__name__}: {e}\n".encode()
    from bibfs_tpu.serve.resilience import healthz_status

    status = healthz_status(state)
    head = "ok" if state == "ready" else state
    reasons = snap.get("reasons") or []
    if reasons:
        head += " " + "; ".join(str(r) for r in reasons)
    body = head + "\n" + json.dumps(snap, sort_keys=True, default=str) + "\n"
    return status, body.encode()


def _make_handler(registry: MetricsRegistry, server: "MetricsServer"):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                status, body = _health_body(server._health_cb)
                self.send_response(status)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def log_message(self, *a):  # scrapes must not spam stderr
            pass

    return Handler


class MetricsServer:
    """A running ``/metrics`` + ``/healthz`` endpoint; ``close()``
    tears it down. ``health`` (or a later :meth:`set_health`) attaches
    the engine's health snapshot callable — the CLI builds the server
    BEFORE the engine so the scrape endpoint exists during engine
    construction; until the callback lands, ``/healthz`` answers the
    standalone 200 ``ok``."""

    def __init__(
        self,
        port: int = 0,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
        health=None,
    ):
        registry = REGISTRY if registry is None else registry
        self._health_cb = health
        self._httpd = ThreadingHTTPServer(
            (host, int(port)), _make_handler(registry, self)
        )
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="bibfs-metrics-http",
            daemon=True,
        )
        self._thread.start()

    def set_health(self, health_cb) -> None:
        """Attach (or replace) the health callback ``/healthz`` asks —
        typically ``engine.health_snapshot``. ``None`` detaches (back
        to the standalone 200 ``ok``)."""
        self._health_cb = health_cb

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    @property
    def health_url(self) -> str:
        return f"http://{self.host}:{self.port}/healthz"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_metrics_server(
    port: int = 0,
    registry: MetricsRegistry | None = None,
    host: str = "127.0.0.1",
    health=None,
) -> MetricsServer:
    """Start serving ``registry`` (default: the process-wide one) on
    ``host:port`` (port 0 = ephemeral); returns the running server.
    ``health`` optionally wires ``/healthz`` to an engine's
    ``health_snapshot`` (attachable later via ``set_health``)."""
    return MetricsServer(port=port, registry=registry, host=host,
                         health=health)
