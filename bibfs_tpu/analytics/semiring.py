"""The semiring seam: one CSR product, four whole-graph analytics.

A BFS level is ``next = A (x) frontier`` over the boolean (OR, AND)
semiring. Generalize the (add, mul) pair and the SAME sparse product
computes the classical whole-graph kinds:

============  ==================  =========================  =========
semiring      (add, mul, zero)    fixpoint / iteration       kind
============  ==================  =========================  =========
``min_plus``  (min, +, +inf)      Bellman relaxation sweeps  sssp
``plus_times``(+, x, 0)           damped power iteration     pagerank
``min_label`` (min, select, inf)  label propagation          components
``bool_count``(+, x, 0)           masked popcount matmul     triangles
============  ==================  =========================  =========

:func:`csr_semiring_matvec` is the host-tier product every host rung
iterates; the blocked device rungs run the identical recurrences over
the tiled tables (:mod:`bibfs_tpu.ops.semiring_plane`) so host and
blocked answers agree element-for-element (integer-valued data stays
exact in f32 below 2^24 — the device gates enforce that bound).

The ``ref_*`` functions are the INDEPENDENT implementations the bench
gates and property tests pin each kind against: binary-heap Dijkstra
(:func:`bibfs_tpu.query.weighted.dijkstra_numpy`), dense-matrix power
iteration, union-find, and adjacency-intersection triangle counting —
none of them share the semiring product above.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: label value for "no label yet" in the min-label semiring (any real
#: vertex id wins the min against it)
_LABEL_INF = np.iinfo(np.int64).max


@dataclasses.dataclass(frozen=True)
class Semiring:
    """One (add, mul, zero, dtype) algebra over the CSR product.

    ``add`` is a NumPy ufunc (its unbuffered ``.at`` is the scatter-
    reduce); ``mul`` combines the gathered plane rows ``[E, C]`` with
    the per-edge values ``[E]`` (or None for unweighted semirings);
    ``zero`` is ``add``'s identity, the empty-neighborhood answer.
    """

    name: str
    add: np.ufunc
    mul: object  # callable(gathered [E, C], edge_vals [E] | None) -> [E, C]
    zero: float
    dtype: np.dtype


def _mul_plus(gathered, edge_vals):
    if edge_vals is None:
        return gathered
    return gathered + edge_vals[:, None]


def _mul_times(gathered, edge_vals):
    if edge_vals is None:
        return gathered
    return gathered * edge_vals[:, None]


def _mul_select(gathered, edge_vals):
    return gathered


SEMIRINGS = {
    "min_plus": Semiring(
        "min_plus", np.minimum, _mul_plus, np.inf, np.dtype(np.float64)
    ),
    "plus_times": Semiring(
        "plus_times", np.add, _mul_times, 0.0, np.dtype(np.float64)
    ),
    "min_label": Semiring(
        "min_label", np.minimum, _mul_select, _LABEL_INF,
        np.dtype(np.int64),
    ),
    "bool_count": Semiring(
        "bool_count", np.add, _mul_times, 0, np.dtype(np.int64)
    ),
}


def csr_semiring_matvec(n, row_ptr, col_ind, plane, sr: Semiring,
                        edge_vals=None):
    """``out[u] = add-reduce over edges (u, v) of mul(plane[v], w_uv)``
    — ONE vectorized gather + unbuffered scatter-reduce, no Python
    per-edge loop. ``plane`` is ``[n, C]`` (or ``[n]``, returned in
    kind); empty neighborhoods answer ``sr.zero``."""
    plane = np.asarray(plane)
    squeeze = plane.ndim == 1
    if squeeze:
        plane = plane[:, None]
    out = np.full((n, plane.shape[1]), sr.zero, dtype=plane.dtype)
    if n and col_ind.size:
        src = np.repeat(
            np.arange(n, dtype=np.int64), np.diff(row_ptr).astype(np.int64)
        )
        contrib = sr.mul(plane[col_ind], edge_vals)
        sr.add.at(out, src, contrib)
    return out[:, 0] if squeeze else out


# ---- host rungs: semiring iteration to fixpoint/tolerance ------------
def host_sssp(n, row_ptr, col_ind, weights, sources):
    """Multi-source (min, +) Bellman sweeps to fixpoint: one distance
    column per source (the all-pairs-to-landmarks shape). Returns
    ``(dist [n, C] float64, rounds)`` — exact for any non-negative
    weights (each sweep settles at least one more hop tier)."""
    sources = [int(s) for s in sources]
    sr = SEMIRINGS["min_plus"]
    dist = np.full((n, len(sources)), np.inf, dtype=np.float64)
    for i, s in enumerate(sources):
        dist[s, i] = 0.0
    rounds = 0
    while rounds < max(1, n):
        cand = csr_semiring_matvec(
            n, row_ptr, col_ind, dist, sr, edge_vals=weights
        )
        new = np.minimum(dist, cand)
        rounds += 1
        if np.array_equal(new, dist):
            break
        dist = new
    return dist, rounds


def host_pagerank(n, row_ptr, col_ind, *, damping=0.85, tol=1e-8,
                  max_iters=100):
    """Damped PageRank by (+, x) power iteration over the CSR, dangling
    mass redistributed uniformly, L1-delta tolerance termination.
    Returns ``(ranks [n] float64, iters, delta)``; ranks sum to 1."""
    if n == 0:
        return np.zeros(0, dtype=np.float64), 0, 0.0
    sr = SEMIRINGS["plus_times"]
    deg = np.diff(row_ptr).astype(np.float64)
    dangling = deg == 0
    r = np.full(n, 1.0 / n, dtype=np.float64)
    it, delta = 0, np.inf
    while it < max(1, int(max_iters)):
        contrib = np.where(dangling, 0.0, r / np.maximum(deg, 1.0))
        y = csr_semiring_matvec(n, row_ptr, col_ind, contrib, sr)
        mass = float(r[dangling].sum())
        rn = (1.0 - damping) / n + damping * (y + mass / n)
        delta = float(np.abs(rn - r).sum())
        r = rn
        it += 1
        if delta <= tol:
            break
    return r, it, delta


def host_components(n, row_ptr, col_ind):
    """Connected components by min-label propagation to fixpoint:
    every vertex converges to the smallest vertex id in its component.
    Returns ``(labels [n] int64, count, rounds)``."""
    sr = SEMIRINGS["min_label"]
    labels = np.arange(n, dtype=np.int64)
    rounds = 0
    while rounds < max(1, n):
        cand = csr_semiring_matvec(n, row_ptr, col_ind, labels, sr)
        new = np.minimum(labels, cand)
        rounds += 1
        if np.array_equal(new, labels):
            break
        labels = new
    count = int(np.unique(labels).size) if n else 0
    return labels, count, rounds


def host_triangles(n, row_ptr, col_ind, *, chunk=None):
    """Triangle count by the masked popcount matmul: per column chunk
    ``P`` of the adjacency, ``sum((A @ P) * P)`` counts each triangle
    once per ordered adjacent (u, j) pair — six times total. Returns
    ``(count, chunks)``."""
    sr = SEMIRINGS["bool_count"]
    e = int(col_ind.size)
    if chunk is None:
        # bound the gathered [E, C] scatter temp to ~2^24 elements
        chunk = max(16, min(1024, (1 << 24) // max(1, e)))
    src = np.repeat(
        np.arange(n, dtype=np.int64), np.diff(row_ptr).astype(np.int64)
    ) if n else np.zeros(0, dtype=np.int64)
    total = 0
    chunks = 0
    for c0 in range(0, n, chunk):
        c1 = min(n, c0 + chunk)
        plane = np.zeros((n, c1 - c0), dtype=np.int64)
        in_cols = (col_ind >= c0) & (col_ind < c1)
        plane[src[in_cols], col_ind[in_cols] - c0] = 1
        y = csr_semiring_matvec(n, row_ptr, col_ind, plane, sr)
        total += int((y * plane).sum())
        chunks += 1
    return total // 6, chunks


# ---- independent references (NOT the semiring product above) ---------
def ref_pagerank_dense(n, row_ptr, col_ind, *, damping=0.85, tol=1e-8,
                       max_iters=100):
    """Dense-matrix power iteration — the NumPy reference the semiring
    rungs are verified against (same math, disjoint machinery: an
    explicit ``[n, n]`` column-stochastic matmul per step)."""
    if n == 0:
        return np.zeros(0, dtype=np.float64)
    a = np.zeros((n, n), dtype=np.float64)
    src = np.repeat(
        np.arange(n, dtype=np.int64), np.diff(row_ptr).astype(np.int64)
    )
    a[src, col_ind] = 1.0
    deg = a.sum(axis=0)
    m = np.divide(a, deg, out=np.zeros_like(a), where=deg > 0)
    dangling = deg == 0
    r = np.full(n, 1.0 / n)
    for _ in range(max(1, int(max_iters))):
        rn = (1.0 - damping) / n + damping * (
            m @ r + float(r[dangling].sum()) / n
        )
        if float(np.abs(rn - r).sum()) <= tol:
            return rn
        r = rn
    return r


def ref_components_unionfind(n, pairs):
    """Union-find over the edge list — the components reference.
    Returns ``(labels [n] int64, count)`` with each class labeled by
    its smallest member (the min-label convention)."""
    parent = np.arange(n, dtype=np.int64)

    def find(x):
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    if pairs is not None:
        for u, v in np.asarray(pairs, dtype=np.int64).reshape(-1, 2):
            ru, rv = find(int(u)), find(int(v))
            if ru != rv:
                # union by min id keeps the canonical label the root
                if ru < rv:
                    parent[rv] = ru
                else:
                    parent[ru] = rv
    labels = np.fromiter(
        (find(i) for i in range(n)), dtype=np.int64, count=n
    )
    return labels, (int(np.unique(labels).size) if n else 0)


def ref_triangles_intersect(n, row_ptr, col_ind):
    """Exact triangle count by per-edge sorted-adjacency intersection:
    ``sum over undirected edges (u, v) of |N(u) & N(v)|`` counts each
    triangle three times. No matmul anywhere — the independent pin."""
    total = 0
    for u in range(n):
        nu = col_ind[row_ptr[u]: row_ptr[u + 1]]
        for v in nu[nu > u]:
            nv = col_ind[row_ptr[v]: row_ptr[v + 1]]
            total += int(np.intersect1d(nu, nv, assume_unique=True).size)
    return total // 3
