"""The per-digest whole-graph result store — analytics answers that
outlive their flush.

Point queries are cheap to recompute; whole-graph vectors (ranks,
component labels, distance columns) are not. This store keeps them:

- **keyed by snapshot digest**: an entry is the answer for one
  ``(graph, query.cache_key())`` against ONE settled snapshot — the
  same no-aliasing argument as the distance cache and kind cache;
- **persisted as sidecar arrays** next to the durable checkpoints
  (``<wal_dir>/analytics/``): each entry commits as a fresh directory
  (``.npy`` per vector + ``meta.json``) renamed into place — the
  rename-last discipline of ``store/sidecar.py`` — and recovers after
  respawn by ``np.load(mmap_mode='r')``, the PR 16 memory-tier move;
- **delta-aware**: the graph store feeds it the digest lineage —
  ``note_update`` (pending overlay deltas), ``note_fold`` (overlay
  compacted into a new digest), ``note_swap`` (wholesale replacement).
  A stored entry whose digest is an ADDS-ONLY ancestor of the current
  digest is **incrementally maintained** instead of recomputed
  (:func:`maintain_sssp` decrease-only relaxation,
  :func:`maintain_components` label re-merge); deletes, swaps, or
  value-global kinds (pagerank, triangles: one new edge moves every
  entry) invalidate.

Locking: one leaf lock over the in-memory index. Every file
open/rename/remove happens OUTSIDE ``self._lock`` (the ``lock-io``
rule) — persists build a complete tmp directory first and publish it
with one ``os.rename``; deletions are deferred to a sweep at the next
store call. The graph store calls the ``note_*`` hooks from its own
locked commits, which is safe because this lock is a leaf: nothing
here calls back into the graph store.

Metrics (README "Analytics tier"):
``bibfs_analytics_store_events_total{store,event}`` (``hit`` /
``miss`` / ``put`` / ``incremental`` / ``invalidated`` / ``load`` /
``evict`` — all cells minted at construction) and
``bibfs_analytics_store_entries{store}``.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
from collections import OrderedDict

import numpy as np

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.metrics import REGISTRY

#: kinds whose stored vectors are maintainable across adds-only deltas
MAINTAINABLE_KINDS = frozenset({"sssp", "components"})

#: the store event vocabulary, minted at construction so the whole
#: family renders at zero before the first analytics query
STORE_EVENTS = (
    "hit", "miss", "put", "incremental", "invalidated", "load", "evict",
)


def _hash_token(value) -> str:
    return hashlib.sha1(repr(value).encode("utf-8")).hexdigest()[:16]


class _Entry:
    """One stored whole-graph answer (arrays mmap-lazy when the entry
    was recovered from disk)."""

    __slots__ = ("digest", "kind", "key", "arrays", "scalars", "path")

    def __init__(self, *, digest, kind, key, arrays, scalars, path):
        self.digest = digest
        self.kind = kind
        self.key = key
        self.arrays = arrays  # dict name -> ndarray, or None until load
        self.scalars = scalars
        self.path = path


class _GraphLog:
    """One graph's digest lineage + entries. ``head`` is the last
    settled digest the graph store told us about; ``segments`` are the
    recorded ``from -> to`` transitions (``adds`` is the int64 [k, 2]
    edge batch, or None for a non-adds-only barrier)."""

    __slots__ = (
        "head", "segments", "entries",
        "pending_adds", "pending_dels", "pending_count",
    )

    def __init__(self):
        self.head = None
        self.segments: list = []
        self.entries: OrderedDict = OrderedDict()
        self.pending_adds: list = []
        self.pending_dels = False
        self.pending_count = 0


@guarded_by("_lock", "_graphs", "_scanned", "_dead")
class AnalyticsResultStore:
    """Module docstring. ``root=None`` is the memory-only store (a
    non-durable graph store): same serving/maintenance semantics,
    nothing survives the process."""

    MAX_SEGMENTS = 8
    MAX_PENDING_EDGES = 4096
    MAX_ENTRIES_PER_GRAPH = 32

    def __init__(self, root=None, *, store_label: str = "store"):
        self._root = None if root is None else os.fspath(root)
        self._lock = threading.Lock()
        self._graphs: dict[str, _GraphLog] = {}
        self._scanned: set = set()
        self._dead: list = []
        events = REGISTRY.counter(
            "bibfs_analytics_store_events_total",
            "Whole-graph analytics result store events (hit/miss/put/"
            "incremental/invalidated/load/evict)",
            ("store", "event"),
        )
        self._events = {
            e: events.labels(store=store_label, event=e)
            for e in STORE_EVENTS
        }
        self._g_entries = REGISTRY.gauge(
            "bibfs_analytics_store_entries",
            "Whole-graph analytics results currently stored",
            ("store",),
        ).labels(store=store_label)

    # ---- digest-lineage hooks (called by the graph store) -----------
    def note_register(self, name: str, digest) -> None:
        """A graph registered/recovered at ``digest`` — the lineage
        origin."""
        with self._lock:
            g = self._graphs.setdefault(name, _GraphLog())
            if g.head is None:
                g.head = digest

    def note_update(self, name: str, adds, dels) -> None:
        """An acked overlay delta batch (pre-fold). Cheap append only —
        this runs inside the graph store's locked commit."""
        with self._lock:
            g = self._graphs.setdefault(name, _GraphLog())
            if dels is not None and len(dels):
                g.pending_dels = True
            if adds is not None and len(adds):
                batch = np.asarray(adds, dtype=np.int64).reshape(-1, 2)
                g.pending_count += int(batch.shape[0])
                if g.pending_count <= self.MAX_PENDING_EDGES:
                    g.pending_adds.append(batch)

    def note_fold(self, name: str, new_digest, *, clean: bool) -> None:
        """The overlay compacted into a fresh snapshot: record the
        ``head -> new_digest`` transition. ``clean=False`` (rebase
        residue left behind) or pending deletes/overflow make it a
        barrier — entries behind it invalidate instead of maintaining."""
        with self._lock:
            g = self._graphs.setdefault(name, _GraphLog())
            adds_only = (
                clean and g.head is not None and not g.pending_dels
                and g.pending_count <= self.MAX_PENDING_EDGES
            )
            adds = None
            if adds_only:
                adds = (
                    np.concatenate(g.pending_adds)
                    if g.pending_adds
                    else np.zeros((0, 2), dtype=np.int64)
                )
            g.segments.append((g.head, new_digest, adds))
            del g.segments[: -self.MAX_SEGMENTS]
            g.head = new_digest
            g.pending_adds = []
            g.pending_dels = False
            g.pending_count = 0

    def note_swap(self, name: str, new_digest) -> None:
        """A wholesale snapshot replacement: every stored entry for the
        graph is stale with no maintainable lineage."""
        with self._lock:
            g = self._graphs.setdefault(name, _GraphLog())
            n_dead = len(g.entries)
            for e in g.entries.values():
                if e.path is not None:
                    self._dead.append(e.path)
            g.entries.clear()
            g.segments.clear()
            g.head = new_digest
            g.pending_adds = []
            g.pending_dels = False
            g.pending_count = 0
            if n_dead:
                self._events["invalidated"].inc(n_dead)
            self._refresh_entries_locked()

    def purge(self, name: str) -> None:
        """The graph left the store entirely."""
        with self._lock:
            g = self._graphs.pop(name, None)
            self._scanned.discard(name)
            if g is not None:
                for e in g.entries.values():
                    if e.path is not None:
                        self._dead.append(e.path)
                self._refresh_entries_locked()
        self._sweep()

    # ---- serving path ------------------------------------------------
    def lookup(self, name: str, key, digest):
        """The engine-seam consult. Returns ``("hit", entry)`` for an
        exact-digest answer, ``("maintain", entry, adds)`` when the
        entry's digest reaches ``digest`` through adds-only segments
        (``adds`` is the concatenated int64 [k, 2] batch — possibly
        empty — and the caller owns running the maintenance and
        committing it back), or None."""
        self._ensure_scanned(name)
        self._sweep()
        key = _key_id(key)
        with self._lock:
            g = self._graphs.get(name)
            entry = None if g is None else g.entries.get(key)
            if entry is None:
                self._events["miss"].inc()
                return None
            g.entries.move_to_end(key)
            if entry.digest == digest:
                self._load_locked(entry)
                if entry.arrays is None:
                    return self._drop_locked(g, key, entry)
                self._events["hit"].inc()
                return ("hit", entry)
            chain = self._chain_locked(g, entry.digest, digest)
            if chain is None or entry.kind not in MAINTAINABLE_KINDS:
                if chain is not None and not chain.shape[0]:
                    # no-op transitions: the answer is unchanged for
                    # EVERY kind — retag in place and serve
                    entry.digest = digest
                    self._load_locked(entry)
                    if entry.arrays is None:
                        return self._drop_locked(g, key, entry)
                    self._events["hit"].inc()
                    return ("hit", entry)
                return self._drop_locked(g, key, entry)
            self._load_locked(entry)
            if entry.arrays is None:
                return self._drop_locked(g, key, entry)
            return ("maintain", entry, chain)

    def put(self, name: str, key, digest, kind, arrays: dict,
            scalars: dict, *, event: str = "put") -> None:
        """Store (and persist) one computed whole-graph answer."""
        self._sweep()
        key = _key_id(key)
        path = self._persist(name, key, digest, kind, arrays, scalars)
        with self._lock:
            g = self._graphs.setdefault(name, _GraphLog())
            old = g.entries.pop(key, None)
            if old is not None and old.path and old.path != path:
                self._dead.append(old.path)
            g.entries[key] = _Entry(
                digest=digest, kind=kind, key=key,
                arrays=dict(arrays), scalars=dict(scalars), path=path,
            )
            g.entries.move_to_end(key)
            self._events[event].inc()
            while len(g.entries) > self.MAX_ENTRIES_PER_GRAPH:
                _k, ev = g.entries.popitem(last=False)
                if ev.path is not None:
                    self._dead.append(ev.path)
                self._events["evict"].inc()
            self._refresh_entries_locked()
        self._sweep()

    def commit_maintained(self, name: str, key, digest, kind,
                          arrays: dict, scalars: dict) -> None:
        """The caller ran the incremental maintenance — store the
        retagged answer (counted ``incremental``, the bench witness
        that no full recompute happened)."""
        self.put(name, key, digest, kind, arrays, scalars,
                 event="incremental")

    def stats(self) -> dict:
        with self._lock:
            return {
                "graphs": len(self._graphs),
                "entries": sum(
                    len(g.entries) for g in self._graphs.values()
                ),
                "segments": sum(
                    len(g.segments) for g in self._graphs.values()
                ),
                "durable": self._root is not None,
                # this store's slice of the event counters — the soak's
                # served-without-recompute witness
                "events": {
                    e: int(c.value) for e, c in self._events.items()
                },
            }

    # ---- lineage walk ------------------------------------------------
    def _chain_locked(self, g: _GraphLog, from_digest, to_digest):
        """The concatenated adds along ``from -> ... -> to``, or None
        when any hop is a barrier or the chain is broken."""
        hops = []
        cur = from_digest
        by_from = {s[0]: s for s in g.segments}
        seen = 0
        while cur != to_digest:
            seg = by_from.get(cur)
            seen += 1
            if seg is None or seg[2] is None or seen > len(g.segments):
                return None
            hops.append(seg[2])
            cur = seg[1]
        if not hops:
            return np.zeros((0, 2), dtype=np.int64)
        return np.concatenate(hops)

    def _drop_locked(self, g: _GraphLog, key, entry):
        g.entries.pop(key, None)
        if entry.path is not None:
            self._dead.append(entry.path)
        self._events["invalidated"].inc()
        self._events["miss"].inc()
        self._refresh_entries_locked()
        return None

    def _refresh_entries_locked(self):
        self._g_entries.set(sum(
            len(g.entries) for g in self._graphs.values()
        ))

    # ---- persistence -------------------------------------------------
    def _graph_dir(self, name: str) -> str:
        return os.path.join(self._root, _hash_token(name))

    def _persist(self, name, key, digest, kind, arrays, scalars):
        """Commit one entry directory: build complete under a tmp name,
        fsync, publish with ONE rename (rename-last, the sidecar
        discipline). Returns the published path (None on a memory-only
        store)."""
        if self._root is None:
            return None
        gdir = self._graph_dir(name)
        final = os.path.join(
            gdir, f"{_hash_token(key)}-{_hash_token(digest)}"
        )
        tmp = final + f".tmp-{os.getpid()}"
        os.makedirs(gdir, exist_ok=True)
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for arr_name, arr in arrays.items():
            np.save(os.path.join(tmp, f"{arr_name}.npy"),
                    np.ascontiguousarray(arr))
        meta = {
            "name": name, "kind": kind, "key": _key_id(key),
            "digest": str(digest), "scalars": dict(scalars),
            "arrays": sorted(arrays),
        }
        meta_path = os.path.join(tmp, "meta.json")
        with open(meta_path, "w", encoding="utf-8") as f:
            json.dump(meta, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        return final

    def _ensure_scanned(self, name: str) -> None:
        """Lazy respawn recovery: adopt any persisted entries for
        ``name`` the first time it is looked up (arrays stay on disk
        until served — the mmap move)."""
        if self._root is None:
            return
        with self._lock:
            if name in self._scanned:
                return
            self._scanned.add(name)
        gdir = self._graph_dir(name)
        found = []
        dead = []
        if os.path.isdir(gdir):
            for sub in sorted(os.listdir(gdir)):
                path = os.path.join(gdir, sub)
                if ".tmp-" in sub:
                    dead.append(path)
                    continue
                try:
                    with open(os.path.join(path, "meta.json"),
                              encoding="utf-8") as f:
                        meta = json.load(f)
                except (OSError, ValueError):
                    dead.append(path)
                    continue
                if meta.get("name") != name:
                    continue
                found.append((meta, path))
        if not found and not dead:
            return
        with self._lock:
            self._dead.extend(dead)
            g = self._graphs.setdefault(name, _GraphLog())
            for meta, path in found:
                key = meta["key"]  # repr string — matched via _key_id
                kid = _key_id(key)
                if kid in g.entries:
                    self._dead.append(path)
                    continue
                g.entries[kid] = _Entry(
                    digest=meta["digest"], kind=meta["kind"], key=kid,
                    arrays=None, scalars=dict(meta["scalars"]),
                    path=path,
                )
                self._events["load"].inc()
            self._refresh_entries_locked()

    def _load_locked(self, entry: _Entry) -> None:
        """Materialize a scanned entry's arrays as read-only mmaps.
        A torn/missing sidecar empties the entry (the caller drops
        it). np.load here is in-memory-index territory but read-only
        and rare (first touch after respawn)."""
        if entry.arrays is not None:
            return
        arrays = {}
        meta_path = os.path.join(entry.path, "meta.json")
        try:
            # read-only mmap adoption, once per entry per process
            # (first touch after respawn); off-lock it would race a
            # concurrent invalidation dropping the entry mid-load
            with open(meta_path, encoding="utf-8") as f:  # bibfs: allow(lock-io): rare read-only respawn adoption, racy off-lock
                meta = json.load(f)
            for arr_name in meta["arrays"]:
                arrays[arr_name] = np.load(
                    os.path.join(entry.path, f"{arr_name}.npy"),
                    mmap_mode="r",
                )
        except (OSError, ValueError):
            entry.arrays = None
            return
        entry.arrays = arrays

    def _sweep(self) -> None:
        """Drain deferred deletions (always outside ``self._lock``)."""
        with self._lock:
            dead, self._dead = self._dead, []
        for path in dead:
            shutil.rmtree(path, ignore_errors=True)


def _key_id(key):
    """The in-memory entry key for a cache key: the tuple itself from
    a live put, its ``repr`` from a disk scan — normalized so both
    address the same entry."""
    return key if isinstance(key, str) else repr(key)


# ---- result <-> stored payload ---------------------------------------
def result_to_payload(kind: str, res) -> tuple[dict, dict]:
    """Split a resolved analytics result into its storable halves:
    ``(arrays, scalars)`` — the vectors persist as ``.npy`` sidecars,
    the scalars ride ``meta.json``."""
    if kind == "sssp":
        return ({"dist": res.dist},
                {"found": bool(res.found), "reached": int(res.reached),
                 "rounds": int(res.rounds), "time_s": float(res.time_s)})
    if kind == "pagerank":
        return ({"ranks": res.ranks},
                {"found": bool(res.found), "iters": int(res.iters),
                 "delta": float(res.delta), "time_s": float(res.time_s)})
    if kind == "components":
        return ({"labels": res.labels},
                {"found": bool(res.found), "count": int(res.count),
                 "rounds": int(res.rounds), "time_s": float(res.time_s)})
    if kind == "triangles":
        return ({}, {"found": bool(res.found), "count": int(res.count),
                     "time_s": float(res.time_s)})
    raise ValueError(f"unknown analytics kind {kind!r}")


def result_from_payload(kind: str, arrays: dict, scalars: dict):
    """Rebuild the result object a stored entry serves (arrays may be
    read-only mmaps — the result types freeze them anyway)."""
    from bibfs_tpu.analytics.queries import (
        ComponentsResult,
        PageRankResult,
        SsspResult,
        TrianglesResult,
    )

    if kind == "sssp":
        return SsspResult(
            found=bool(scalars["found"]), dist=arrays["dist"],
            reached=int(scalars["reached"]),
            rounds=int(scalars["rounds"]),
            time_s=float(scalars["time_s"]),
        )
    if kind == "pagerank":
        return PageRankResult(
            found=bool(scalars["found"]), ranks=arrays["ranks"],
            iters=int(scalars["iters"]), delta=float(scalars["delta"]),
            time_s=float(scalars["time_s"]),
        )
    if kind == "components":
        return ComponentsResult(
            found=bool(scalars["found"]), labels=arrays["labels"],
            count=int(scalars["count"]), rounds=int(scalars["rounds"]),
            time_s=float(scalars["time_s"]),
        )
    if kind == "triangles":
        return TrianglesResult(
            found=bool(scalars["found"]), count=int(scalars["count"]),
            time_s=float(scalars["time_s"]),
        )
    raise ValueError(f"unknown analytics kind {kind!r}")


# ---- incremental maintenance (adds-only) -----------------------------
def maintain_sssp(dist_old, adds, n, row_ptr, col_ind, weights, seed):
    """Decrease-only relaxation for edge INSERTIONS: stored distances
    stay valid upper bounds, any improvement routes through a new
    edge — seed a Dijkstra-style worklist at the inserted endpoints
    and propagate over the current CSR. Exact, touches only the
    affected region. Returns ``(dist float64 [n], relaxed_count)``."""
    import heapq

    from bibfs_tpu.query.weighted import edge_weight_hash

    dist_old = np.asarray(dist_old, dtype=np.float64)
    d = np.full(n, np.inf, dtype=np.float64)
    d[: dist_old.size] = dist_old[:n]
    heap = []
    adds = np.asarray(adds, dtype=np.int64).reshape(-1, 2)
    if adds.shape[0]:
        w_new = edge_weight_hash(adds[:, 0], adds[:, 1], seed)
        for (u, v), w in zip(adds, w_new):
            for a, b in ((int(u), int(v)), (int(v), int(u))):
                if d[a] + w < d[b]:
                    d[b] = d[a] + w
                    heapq.heappush(heap, (d[b], b))
    relaxed = 0
    while heap:
        du, u = heapq.heappop(heap)
        if du > d[u]:
            continue
        relaxed += 1
        for i in range(row_ptr[u], row_ptr[u + 1]):
            v = int(col_ind[i])
            nd = du + weights[i]
            if nd < d[v]:
                d[v] = nd
                heapq.heappush(heap, (nd, v))
    return d, relaxed


def maintain_components(labels_old, adds, n):
    """Component re-merge for edge INSERTIONS: union the stored
    min-labels across each new edge (new vertices start as their own
    label), then remap every vertex to its class minimum — the exact
    min-label-propagation answer without touching the old edges.
    Returns ``(labels int64 [n], count)``."""
    labels_old = np.asarray(labels_old, dtype=np.int64)
    labels = np.arange(n, dtype=np.int64)
    labels[: labels_old.size] = labels_old[:n]
    parent: dict = {}

    def find(x):
        root = x
        while parent.get(root, root) != root:
            root = parent[root]
        while parent.get(x, x) != root:
            parent[x], x = root, parent[x]
        return root

    for u, v in np.asarray(adds, dtype=np.int64).reshape(-1, 2):
        ru, rv = find(int(labels[u])), find(int(labels[v]))
        if ru != rv:
            if ru < rv:
                parent[rv] = ru
            else:
                parent[ru] = rv
    if parent:
        uniq = np.unique(labels)
        remap = {int(x): find(int(x)) for x in uniq}
        labels = np.fromiter(
            (remap[int(x)] for x in labels), dtype=np.int64,
            count=labels.size,
        )
    count = int(np.unique(labels).size) if n else 0
    return labels, count
