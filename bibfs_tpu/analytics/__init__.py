"""Whole-graph semiring analytics over the blocked tile substrate.

BLEST and "Graph Traversal on Tensor Cores" (PAPERS.md) show the BFS
level is one instance of a family: swap the (OR, AND) semiring under
the SAME tiled adjacency product and the machinery computes whole-graph
analytics at MXU speed. This package is that seam made explicit:

- :mod:`bibfs_tpu.analytics.semiring` — the
  :class:`~bibfs_tpu.analytics.semiring.Semiring` abstraction, the CSR
  host kernels, and the INDEPENDENT references every kind is pinned to
  (Dijkstra / dense power iteration / union-find / adjacency-
  intersection triangle count);
- :mod:`bibfs_tpu.analytics.queries` — the typed whole-graph query
  kinds (``sssp`` / ``pagerank`` / ``components`` / ``triangles``)
  riding the PR 13/14 kind ladder;
- :mod:`bibfs_tpu.analytics.results` — the per-digest whole-graph
  result store (sidecar arrays next to durable checkpoints, served on
  repeat queries, incrementally maintained across adds-only deltas,
  mmap-recovered after respawn).

The device rungs live in :mod:`bibfs_tpu.ops.semiring_plane` (the
generalized ``blocked_expand``) and
:mod:`bibfs_tpu.serve.routes.analytics` (the ladder rungs).
"""

from __future__ import annotations

from bibfs_tpu.analytics.queries import (
    ANALYTICS_KINDS,
    Components,
    ComponentsResult,
    PageRank,
    PageRankResult,
    Sssp,
    SsspResult,
    Triangles,
    TrianglesResult,
    analytics_query_from_spec,
    analytics_summary,
)
from bibfs_tpu.analytics.semiring import SEMIRINGS, Semiring

__all__ = [
    "ANALYTICS_KINDS",
    "SEMIRINGS",
    "Semiring",
    "Sssp",
    "SsspResult",
    "PageRank",
    "PageRankResult",
    "Components",
    "ComponentsResult",
    "Triangles",
    "TrianglesResult",
    "analytics_query_from_spec",
    "analytics_summary",
]
