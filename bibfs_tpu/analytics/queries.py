"""The typed whole-graph analytics kinds — ``sssp`` / ``pagerank`` /
``components`` / ``triangles`` as peer members of the query taxonomy.

Each kind is a frozen :class:`~bibfs_tpu.query.types.Query` subclass
(same ``validate``/``cache_key`` contract, same engine dispatch) whose
answer is a WHOLE-GRAPH vector or scalar instead of one path:

- :class:`Sssp` — (min, +) single-source distances under the seeded
  symmetric edge-weight hash (``weight_seed``, the delta-stepping
  convention); a flush's same-seed sources batch into ONE multi-column
  plane (the all-pairs-to-landmarks shape).
- :class:`PageRank` — (+, x) damped power iteration with L1-tolerance
  termination.
- :class:`Components` — min-label propagation; every vertex converges
  to the smallest id in its component.
- :class:`Triangles` — the masked popcount matmul count.

``rep_pair()`` is the representative (src, dst) the engines use for
fault targeting and error reporting — whole-graph kinds have no (s, t)
of their own, so the source (or vertex 0) stands in.

Results carry their full vectors (read-only arrays — cached and
store-served objects are shared between tickets);
:func:`analytics_summary` is the one-line JSON shape the REPL / net
``analytics`` control op replies with.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from bibfs_tpu.query.types import Query, _check_node

#: the whole-graph kind taxonomy (``bibfs_query_total{kind=}`` values,
#: ladder names ``<kind>_blocked`` / ``<kind>`` / ``host``)
ANALYTICS_KINDS = ("sssp", "pagerank", "components", "triangles")


@dataclasses.dataclass(frozen=True)
class Sssp(Query):
    """Single-source shortest-path distances to EVERY vertex under the
    seeded symmetric weight hash (exact vs the Dijkstra oracle)."""

    source: int
    weight_seed: int = 0
    kind = "sssp"

    def validate(self, n: int) -> None:
        _check_node(self.source, n, "source")

    def cache_key(self) -> tuple:
        return ("sssp", int(self.source), int(self.weight_seed))

    def rep_pair(self) -> tuple:
        return (int(self.source), int(self.source))


@dataclasses.dataclass(frozen=True)
class PageRank(Query):
    """Damped PageRank with convergence-tolerance termination (verified
    vs dense NumPy power iteration)."""

    damping: float = 0.85
    tol: float = 1e-8
    max_iters: int = 100
    kind = "pagerank"

    def validate(self, n: int) -> None:
        if not 0.0 < float(self.damping) < 1.0:
            raise ValueError(
                f"damping must be in (0, 1), got {self.damping}"
            )
        if float(self.tol) <= 0.0:
            raise ValueError(f"tol must be > 0, got {self.tol}")
        if int(self.max_iters) < 1:
            raise ValueError(
                f"max_iters must be >= 1, got {self.max_iters}"
            )

    def cache_key(self) -> tuple:
        return ("pagerank", float(self.damping), float(self.tol),
                int(self.max_iters))

    def rep_pair(self) -> tuple:
        return (0, 0)


@dataclasses.dataclass(frozen=True)
class Components(Query):
    """Connected-component labels by min-label propagation (verified
    vs union-find)."""

    kind = "components"

    def validate(self, n: int) -> None:
        pass  # the whole graph, any n

    def cache_key(self) -> tuple:
        return ("components",)

    def rep_pair(self) -> tuple:
        return (0, 0)


@dataclasses.dataclass(frozen=True)
class Triangles(Query):
    """Whole-graph triangle count by the masked popcount matmul
    (verified vs the adjacency-intersection exact count)."""

    kind = "triangles"

    def validate(self, n: int) -> None:
        pass  # the whole graph, any n

    def cache_key(self) -> tuple:
        return ("triangles",)

    def rep_pair(self) -> tuple:
        return (0, 0)


# ---- results ---------------------------------------------------------
def _freeze(arr):
    arr = np.ascontiguousarray(arr)
    arr.flags.writeable = False
    return arr


@dataclasses.dataclass
class SsspResult:
    """One :class:`Sssp` answer: ``dist[v]`` is the exact weighted
    distance from ``source`` (+inf = unreachable)."""

    found: bool                      # source in range and n > 0
    dist: np.ndarray                 # float64 [n], read-only
    reached: int                     # finite entries
    rounds: int                      # relaxation sweeps to fixpoint
    time_s: float

    def __post_init__(self):
        self.dist = _freeze(self.dist)


@dataclasses.dataclass
class PageRankResult:
    """One :class:`PageRank` answer; ``ranks`` sums to 1."""

    found: bool
    ranks: np.ndarray                # float64 [n], read-only
    iters: int
    delta: float                     # final L1 step delta
    time_s: float

    def __post_init__(self):
        self.ranks = _freeze(self.ranks)


@dataclasses.dataclass
class ComponentsResult:
    """One :class:`Components` answer: ``labels[v]`` is the smallest
    vertex id in v's component."""

    found: bool
    labels: np.ndarray               # int64 [n], read-only
    count: int                       # distinct components
    rounds: int
    time_s: float

    def __post_init__(self):
        self.labels = _freeze(self.labels)


@dataclasses.dataclass
class TrianglesResult:
    """One :class:`Triangles` answer."""

    found: bool
    count: int
    time_s: float


def analytics_summary(res) -> dict:
    """The one-line JSON-safe summary the ``analytics`` control op
    replies with — scalars only, never the whole vector."""
    if isinstance(res, SsspResult):
        finite = res.dist[np.isfinite(res.dist)]
        return {
            "kind": "sssp", "found": bool(res.found),
            "n": int(res.dist.size), "reached": int(res.reached),
            "max_dist": float(finite.max()) if finite.size else None,
            "rounds": int(res.rounds), "time_s": float(res.time_s),
        }
    if isinstance(res, PageRankResult):
        return {
            "kind": "pagerank", "found": bool(res.found),
            "n": int(res.ranks.size), "iters": int(res.iters),
            "delta": float(res.delta),
            "top": int(res.ranks.argmax()) if res.ranks.size else None,
            "time_s": float(res.time_s),
        }
    if isinstance(res, ComponentsResult):
        return {
            "kind": "components", "found": bool(res.found),
            "n": int(res.labels.size), "count": int(res.count),
            "rounds": int(res.rounds), "time_s": float(res.time_s),
        }
    if isinstance(res, TrianglesResult):
        return {
            "kind": "triangles", "found": bool(res.found),
            "count": int(res.count), "time_s": float(res.time_s),
        }
    raise ValueError(f"not an analytics result: {type(res).__name__}")


def analytics_query_from_spec(kind: str, params: dict) -> Query:
    """Build one analytics query from the REPL / net control-op shape
    (string kind + loose params) — unknown kinds and bad fields raise
    ``ValueError``, the ``error invalid:`` seam."""
    params = dict(params or {})
    if kind == "sssp":
        if "source" not in params:
            raise ValueError("sssp needs source=<vertex>")
        q = Sssp(int(params.pop("source")),
                 weight_seed=int(params.pop("weight_seed", 0)))
    elif kind == "pagerank":
        q = PageRank(
            damping=float(params.pop("damping", 0.85)),
            tol=float(params.pop("tol", 1e-8)),
            max_iters=int(params.pop("max_iters", 100)),
        )
    elif kind == "components":
        q = Components()
    elif kind == "triangles":
        q = Triangles()
    else:
        raise ValueError(
            f"unknown analytics kind {kind!r} (one of {ANALYTICS_KINDS})"
        )
    if params:
        raise ValueError(
            f"unknown {kind} params: {', '.join(sorted(params))}"
        )
    return q
