"""Pod-mesh serving plumbing — the control plane that lets ONE logical
replica span MULTIPLE ``jax.distributed`` processes.

The data plane needs no help: once :func:`bibfs_tpu.parallel.mesh.
init_distributed` has joined the job, the vertex-sharded batch program
(:mod:`bibfs_tpu.solvers.sharded`) runs as one SPMD program over the
global mesh and its bitpacked dual-frontier all_gathers
(``parallel/collectives.all_gather_bits_dual``) cross the process
boundary on their own — ``tests/test_multihost.py`` has proven that
exactness since round 7. What multi-process SERVING adds is a control
problem: every process must enter the same collectives in the same
order with the same operands, but only process 0 (the primary) sees
the query stream, the store, and the network front door. This module
is that missing lockstep:

- :class:`PodPrimary` (process 0) owns one TCP control connection per
  worker (the same length-prefixed JSON frames as
  :mod:`bibfs_tpu.serve.net` — one wire format for the whole PR) and
  broadcasts ``graph`` / ``solve`` / ``shutdown`` descriptors;
- :func:`run_pod_worker` (process > 0) executes descriptors strictly
  in receipt order: rebuild the sharded graph on a ``graph``
  descriptor, dispatch the IDENTICAL padded batch program on a
  ``solve`` descriptor, ack each phase back;
- :class:`bibfs_tpu.serve.routes.pod.PodMeshRoute` drives the primary
  side from inside the engine's existing mesh rung.

**The join barrier (two-phase).** A ``solve`` launch is a
commit/abort protocol: the worker acks ``join`` once it has validated
the graph digest and built the dispatch, then PARKS until the primary
broadcasts a verdict — ``go`` (every worker joined ok: everyone,
primary included, enters the collective) or ``abort`` (some worker
refused, died, or timed out: the parked workers skip the batch and
return to the descriptor loop). The verdict phase is what makes the
failure story sound with >1 worker: without it, a worker that acked
would already be inside the collective when the primary aborted
on-host, wedging the pod until the ``jax.distributed`` heartbeat.
After ``go``, each worker acks ``done`` once its
``block_until_ready`` returned (carrying its replicated ``best``
vector so the primary can assert cross-process agreement). Any
refused/dead/timed-out join fails the launch as a :class:`PodError`
while every process is still on the host, and the engine's fallback
ladder re-runs the batch on the local single-device rungs — degraded
throughput, never a hang and never a wrong answer. (A process dying
INSIDE the collective — between ``go`` and ``done`` — is the one
fault this cannot catch; that is ``jax.distributed``'s heartbeat
timeout's job, exactly as it was ``MPI_Allreduce``'s.)

**Graph identity.** A ``graph`` descriptor ships the snapshot's
canonical pairs + content digest — as a header frame followed by
``chunks`` ``graph_chunk`` frames of :data:`GRAPH_CHUNK_EDGES` edges
each, because a realistically-sized graph (the PR 16 RMAT soaks) far
exceeds the 1 MiB frame bound as a single JSON frame. The worker
reassembles the stream and rebuilds the SAME
``GraphSnapshot -> bucketed ELL -> repad_rows -> ShardedGraph``
chain the primary's engine runtime built, verifying the digest over
the received pairs first. Same pairs + same mesh => bit-identical
shapes and content => the same compiled SPMD program on every
process. A store hot-swap on the primary needs no special casing:
the next launch sees a new digest and re-broadcasts before solving —
the mid-traffic hot-swap the soak gates.

Thread discipline (lockgraph-checked): descriptor SENDS happen only
on the engine's flusher thread (launches are serialized by
construction; ``shutdown`` only after the engine is closed), so the
sockets have a single writer and no send lock. Acks are consumed by
one daemon reader thread per worker into a mailbox guarded by
``_lock``; waiters block on the mailbox condition, never on a socket.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from collections import deque

import numpy as np

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.dtrace import ctx_fields, ctx_from_fields, dspan
from bibfs_tpu.obs.metrics import REGISTRY, next_instance_label
from bibfs_tpu.serve.net import MAX_FRAME_BYTES, encode_frame, extract_frames

#: default pod control port offset from the jax.distributed coordinator
#: port — ``bibfs-serve --coordinator host:P`` listens for workers on
#: ``P + POD_PORT_OFFSET`` unless ``--pod-port`` overrides it
POD_PORT_OFFSET = 1

#: edges per ``graph_chunk`` frame. Worst case an int64 edge is two
#: 20-digit values + separators ≈ 42 JSON bytes, so 20k edges ≈ 840 KiB
#: — under the 1 MiB frame bound with envelope headroom.
GRAPH_CHUNK_EDGES = 20_000


class PodError(RuntimeError):
    """A pod control-plane failure (worker refused/died/timed out).
    Raised out of the mesh rung's launch/finish, where the engine's
    resilience ladder catches it and re-runs the batch on the local
    single-device rungs — exact answers, degraded throughput."""


def _recv_frames(sock, buf: bytearray):
    """Blocking read -> complete DECODED frames (empty list on a short
    read that completed no frame). Raises ConnectionError on EOF and
    ValueError on a frame that is not a JSON object."""
    data = sock.recv(1 << 16)
    if not data:
        raise ConnectionError("pod peer closed the control connection")
    buf.extend(data)
    out = []
    for raw in extract_frames(buf, MAX_FRAME_BYTES):
        msg = json.loads(raw.decode("utf-8"))
        if not isinstance(msg, dict):
            raise ValueError(f"pod frame is not an object: {msg!r}")
        out.append(msg)
    return out


@guarded_by("_lock", "_acks", "_dead", "_seq", "_workers", "_epochs",
            "_last_hb", "_fenced", "_regraph")
class PodPrimary:
    """Process 0's side of the pod control plane (module docstring).

    ``accept_workers`` blocks until every worker has connected and
    introduced itself, then starts one reader thread per connection.
    ``post_*`` broadcast a descriptor (single-writer by construction:
    the engine flusher); ``await_phase`` blocks on the ack mailbox.

    **Failure domains (epoch fencing).** Every worker's hello declares
    an incarnation ``epoch``, echoed on each of its acks/heartbeats.
    The reader fences any frame whose epoch is not the worker's
    CURRENT one — a zombie incarnation's late acks are dropped and
    counted (:attr:`fenced_frames`) instead of feeding
    ``await_phase``. A dead worker's replacement rejoins through
    :meth:`accept_rejoin` at a strictly higher epoch; the next launch
    re-broadcasts the graph through the existing chunk stream, so the
    mesh rung RECOVERS rather than degrading forever. Workers spawned
    with ``heartbeat_s`` send periodic ``hb`` frames;
    :meth:`check_heartbeats` (the supervisor's tick) marks a silent
    worker dead after ``heartbeat_timeout_s`` — the launch path then
    aborts pre-collective exactly like an observed death.
    """

    def __init__(self, num_workers: int, *, host: str = "",
                 port: int = 0, accept_timeout_s: float = 120.0,
                 heartbeat_timeout_s: float | None = None):
        self.num_workers = int(num_workers)
        self._accept_timeout_s = float(accept_timeout_s)
        self._hb_timeout_s = (
            None if heartbeat_timeout_s is None
            else float(heartbeat_timeout_s)
        )
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._seq = 0
        self._workers: dict = {}       # process_index -> socket
        self._acks: dict = {}          # (seq, phase) -> {pidx: msg}
        self._dead: dict = {}          # process_index -> reason
        self._epochs: dict = {}        # process_index -> current epoch
        self._last_hb: dict = {}       # process_index -> monotonic
        self._fenced = 0               # stale-epoch frames dropped
        self._regraph = False          # rejoin -> re-broadcast graph
        self._last_digest: str | None = None  # flusher-only state
        self._closed = False
        self._obs_label = next_instance_label("pod")
        self._g_epoch = REGISTRY.gauge(
            "bibfs_pod_worker_epoch",
            "Each pod worker's current incarnation epoch",
            ("pod", "worker"),
        )
        for pidx in range(1, self.num_workers + 1):  # render at zero
            self._g_epoch.labels(
                pod=self._obs_label, worker=str(pidx)
            ).set(0)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(self.num_workers or 1)
        self.port = self._listener.getsockname()[1]

    # ---- join --------------------------------------------------------
    def accept_workers(self) -> None:
        """Block until all ``num_workers`` workers connected and sent
        their hello; start their reader threads. Raises
        :class:`PodError` past the accept timeout."""
        deadline = time.monotonic() + self._accept_timeout_s
        joined: dict = {}
        epochs: dict = {}
        while len(joined) < self.num_workers:
            self._listener.settimeout(
                max(0.1, deadline - time.monotonic())
            )
            try:
                sock, _addr = self._listener.accept()
            except (socket.timeout, OSError):
                raise PodError(
                    f"pod: {len(joined)}/{self.num_workers} workers "
                    f"joined within {self._accept_timeout_s}s"
                ) from None
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hello = self._read_hello(sock, deadline)
            pidx = int(hello.get("process", -1))
            if pidx < 1:
                sock.close()
                continue
            joined[pidx] = sock
            epochs[pidx] = int(hello.get("epoch", 0))
        with self._lock:
            self._workers = joined
            self._epochs = epochs
        for pidx, epoch in epochs.items():
            self._g_epoch.labels(
                pod=self._obs_label, worker=str(pidx)
            ).set(epoch)
        for pidx, sock in joined.items():
            threading.Thread(
                target=self._reader, args=(pidx, sock, epochs[pidx]),
                name=f"bibfs-pod-ack-{pidx}", daemon=True,
            ).start()

    @staticmethod
    def _read_hello(sock, deadline: float) -> dict:
        buf = bytearray()
        while True:
            sock.settimeout(max(0.1, deadline - time.monotonic()))
            try:
                frames = _recv_frames(sock, buf)
            except (ConnectionError, socket.timeout, OSError,
                    ValueError) as e:
                raise PodError(f"pod: worker hello failed: {e}") from e
            if frames:
                return frames[0]

    # ---- ack plumbing ------------------------------------------------
    def _reader(self, pidx: int, sock, epoch: int = 0) -> None:
        buf = bytearray()
        why = "worker closed the control connection"
        try:
            while True:
                for msg in _recv_frames(sock, buf):
                    with self._lock:
                        # epoch fence: a frame from any incarnation
                        # other than the worker's CURRENT one (a
                        # zombie's late ack after a rejoin) is dropped
                        # and counted — it must never feed await_phase.
                        # An epoch-less frame defaults to THIS reader's
                        # connection epoch, so a zombie cannot dodge
                        # the fence by omitting the field.
                        cur = self._epochs.get(pidx, 0)
                        if int(msg.get("epoch", epoch)) != cur:
                            self._fenced += 1
                            continue
                        if msg.get("op") == "hb":
                            self._last_hb[pidx] = time.monotonic()
                            continue
                        key = (int(msg.get("seq", -1)),
                               str(msg.get("phase", "done")))
                        self._acks.setdefault(key, {})[pidx] = msg
                        # sweep acks that straggled in after their seq
                        # was abandoned (await_phase pops the key it
                        # waits on; a late ack re-creates it): launches
                        # are serialized, so nothing legitimately waits
                        # this far behind the current seq
                        stale = [k for k in self._acks
                                 if k[0] + 64 < self._seq]
                        for k in stale:
                            del self._acks[k]
                        self._cv.notify_all()
        except (ConnectionError, OSError, ValueError) as e:
            why = str(e) or why
        with self._lock:
            # a fenced-out incarnation's reader exits SILENTLY: its
            # socket death says nothing about the current incarnation
            if self._epochs.get(pidx, 0) == epoch:
                self._dead[pidx] = why
                self._cv.notify_all()

    def await_phase(self, seq: int, phase: str,
                    timeout: float = 120.0) -> dict:
        """Block until EVERY worker acked ``(seq, phase)`` ok; returns
        ``{process_index: ack}``. Raises :class:`PodError` on a dead
        worker, a not-ok ack, or timeout."""
        deadline = time.monotonic() + timeout
        key = (int(seq), phase)
        with self._lock:
            # the key is popped on EVERY exit (success, dead worker,
            # timeout): an abandoned seq must not leave its partial
            # ack dict — worker `best` vectors included — in the
            # mailbox forever
            try:
                while True:
                    if self._dead:
                        pidx, why = next(iter(self._dead.items()))
                        raise PodError(f"pod worker {pidx} died: {why}")
                    got = self._acks.get(key, {})
                    if len(got) >= len(self._workers):
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise PodError(
                            f"pod: {len(got)}/{len(self._workers)} "
                            f"workers acked seq {seq} phase {phase!r} "
                            f"within {timeout}s"
                        )
                    self._cv.wait(left)
            finally:
                self._acks.pop(key, None)
        for pidx, msg in got.items():
            if not msg.get("ok", False):
                raise PodError(
                    f"pod worker {pidx} failed seq {seq} "
                    f"({phase}): {msg.get('error', 'unspecified')}"
                )
        return got

    # ---- failure domains --------------------------------------------
    @property
    def fenced_frames(self) -> int:
        """Stale-epoch frames dropped by the reader fence (zombie
        incarnations' late acks) — the soak's fence witness."""
        return self._fenced

    def worker_epoch(self, pidx: int) -> int:
        with self._lock:
            return int(self._epochs.get(int(pidx), 0))

    def dead_workers(self) -> dict:
        """``{process_index: reason}`` for every worker currently known
        dead — the supervisor's pod-heal input."""
        with self._lock:
            return dict(self._dead)

    def check_heartbeats(self) -> list:
        """Mark workers whose heartbeat went silent for longer than
        ``heartbeat_timeout_s`` as dead; returns the newly-dead
        process indexes. Only workers that have EVER heartbeat are
        judged (a worker spawned without ``heartbeat_s`` opted out),
        and a no-op when the primary was built without a timeout —
        so legacy pods keep their exact pre-heartbeat behavior."""
        if self._hb_timeout_s is None:
            return []
        now = time.monotonic()
        newly: list = []
        with self._lock:
            for pidx, last in list(self._last_hb.items()):
                if pidx in self._dead or pidx not in self._workers:
                    continue
                if now - last > self._hb_timeout_s:
                    self._dead[pidx] = (
                        f"heartbeat silent for {now - last:.1f}s"
                    )
                    newly.append(pidx)
            if newly:
                self._cv.notify_all()
        return newly

    def accept_rejoin(self, timeout_s: float = 30.0) -> int:
        """Admit ONE respawned worker back into the mesh: accept its
        connection, require a known process index at a STRICTLY higher
        epoch than the incarnation being replaced (the fence that
        keeps a zombie from re-admitting itself), swap the control
        socket, clear the death record, and flag the next launch to
        re-broadcast the graph through the existing chunk stream (the
        respawned process holds no graph). Returns the process index;
        raises :class:`PodError` past the timeout. The old incarnation's
        socket is deliberately LEFT OPEN: a zombie is by definition
        still alive, and closing its connection under it would discard
        its late acks unseen — instead its reader keeps draining them
        into the epoch fence (counted in :attr:`fenced_frames`) until
        the zombie's own EOF retires the reader silently, so the
        recovered incarnation is never re-marked dead by its
        predecessor's death."""
        deadline = time.monotonic() + timeout_s
        while True:
            if time.monotonic() >= deadline:
                raise PodError(
                    f"pod: no acceptable rejoin within {timeout_s}s"
                )
            self._listener.settimeout(
                max(0.1, deadline - time.monotonic())
            )
            try:
                sock, _addr = self._listener.accept()
            except (socket.timeout, OSError):
                raise PodError(
                    f"pod: no rejoin connection within {timeout_s}s"
                ) from None
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                hello = self._read_hello(sock, deadline)
            except PodError:
                sock.close()
                continue
            pidx = int(hello.get("process", -1))
            epoch = int(hello.get("epoch", 0))
            with self._lock:
                known = pidx in self._workers
                cur = self._epochs.get(pidx, 0)
            if pidx < 1 or not known or epoch <= cur:
                sock.close()
                continue
            with self._lock:
                self._workers[pidx] = sock
                self._epochs[pidx] = epoch
                self._dead.pop(pidx, None)
                self._last_hb.pop(pidx, None)
                self._regraph = True
                self._cv.notify_all()
            self._g_epoch.labels(
                pod=self._obs_label, worker=str(pidx)
            ).set(epoch)
            threading.Thread(
                target=self._reader, args=(pidx, sock, epoch),
                name=f"bibfs-pod-ack-{pidx}e{epoch}", daemon=True,
            ).start()
            return pidx

    # ---- broadcasts (engine-flusher thread only) ---------------------
    def _post(self, desc: dict) -> int:
        with self._lock:
            if self._closed:
                raise PodError("pod control plane is closed")
            if self._dead:
                pidx, why = next(iter(self._dead.items()))
                raise PodError(f"pod worker {pidx} died: {why}")
            self._seq += 1
            seq = self._seq
            workers = dict(self._workers)
        desc = dict(desc, seq=seq)
        try:
            data = encode_frame(desc)
        except ValueError as e:
            # the flusher's resilience ladder speaks PodError; a raw
            # encode ValueError would escape it
            raise PodError(f"pod descriptor encode failed: {e}") from e
        # single writer by construction (module docstring): sendall
        # happens OUTSIDE the lock, on the one broadcasting thread
        for pidx, sock in workers.items():
            try:
                sock.sendall(data)
            except OSError as e:
                with self._lock:
                    self._dead[pidx] = f"broadcast failed: {e}"
                    self._cv.notify_all()
                raise PodError(
                    f"pod worker {pidx}: broadcast failed: {e}"
                ) from e
        return seq

    def ensure_graph(self, snapshot, build=None,
                     timeout: float = 120.0):
        """Broadcast ``snapshot`` (canonical pairs + digest) if it is
        not the workers' current graph, run the primary's own ``build``
        callable, then await the workers' rebuild acks — in THAT order,
        because building the sharded graph (``jax.device_put`` onto the
        global mesh) is itself collective on a multi-process backend:
        the primary building before the workers have the descriptor
        deadlocks in the transfer layer's rendezvous. Returns
        ``build()``'s result. Flusher-thread only; the digest memo
        makes the steady-state cost one string compare per launch. A
        worker rejoin (:meth:`accept_rejoin`) voids the memo via the
        ``_regraph`` flag — the respawned incarnation holds no graph,
        so the next launch re-broadcasts even an unchanged digest."""
        with self._lock:
            regraph = self._regraph
        if not regraph and snapshot.digest == self._last_digest:
            return build() if build is not None else None
        seq = self.post_graph(snapshot)
        out = build() if build is not None else None
        self.await_phase(seq, "done", timeout)
        self._last_digest = snapshot.digest
        with self._lock:
            self._regraph = False
        return out

    def post_graph(self, snapshot) -> int:
        """Broadcast one graph descriptor as a chunked frame stream:
        a header frame (n/digest/version/chunk count) followed by that
        many ``graph_chunk`` frames of at most
        :data:`GRAPH_CHUNK_EDGES` edges each, keyed to the header by
        ``for`` — the frame bound is 1 MiB and realistic graphs are
        far bigger as JSON. Returns the header's seq (the one the
        workers ack ``done`` on after rebuilding)."""
        flat = np.asarray(snapshot.pairs, dtype=np.int64).ravel()
        step = 2 * GRAPH_CHUNK_EDGES
        chunks = [flat[i: i + step].tolist()
                  for i in range(0, len(flat), step)]
        seq = self._post({
            "op": "graph",
            "n": int(snapshot.n),
            "digest": snapshot.digest,
            "version": int(snapshot.version),
            "chunks": len(chunks),
        })
        for i, chunk in enumerate(chunks):
            self._post({
                "op": "graph_chunk", "for": seq, "i": i,
                "pairs": chunk,
            })
        return seq

    def post_solve(self, digest: str, mode: str, padded,
                   count: int, ctx=None) -> int:
        """Broadcast one padded solve batch; returns its seq. The
        caller awaits ``join`` before entering the collective and
        ``done`` (with per-worker ``best``) in finish. ``ctx`` is a
        sampled query's trace context: the broadcast span parents
        every worker's ``pod_worker_solve`` span, and the descriptor
        carries the context fields across the process boundary."""
        desc = {
            "op": "solve",
            "digest": digest,
            "mode": mode,
            "count": int(count),
            "pairs": np.asarray(padded, dtype=np.int64).ravel().tolist(),
        }
        if ctx is None:
            return self._post(desc)
        sp = dspan("pod_broadcast", ctx, count=int(count),
                   workers=self.num_workers)
        desc.update(ctx_fields(sp.ctx))
        try:
            return self._post(desc)
        finally:
            sp.finish()

    def commit_solve(self, seq: int) -> None:
        """Broadcast the ``go`` verdict for ``seq``: every worker
        acked ``join``, so every process (primary included) may enter
        the collective. Raises :class:`PodError` if a worker socket is
        gone mid-broadcast — the primary then aborts on-host without
        entering the collective (a worker that already got its ``go``
        is inside one short a participant, which is the dead-worker
        case the ``jax.distributed`` heartbeat owns anyway)."""
        self._post({"op": "go", "for": int(seq)})

    def abort_solve(self, seq: int) -> None:
        """Best-effort ``abort`` verdict for ``seq`` after a failed
        join barrier: workers parked in their verdict wait skip the
        collective instead of entering it short the primary. Sends to
        every worker not known dead and never raises — the launch is
        already failing with its own :class:`PodError`."""
        with self._lock:
            if self._closed:
                return
            self._seq += 1
            vseq = self._seq
            workers = {p: s for p, s in self._workers.items()
                       if p not in self._dead}
        try:
            data = encode_frame(
                {"op": "abort", "for": int(seq), "seq": vseq}
            )
        except ValueError:
            return
        for pidx, sock in workers.items():
            try:
                sock.sendall(data)
            except OSError as e:
                with self._lock:
                    self._dead.setdefault(
                        pidx, f"broadcast failed: {e}"
                    )
                    self._cv.notify_all()

    # ---- lifecycle ---------------------------------------------------
    def shutdown(self, timeout: float = 30.0) -> None:
        """Broadcast shutdown and wait for the workers' goodbyes (best
        effort — a worker already gone is fine at this point)."""
        try:
            seq = self._post({"op": "shutdown"})
            self.await_phase(seq, "done", timeout)
        except PodError:
            pass
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            workers = dict(self._workers)
        for sock in workers.values():
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass


def _connect_retry(host: str, port: int, timeout_s: float):
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)
            return sock
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.1)


def _build_worker_graph(msg: dict, parts: list, mesh):
    """Rebuild the primary's sharded graph from a ``graph`` header and
    its reassembled ``graph_chunk`` pair lists: verify the content
    digest over the received pairs, then run the SAME snapshot ->
    bucketed ELL -> repad -> shard chain the engine runtime runs
    (``serve/engine._GraphRuntime.mesh_graph``) so shapes and content
    are bit-identical across processes."""
    from bibfs_tpu.serve.buckets import repad_rows
    from bibfs_tpu.solvers.sharded import ShardedGraph
    from bibfs_tpu.store.snapshot import GraphSnapshot, content_digest

    n = int(msg["n"])
    flat = (np.concatenate(
        [np.asarray(p, dtype=np.int64).ravel() for p in parts]
    ) if parts else np.zeros(0, dtype=np.int64))
    pairs = flat.reshape(-1, 2)
    digest = str(msg["digest"])
    got = content_digest(n, pairs)
    if got != digest:
        raise ValueError(
            f"pod graph digest mismatch: wire {digest} != rebuilt {got}"
        )
    snap = GraphSnapshot(n, pairs, digest=digest,
                         version=int(msg.get("version", 0)))
    ell = repad_rows(snap.ell(), int(mesh.devices.size))
    return digest, ShardedGraph(ell, mesh)


def run_pod_worker(host: str, port: int, *, process_index: int,
                   connect_timeout_s: float = 120.0, log=None,
                   epoch: int = 0,
                   heartbeat_s: float | None = None) -> int:
    """The worker process's main loop (module docstring): connect to
    the primary's pod control port, then execute descriptors strictly
    in receipt order until ``shutdown`` (returns 0) or the primary
    closes the connection (returns 0 too — a vanished primary is a
    normal teardown, the jax.distributed layer owns crash detection).

    ``epoch`` is this incarnation's fencing identity: it rides the
    hello and every ack, so the primary can reject a previous
    incarnation's late frames after this worker rejoined at a higher
    epoch. ``heartbeat_s`` (None = off) starts a sender thread posting
    ``hb`` frames at that cadence — the primary's
    ``check_heartbeats`` marks this worker dead when they stop. The
    socket gains a second writer with heartbeats on, so sends
    serialize on a leaf write lock (the :class:`NetClient` pattern).
    """
    from bibfs_tpu.parallel.mesh import make_1d_mesh
    from bibfs_tpu.solvers import sharded as _sharded
    from bibfs_tpu.solvers.timing import force_scalar

    def say(msg: str) -> None:
        if log is not None:
            log(msg)

    epoch = int(epoch)
    mesh = make_1d_mesh()  # the global mesh, spanning every process
    sock = _connect_retry(host, port, connect_timeout_s)
    wlock = threading.Lock()

    def send(data: bytes) -> None:
        with wlock:
            sock.sendall(data)

    send(encode_frame(
        {"op": "hello", "process": int(process_index), "epoch": epoch}
    ))
    say(f"[Pod] worker {process_index}: joined {host}:{port} "
        f"(epoch {epoch}, {mesh.devices.size}-device global mesh)")
    hb_stop = threading.Event()
    if heartbeat_s is not None:
        def _hb_main() -> None:
            frame = encode_frame({
                "op": "hb", "process": int(process_index),
                "epoch": epoch,
            })
            while not hb_stop.wait(heartbeat_s):
                try:
                    send(frame)
                except OSError:
                    return

        threading.Thread(
            target=_hb_main,
            name=f"bibfs-pod-hb-{process_index}", daemon=True,
        ).start()
    graphs: dict = {}  # digest -> ShardedGraph (current only)
    buf = bytearray()
    pending: deque = deque()  # decoded frames not yet dispatched

    def next_msg() -> dict:
        while not pending:
            pending.extend(_recv_frames(sock, buf))
        return pending.popleft()

    def ack(seq, phase, ok, **extra):
        send(encode_frame(
            dict(extra, seq=seq, phase=phase, ok=ok, epoch=epoch)
        ))

    def await_verdict(seq: int) -> bool:
        """Park for the primary's commit/abort verdict on ``seq``
        (module docstring): True on ``go``, False on ``abort``.
        Verdicts for other seqs are stale (a late abort for a batch
        this worker already refused) and skipped. Any OTHER descriptor
        means the primary moved on without a verdict — impossible
        under the single-writer discipline, but a control-plane bug
        must degrade to a skipped batch, not a worker wedged inside a
        collective: push it back and treat the solve as aborted."""
        while True:
            m = next_msg()
            mop = m.get("op")
            if mop in ("go", "abort"):
                if int(m.get("for", -1)) == seq:
                    return mop == "go"
                continue
            pending.appendleft(m)
            return False

    try:
        while True:
            try:
                msg = next_msg()
            except (ConnectionError, ValueError):
                return 0
            op = msg.get("op")
            seq = int(msg.get("seq", -1))
            if op == "shutdown":
                ack(seq, "done", True)
                return 0
            if op in ("go", "abort"):
                # a verdict for a seq this worker already refused (or
                # never joined): stale, skip
                continue
            if op == "graph":
                nchunks = int(msg.get("chunks", 0))
                parts, bad = [], None
                for i in range(nchunks):
                    try:
                        m = next_msg()
                    except (ConnectionError, ValueError):
                        return 0
                    if (m.get("op") != "graph_chunk"
                            or int(m.get("for", -1)) != seq):
                        bad = (f"expected graph_chunk {i} for seq "
                               f"{seq}, got {m.get('op')!r}")
                        pending.appendleft(m)
                        break
                    parts.append(m.get("pairs", ()))
                if bad is not None:
                    ack(seq, "done", False, error=bad)
                    continue
                try:
                    digest, sg = _build_worker_graph(msg, parts, mesh)
                except (KeyError, TypeError, ValueError) as e:
                    ack(seq, "done", False, error=str(e))
                    continue
                graphs.clear()  # one served graph at a time
                graphs[digest] = sg
                ack(seq, "done", True, digest=digest)
                say(f"[Pod] worker {process_index}: graph "
                    f"{digest[:12]} n={sg.n}")
                continue
            if op == "solve":
                sg = graphs.get(str(msg.get("digest")))
                if sg is None:
                    # refuse BEFORE the join ack: the primary aborts
                    # on the host, nobody enters a collective short
                    # one participant
                    ack(seq, "join", False,
                        error="unknown graph digest "
                              f"{msg.get('digest')!r}")
                    continue
                try:
                    padded = np.asarray(
                        msg["pairs"], dtype=np.int64
                    ).reshape(-1, 2)
                    _p, dispatch = _sharded._batch_dispatch(
                        sg, padded, str(msg.get("mode", "sync"))
                    )
                except (KeyError, TypeError, ValueError) as e:
                    ack(seq, "join", False, error=str(e))
                    continue
                ack(seq, "join", True)
                try:
                    committed = await_verdict(seq)
                except (ConnectionError, ValueError):
                    return 0
                if not committed:
                    continue
                # sampled queries carry their trace context on the
                # descriptor: this worker's solve span lands in ITS
                # spool, parented by the primary's pod_broadcast span
                with dspan("pod_worker_solve", ctx_from_fields(msg),
                           worker=int(process_index),
                           count=int(msg.get("count", 0))):
                    out = dispatch()
                    force_scalar(out)
                # best/meet are REPLICATED outputs: addressable on
                # this host (the sharded parent planes are not —
                # test_multihost.py documents the split)
                best = [int(b) for b in np.asarray(out[0])]
                ack(seq, "done", True, best=best)
                continue
            ack(seq, "done", False, error=f"unknown op {op!r}")
    finally:
        hb_stop.set()
        try:
            sock.close()
        except OSError:
            pass
