"""Collective helpers — the TPU-native census of the reference's MPI usage.

Complete mapping (reference collective census in SURVEY.md §2):

| MPI (reference)              | here                                    |
|------------------------------|-----------------------------------------|
| ``Allreduce BOR`` of bitsets | ``or_allreduce`` (psum of masks > 0)    |
| ``Allreduce LOR`` votes      | ``or_allreduce`` on a scalar bool       |
| ``Allreduce SUM`` popcounts  | ``sum_allreduce``                       |
| ``Allreduce MIN`` best dist  | ``global_min_and_argmin`` (pmin)        |
| ``Allgather(v)`` frontiers   | ``all_gather_bits`` (packed uint32)     |
| ``Bcast`` graph replication  | none — the graph is 1D-sharded at load  |

``all_gather_bits`` is the direct analog of v2's bitset exchange
(second_try.cpp:53-62: frontiers as ``uint64_t`` words, 64 vertices/word,
merged with ``Allreduce BOR``): the per-level frontier crossing the ICI is
packed 32 vertices to a ``uint32`` word, so the wire payload is n/8 bytes
instead of the n bool bytes a plain ``all_gather`` would ship — 8× less
traffic on the one exchange whose size scales with the graph.

All helpers are usable inside ``shard_map`` bodies (including under
``lax.while_loop``/``lax.cond``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# plain int, NOT jnp.int32: a device constant here would initialize a JAX
# backend at import time (same rule as dense.INF32 — and on a hung tunneled
# backend that import-time init blocks the whole process)
_IMAX = 2**31 - 1


def or_allreduce(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Boolean OR across the mesh axis (MPI_Allreduce BOR/LOR,
    v2/second_try.cpp:82-85,115; v4/mpi_bas.cpp:107,124)."""
    return jax.lax.psum(x.astype(jnp.int32), axis) > 0


def sum_allreduce(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Sum across the mesh axis (MPI_Allreduce SUM, second_try.cpp:123-124)."""
    return jax.lax.psum(x, axis)


def max_allreduce(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Max across the mesh axis (MPI_Allreduce MAX, main-v2.cpp:70-71)."""
    return jax.lax.pmax(x, axis)


PACK_W = 32  # vertices per packed word (v2 used 64/word, second_try.cpp:53)


def pack_bits(fr: jnp.ndarray) -> jnp.ndarray:
    """Pack ``bool[m]`` into little-endian ``uint32[ceil(m/32)]`` words.

    Pure elementwise/reshape ops — fuses into the surrounding level kernel;
    the only thing it changes is the payload that crosses the ICI.
    """
    m = fr.shape[0]
    nw = -(-m // PACK_W)
    b = jnp.pad(fr, (0, nw * PACK_W - m)).reshape(nw, PACK_W)
    weights = jnp.left_shift(
        jnp.uint32(1), jnp.arange(PACK_W, dtype=jnp.uint32)
    )[None, :]
    return jnp.sum(
        jnp.where(b, weights, jnp.uint32(0)), axis=1, dtype=jnp.uint32
    )


def unpack_bits(words: jnp.ndarray, m: int) -> jnp.ndarray:
    """Inverse of :func:`pack_bits`: ``uint32[nw] -> bool[m]``."""
    bits = jnp.bitwise_and(
        jnp.right_shift(
            words[:, None], jnp.arange(PACK_W, dtype=jnp.uint32)[None, :]
        ),
        jnp.uint32(1),
    )
    return bits.reshape(-1)[:m].astype(jnp.bool_)


def _unpack_shard_words(words: jnp.ndarray, n_loc: int) -> jnp.ndarray:
    """Per-shard word unpack shared by the gather helpers below:
    ``uint32[ndev, ..., nw] -> uint32 0/1 [ndev, ..., n_loc]`` with each
    shard's pad-to-word gap stripped (so ``n_loc`` need not divide the
    word size). THE one implementation of the unpack/strip rule."""
    bits = jnp.bitwise_and(
        jnp.right_shift(
            words[..., None],
            jnp.arange(PACK_W, dtype=jnp.uint32),
        ),
        jnp.uint32(1),
    )
    return bits.reshape(*words.shape[:-1], -1)[..., :n_loc]


def all_gather_bits(fr: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Bitpacked boolean all_gather: each shard packs its ``bool[n_loc]``
    into uint32 words, ONE tiled ``all_gather`` ships the words (n/8 bytes
    on the wire vs n for bools), and every device unpacks the global
    frontier locally."""
    n_loc = fr.shape[0]
    nw = -(-n_loc // PACK_W)
    words = jax.lax.all_gather(pack_bits(fr), axis, tiled=True)  # [ndev*nw]
    ndev = words.shape[0] // nw
    bits = _unpack_shard_words(words.reshape(ndev, nw), n_loc)
    return bits.reshape(-1).astype(jnp.bool_)


def all_gather_bits_dual(
    fr_s: jnp.ndarray, fr_t: jnp.ndarray, axis: str
) -> jnp.ndarray:
    """Both sides' bitpacked frontiers in ONE ``all_gather``: the two word
    planes ride a single ``[2, nw]`` payload per shard, so a lock-step
    round pays one collective's latency instead of two (the wire BYTES are
    the same 2·n/8 either way — this halves the per-round latency/sync
    term, which is what dominates small-message ICI collectives). Returns
    the :func:`bibfs_tpu.ops.expand.pack_dual`-coded global frontier
    ``uint8[n]`` (bit 0 = source side, bit 1 = target side), ready for
    ``expand_pull_dual`` with no bool round-trip."""
    n_loc = fr_s.shape[0]
    planes = jnp.stack([pack_bits(fr_s), pack_bits(fr_t)])  # [2, nw]
    allp = jax.lax.all_gather(planes, axis)  # [ndev, 2, nw]
    bits = _unpack_shard_words(allp, n_loc)  # [ndev, 2, n_loc]
    code = bits[:, 0, :] | (bits[:, 1, :] << 1)
    return code.reshape(-1).astype(jnp.uint8)


def frontier_exchange_bytes(n_loc: int, packed: bool = True) -> int:
    """Wire bytes per device for one frontier exchange — the measured
    traffic number the bench detail reports (packed uint32 words vs the
    round-1 bool payload)."""
    return (-(-n_loc // PACK_W)) * 4 if packed else n_loc


def global_min_and_argmin(
    local_min: jnp.ndarray, local_arg: jnp.ndarray, axis: str
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Global (min value, arg at min) across shards.

    ``local_arg`` must be a GLOBAL id. Tie-break: smallest arg among shards
    achieving the min — deterministic, unlike MPI rank-order races.
    """
    gmin = jax.lax.pmin(local_min, axis)
    cand = jnp.where(local_min == gmin, local_arg, _IMAX)
    garg = jax.lax.pmin(cand, axis)
    return gmin, garg
