"""Collective helpers — the TPU-native census of the reference's MPI usage.

Complete mapping (reference collective census in SURVEY.md §2):

| MPI (reference)              | here                                    |
|------------------------------|-----------------------------------------|
| ``Allreduce BOR`` of bitsets | ``or_allreduce`` (psum of masks > 0)    |
| ``Allreduce LOR`` votes      | ``or_allreduce`` on a scalar bool       |
| ``Allreduce SUM`` popcounts  | ``sum_allreduce``                       |
| ``Allreduce MIN`` best dist  | ``global_min_and_argmin`` (pmin)        |
| ``Allgather(v)`` frontiers   | ``jax.lax.all_gather(..., tiled=True)`` |
| ``Bcast`` graph replication  | none — the graph is 1D-sharded at load  |

All helpers are usable inside ``shard_map`` bodies (including under
``lax.while_loop``/``lax.cond``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_IMAX = jnp.int32(2**31 - 1)


def or_allreduce(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Boolean OR across the mesh axis (MPI_Allreduce BOR/LOR,
    v2/second_try.cpp:82-85,115; v4/mpi_bas.cpp:107,124)."""
    return jax.lax.psum(x.astype(jnp.int32), axis) > 0


def sum_allreduce(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Sum across the mesh axis (MPI_Allreduce SUM, second_try.cpp:123-124)."""
    return jax.lax.psum(x, axis)


def max_allreduce(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Max across the mesh axis (MPI_Allreduce MAX, main-v2.cpp:70-71)."""
    return jax.lax.pmax(x, axis)


def global_min_and_argmin(
    local_min: jnp.ndarray, local_arg: jnp.ndarray, axis: str
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Global (min value, arg at min) across shards.

    ``local_arg`` must be a GLOBAL id. Tie-break: smallest arg among shards
    achieving the min — deterministic, unlike MPI rank-order races.
    """
    gmin = jax.lax.pmin(local_min, axis)
    cand = jnp.where(local_min == gmin, local_arg, _IMAX)
    garg = jax.lax.pmin(cand, axis)
    return gmin, garg
