from bibfs_tpu.parallel.mesh import make_1d_mesh, shard_spec  # noqa: F401
from bibfs_tpu.parallel.collectives import (  # noqa: F401
    or_allreduce,
    sum_allreduce,
    global_min_and_argmin,
)
