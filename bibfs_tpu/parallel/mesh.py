"""Device-mesh utilities — the framework's replacement for MPI rank/size
bookkeeping (v2/second_try.cpp:16-19, v4/mpi_bas.cpp:12-15).

The reference's process model is `mpirun -n p` CPU ranks over 1 Gb Ethernet;
here a single SPMD program spans a `jax.sharding.Mesh` whose collectives
ride ICI (intra-pod) / DCN (multi-slice), and "rank"/"size" become
`jax.lax.axis_index` / mesh axis size inside `shard_map` blocks.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

VERTEX_AXIS = "x"


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions.

    The top-level export (and its ``check_vma`` kwarg) only exists on
    newer jax lines; older ones ship the same transform as
    ``jax.experimental.shard_map`` with the kwarg named ``check_rep``.
    Every shard_map in this framework goes through here so a version
    skew degrades to the equivalent call instead of an
    ``AttributeError`` at first dispatch."""
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # the experimental checker (check_rep) predates replication rules for
    # while_loop — and every search program here IS one lax.while_loop —
    # so on these versions the checker can never validate the programs it
    # would guard; off is the documented workaround, and it is only a
    # checker (the newer vma checker takes over where available)
    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def pcast(x, axis, *, to):
    """``jax.lax.pcast`` across jax versions: the vma (varying-manual-
    axes) cast exists only on jax lines that ship the vma checker. Older
    lines have no vma system — there is nothing to pin, every provenance
    is acceptable to their replication checker, and the cast is the
    identity."""
    import jax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis, to=to)
    return x


def axis_size(axis):
    """``jax.lax.axis_size`` across jax versions; older lines use the
    ``psum(1, axis)`` idiom, which constant-folds to the static axis
    size at trace time."""
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)


def make_1d_mesh(num_devices: int | None = None, axis: str = VERTEX_AXIS) -> Mesh:
    """A 1D mesh over the first ``num_devices`` visible devices (all by
    default). Vertex arrays are 1D-sharded over this axis (the real
    owner-computes partition the reference's v4 compiled in but disabled,
    v4/comp.cu:27,99 — quirk Q4)."""
    devs = jax.devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devs)}"
            )
        devs = devs[:num_devices]
    return jax.make_mesh((len(devs),), (axis,), devices=devs)


ROW_AXIS = "r"
COL_AXIS = "c"


def make_2d_mesh(rows: int, cols: int) -> Mesh:
    """An ``rows x cols`` mesh for the 2D-partitioned solver
    (:mod:`bibfs_tpu.solvers.sharded2d`): adjacency blocks shard over both
    axes, per-level frontier exchange rides the ``r`` axis and the fold
    rides the ``c`` axis — O(n/C + n/R) wire traffic per device per level
    instead of the 1D solver's O(n)."""
    devs = jax.devices()
    if rows * cols > len(devs):
        raise ValueError(
            f"requested {rows}x{cols} mesh, have {len(devs)} devices"
        )
    return jax.make_mesh((rows, cols), (ROW_AXIS, COL_AXIS),
                         devices=devs[: rows * cols])


def shard_spec(mesh: Mesh, axis: str = VERTEX_AXIS) -> NamedSharding:
    """NamedSharding that splits the leading (vertex) dimension."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


class DistributedContext:
    """What :func:`init_distributed` proved about the joined job: this
    process's index, the job size, and the local/global device split —
    the numbers a pod-serving primary checks before it trusts a global
    mesh (a worker that silently joined with 0 local devices would
    otherwise surface only as a hang inside the first collective)."""

    __slots__ = ("process_index", "process_count", "local_device_count",
                 "global_device_count", "coordinator_address")

    def __init__(self, process_index, process_count, local_device_count,
                 global_device_count, coordinator_address):
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.local_device_count = int(local_device_count)
        self.global_device_count = int(global_device_count)
        self.coordinator_address = coordinator_address

    @property
    def is_primary(self) -> bool:
        """Process 0 — the one that owns the serving front door in a
        pod-mesh replica (:mod:`bibfs_tpu.parallel.podmesh`)."""
        return self.process_index == 0

    def asdict(self) -> dict:
        return {
            "process_index": self.process_index,
            "process_count": self.process_count,
            "local_device_count": self.local_device_count,
            "global_device_count": self.global_device_count,
            "coordinator_address": self.coordinator_address,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"DistributedContext(process {self.process_index}/"
            f"{self.process_count}, devices "
            f"{self.local_device_count}/{self.global_device_count})"
        )


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    auto: bool = False,
) -> DistributedContext:
    """Join a multi-process SPMD job and report what was joined.

    The served-configuration entry point behind ``bibfs-serve``'s
    ``--coordinator`` flags: same :func:`jax.distributed.initialize`
    contract as :func:`init_multihost` (explicit coordinator triple, or
    ``auto=True`` for cluster auto-detection; bare calls raise
    :class:`ValueError` instead of hanging in connection retry), but
    returns a :class:`DistributedContext` carrying process index/count
    and local/global device visibility so callers can ASSERT the
    topology they asked for before building a global mesh over it.
    Must run before anything touches a backend (jax requirement).
    """
    if coordinator_address is None and not auto:
        raise ValueError(
            "init_distributed needs a coordinator_address, or auto=True "
            "to use jax's cluster auto-detection (TPU pod / GKE / SLURM "
            "/ MPI); on a single host just build a mesh with "
            "make_1d_mesh()"
        )
    # XLA's default CPU collectives stop at the process boundary
    # ("Multiprocess computations aren't implemented on the CPU
    # backend"); gloo does the real wire exchange, which the CPU
    # dryruns of the pod-serving soak depend on. Config must land
    # before the backend initializes — this function's contract.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # a jaxlib without the knob: TPU/GPU jobs don't need it
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return DistributedContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=len(jax.devices()),
        coordinator_address=coordinator_address,
    )


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    auto: bool = False,
) -> int:
    """Join a multi-host SPMD job — the framework's ``mpirun -hostfile``
    analog (the reference's two-laptop cluster launch, README.md:16).

    Wraps :func:`jax.distributed.initialize`: every host runs the same
    program, this call makes ``jax.devices()`` span ALL hosts' chips
    (collectives then ride ICI within a slice and DCN across slices), and
    :func:`make_1d_mesh` over the global device list gives each process its
    addressable shard of the vertex partition. Returns this process's
    index. Must run before anything touches a backend (jax requirement).

    Two ways to call it:

    - explicit: pass ``coordinator_address`` (+ ``num_processes``,
      ``process_id``) — the hostfile analog;
    - ``auto=True``: delegate entirely to jax's cluster auto-detection
      (Cloud TPU pods, GKE, SLURM, Open MPI). May block retrying the
      coordinator connection if detection misfires, which is why it is
      opt-in rather than the no-argument default.

    With neither, raises :class:`ValueError` immediately — a bare call on
    an unconfigured single host would otherwise hang in connection retry;
    single-host meshes (including the 8-device virtual CPU test mesh) do
    not need this function at all.
    """
    if coordinator_address is None and not auto:
        raise ValueError(
            "init_multihost needs a coordinator_address, or auto=True to "
            "use jax's cluster auto-detection (TPU pod / GKE / SLURM / "
            "MPI); on a single host just build a mesh with make_1d_mesh()"
        )
    return init_distributed(
        coordinator_address, num_processes, process_id, auto=auto,
    ).process_index
