"""Device-mesh utilities — the framework's replacement for MPI rank/size
bookkeeping (v2/second_try.cpp:16-19, v4/mpi_bas.cpp:12-15).

The reference's process model is `mpirun -n p` CPU ranks over 1 Gb Ethernet;
here a single SPMD program spans a `jax.sharding.Mesh` whose collectives
ride ICI (intra-pod) / DCN (multi-slice), and "rank"/"size" become
`jax.lax.axis_index` / mesh axis size inside `shard_map` blocks.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

VERTEX_AXIS = "x"


def make_1d_mesh(num_devices: int | None = None, axis: str = VERTEX_AXIS) -> Mesh:
    """A 1D mesh over the first ``num_devices`` visible devices (all by
    default). Vertex arrays are 1D-sharded over this axis (the real
    owner-computes partition the reference's v4 compiled in but disabled,
    v4/comp.cu:27,99 — quirk Q4)."""
    devs = jax.devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devs)}"
            )
        devs = devs[:num_devices]
    return jax.make_mesh((len(devs),), (axis,), devices=devs)


def shard_spec(mesh: Mesh, axis: str = VERTEX_AXIS) -> NamedSharding:
    """NamedSharding that splits the leading (vertex) dimension."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
