"""Landmark distance vectors: one bitmask-packed multi-source BFS.

The build primitive the whole oracle tier stands on. The reference's
MPI version already hints at it — its bitset frontiers
(v2/second_try.cpp) pack one bit per search into machine words so one
word-wide OR advances 32 searches at once. Generalized here: K landmark
searches ride ONE level-synchronous pass, each vertex carrying a
``ceil(K/64)``-word ``uint64`` reachability mask, so constructing all K
BFS trees costs one traversal of the graph per *distinct level*, not K
traversals. The result is the ``K x n`` landmark distance matrix
(stored vertex-major as ``int16 [n, K]`` so one query's two lookups —
``dist[s]`` and ``dist[t]`` — are contiguous row reads; ``-1`` means
unreachable).

An index is immutable once built and keyed by its base snapshot's
content digest plus the store's live-graph generation tag (``gen``), so
the store's follow-the-graph accessor can refuse to serve a stale index
by one integer compare. Incremental repair (:meth:`LandmarkIndex.
repair_adds`) handles adds-only live-update batches exactly: edge
inserts can only *decrease* BFS distances, so a decrease-only
relaxation from the inserted endpoints converges to precisely the
fresh-rebuild distances (property-tested). Deletes can increase
distances — there is no cheap exact repair — so a delete invalidates
the index until the next compaction rebuild.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

# "unreachable" while relaxing in int32 (large enough that +1 cannot
# wrap, distinguishable from any real level)
_INF32 = np.int32(1 << 30)


def _as_int16_dist(d32: np.ndarray) -> np.ndarray:
    out = np.where(d32 >= _INF32, np.int32(-1), d32)
    if d32.size and int(out.max(initial=0)) > np.iinfo(np.int16).max:
        raise ValueError("graph diameter exceeds int16 distance range")
    return out.astype(np.int16)


def multi_source_bfs(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                     sources) -> np.ndarray:
    """All ``len(sources)`` BFS distance vectors in ONE pass.

    Returns ``int16 [n, K]`` (``-1`` = unreachable). Each vertex carries
    a packed ``uint64`` reachability mask (bit k = "search k has reached
    me"); one level scatters every frontier vertex's *newly gained* bits
    to its neighbors with a single ``bitwise_or.at``, so the level cost
    is O(frontier edges) however many searches are live — the v2 bitset
    idea, word-packed and vectorized.
    """
    sources = np.asarray(sources, dtype=np.int64).ravel()
    k = int(sources.size)
    if k == 0:
        return np.zeros((n, 0), dtype=np.int16)
    if sources.size and (int(sources.min()) < 0 or int(sources.max()) >= n):
        raise ValueError(f"landmark out of range for n={n}")
    words = -(-k // 64)
    mask = np.zeros((n, words), dtype=np.uint64)
    dist = np.full((n, k), _INF32, dtype=np.int32)
    bit_word = (np.arange(k) // 64).astype(np.int64)
    bit_val = (np.uint64(1) << (np.arange(k, dtype=np.uint64) % np.uint64(64)))
    np.bitwise_or.at(mask, (sources, bit_word), bit_val)
    dist[sources, np.arange(k)] = 0
    # pending = bits each vertex gained LAST level (what it must push)
    pending = np.zeros_like(mask)
    pending[sources] = mask[sources]
    frontier = np.unique(sources)
    level = 0
    while frontier.size:
        level += 1
        starts = row_ptr[frontier]
        counts = row_ptr[frontier + 1] - starts
        total = int(counts.sum())
        if total == 0:
            break
        offs = np.cumsum(counts) - counts
        src_pos = np.repeat(np.arange(frontier.size), counts)
        gather = (np.arange(total, dtype=np.int64) - offs[src_pos]
                  + starts[src_pos])
        neigh = col_ind[gather]
        # everything below is restricted to the rows this level can
        # touch — a full-matrix accumulate would cost O(n * words) per
        # LEVEL, which is worst exactly on the large-diameter graphs
        # the oracle tier targets (a 500x500 grid runs ~1000 levels)
        touched = np.unique(neigh)
        pos = np.searchsorted(touched, neigh)
        acc = np.zeros((touched.size, words), dtype=np.uint64)
        np.bitwise_or.at(acc, pos, pending[frontier[src_pos]])
        new = acc & ~mask[touched]
        gained = new.any(axis=1)
        if not gained.any():
            break
        rows = touched[gained]
        newbits = new[gained]
        mask[rows] |= newbits
        # unpack this level's arrivals into the distance matrix in ONE
        # vectorized pass: little-endian bit explosion of the gained
        # words, nonzero -> (row, search) scatter. The old per-search
        # loop cost 64 masked passes per level — the difference between
        # the sweep beating and losing to 64 per-query solves when this
        # primitive serves the msbfs query route (query/msbfs.py).
        bits = np.unpackbits(
            newbits.view(np.uint8).reshape(rows.size, words * 8),
            axis=1, bitorder="little",
        )[:, :k]
        rr, jj = np.nonzero(bits)
        dist[rows[rr], jj] = level
        # pending is zero outside the live frontier by invariant: clear
        # last level's rows, stamp this level's (a vertex in both keeps
        # only its NEW bits — the old ones were pushed above)
        pending[frontier] = 0
        pending[rows] = newbits
        frontier = rows
    return _as_int16_dist(dist)


def _device_sweep_wanted() -> bool:
    """Whether the packed sweep should run on the device tier:
    ``BIBFS_MSBFS_DEVICE`` forces it on (``1``) or off (``0``); absent
    that, the sweep follows the substrate — an accelerator backend
    routes device, the CPU substrate keeps the NumPy sweep (the same
    auto-by-substrate rule as ``QueryEngine._use_device``). Never
    initializes a backend on its own: with jax unimported the answer
    is host (an oracle build must not pay a backend boot)."""
    env = os.environ.get("BIBFS_MSBFS_DEVICE", "")
    if env in ("0", "1"):
        return env == "1"
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return jax.default_backend() != "cpu"
    except RuntimeError:
        return False


def multi_source_dist(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                      sources, *, device: bool | None = None) -> np.ndarray:
    """One packed K-source sweep, routed by tier: the jitted device
    kernel (:mod:`bibfs_tpu.ops.msbfs_device`) when a device is present
    or forced, the NumPy sweep otherwise — identical ``int16 [n, K]``
    output either way (parity-pinned in tests), so K x n oracle index
    builds come off the host whenever an accelerator exists. A device
    failure falls back to the host sweep: the oracle tier's build path
    degrades, it never dies with the accelerator."""
    use = _device_sweep_wanted() if device is None else bool(device)
    if use:
        try:
            from bibfs_tpu.ops.msbfs_device import msbfs_plane_csr

            return msbfs_plane_csr(n, row_ptr, col_ind, sources)
        except Exception:
            # host fallback intact — a broken device stack costs the
            # build its speedup, never the index
            pass
    return multi_source_bfs(n, row_ptr, col_ind, sources)


class LandmarkIndex:
    """The K landmark distance vectors of ONE graph state (module
    docstring). Immutable once built: repair returns a NEW index, so a
    query thread that grabbed a reference keeps reading a consistent
    matrix whatever the store swaps in meanwhile — mid-repair
    inconsistency would make the ``LB`` bound (a max of differences)
    exceed the true distance.

    - ``landmarks``: ``int64 [K]`` vertex ids, selection order;
    - ``dist``: ``int16 [n, K]`` — the K x n distance matrix,
      vertex-major for per-query read locality; ``-1`` = unreachable;
    - ``digest``/``version``: the base snapshot's identity;
    - ``gen``: the store's live-graph generation this index describes
      (base + however many repaired add-batches) — the
      follow-the-graph tag;
    - ``repaired_edges``: adds folded in since the last full build (the
      store's rebuild threshold counts it).
    """

    __slots__ = ("n", "landmarks", "dist", "digest", "version", "gen",
                 "built_at", "repaired_edges", "lm_col", "dist32")

    #: "unreachable" in the consult-path ``dist32`` encoding — far above
    #: any int16 distance, and ``2 * CONSULT_INF`` still fits int32, so
    #: a sum over two rows can never wrap
    CONSULT_INF = np.int32(1 << 20)

    def __init__(self, n: int, landmarks: np.ndarray, dist: np.ndarray, *,
                 digest: str = "anon", version: int = 0, gen: int = 0,
                 built_at: float | None = None, repaired_edges: int = 0):
        self.n = int(n)
        self.landmarks = np.asarray(landmarks, dtype=np.int64)
        self.dist = dist
        # the consult fast path reads THIS matrix: int32 with
        # unreachable encoded as CONSULT_INF instead of -1, so
        # ``row_s + row_t`` needs no reachability mask before the min —
        # the per-query cost is the tier's whole value proposition
        self.dist32 = np.where(
            dist < 0, self.CONSULT_INF, dist.astype(np.int32)
        )
        self.digest = str(digest)
        self.version = int(version)
        self.gen = int(gen)
        self.built_at = time.time() if built_at is None else float(built_at)
        self.repaired_edges = int(repaired_edges)
        # landmark vertex -> its column in ``dist`` — the consult fast
        # path (oracle.py): a query touching a landmark is answered by
        # ONE matrix cell, no K-wide reduction at all
        self.lm_col = {int(v): i for i, v in enumerate(self.landmarks)}

    @property
    def k(self) -> int:
        return int(self.landmarks.size)

    def is_landmark(self, v: int) -> bool:
        return v in self.lm_col

    def repair_adds(self, row_ptr, col_ind, add_adj: dict, new_adds, *,
                    gen: int | None = None) -> "LandmarkIndex":
        """The index for this graph state PLUS ``new_adds`` — exact.

        ``row_ptr``/``col_ind`` is the base snapshot's CSR and
        ``add_adj`` the overlay's full add adjacency (including
        ``new_adds``), i.e. the post-batch live graph; the overlay must
        hold no pending deletes (the store never repairs across one —
        relaxing through a deleted base edge would under-count).
        Distances under edge insertion only decrease, so a
        decrease-only relaxation seeded at the inserted endpoints and
        run to fixpoint lands on exactly the distances a fresh
        multi-source rebuild (same landmarks) would compute — the
        equivalence the property tests pin.
        """
        d = np.where(self.dist < 0, _INF32, self.dist.astype(np.int32))
        frontier: set[int] = set()
        for u, v in new_adds:
            for a, b in ((int(u), int(v)), (int(v), int(u))):
                cand = d[a] + 1
                if (cand < d[b]).any():
                    np.minimum(d[b], cand, out=d[b])
                    frontier.add(b)
        while frontier:
            nxt: set[int] = set()
            for w in frontier:
                nbrs = col_ind[row_ptr[w]: row_ptr[w + 1]]
                extra = add_adj.get(w)
                if extra:
                    nbrs = np.concatenate(
                        [nbrs, np.asarray(extra, dtype=nbrs.dtype)]
                    )
                if nbrs.size == 0:
                    continue
                cand = d[w] + 1
                sub = d[nbrs]
                newsub = np.minimum(sub, cand[None, :])
                chg = (newsub < sub).any(axis=1)
                if chg.any():
                    # duplicate neighbor rows scatter identical values,
                    # so last-write-wins is harmless
                    d[nbrs[chg]] = newsub[chg]
                    nxt.update(int(x) for x in nbrs[chg])
            frontier = nxt
        return LandmarkIndex(
            self.n, self.landmarks, _as_int16_dist(d),
            digest=self.digest, version=self.version,
            gen=self.gen + 1 if gen is None else gen,
            repaired_edges=self.repaired_edges + len(list(new_adds)),
        )

    def stats(self) -> dict:
        return {
            "k": self.k,
            "n": self.n,
            "digest": self.digest,
            "version": self.version,
            "gen": self.gen,
            "repaired_edges": self.repaired_edges,
            "age_s": round(time.time() - self.built_at, 3),
            "bytes": int(self.dist.nbytes),
        }

    def __repr__(self) -> str:
        return (f"LandmarkIndex(k={self.k}, n={self.n}, "
                f"digest={self.digest[:12]}, gen={self.gen})")


def build_index(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                k: int, *, seed: int = 0,
                landmarks: np.ndarray | None = None,
                digest: str = "anon", version: int = 0,
                gen: int = 0) -> LandmarkIndex:
    """Select landmarks (unless given) and build their distance matrix.

    With ``landmarks=`` this is the pure single-pass rebuild primitive —
    what the store's compaction rebuilds and the repair-equivalence
    tests use; without it, selection
    (:func:`bibfs_tpu.oracle.landmarks.select_landmarks`) runs its
    chunked farthest-point refinement, which already produces the
    distance rows as a by-product, so nothing is traversed twice.
    """
    from bibfs_tpu.oracle.landmarks import select_landmarks

    if landmarks is None:
        landmarks, dist = select_landmarks(
            n, row_ptr, col_ind, k, seed=seed, return_dist=True
        )
    else:
        landmarks = np.asarray(landmarks, dtype=np.int64)
        dist = multi_source_dist(n, row_ptr, col_ind, landmarks)
    return LandmarkIndex(n, landmarks, dist, digest=digest,
                         version=version, gen=gen)
