"""Seeded landmark selection: degree-seeded + farthest-point refinement.

Landmark quality decides the oracle's hit rate: ``LB == UB`` needs a
landmark sitting ON (a geodesic extension of) the query's shortest
path, and in the small-world graphs serving traffic runs over, shortest
paths funnel through the high-degree core — so the first landmarks are
the highest-degree vertices (which are also exactly the endpoints hot
traffic hammers: a query touching a landmark is answered exactly for
free). Degree alone clusters landmarks together, so the rest are
farthest-point refined: each round picks the vertices farthest from
every landmark chosen so far — which also lands landmarks in so-far
uncovered components, and component coverage is what turns
disconnected pairs into exact no-path answers (``oracle.py``).

The refinement runs in CHUNKS of the bitmask-packed multi-source BFS
(:func:`bibfs_tpu.oracle.trees.multi_source_bfs`): one packed pass per
chunk instead of one BFS per landmark, and the passes' distance rows
ARE the final index columns — selection and construction share every
traversal. Score ties break by vertex id, so selection is fully
deterministic AND shares its ranking with traffic modeling: the load
generator's skewed sampler (``serve/loadgen.sample_skewed_pairs``)
ranks hot endpoints by the same ``(degree desc, id)`` key, which makes
"the degree-seeded landmarks are the endpoints hot traffic hammers"
hold by construction, not by luck. ``seed`` is accepted (and plumbed
from ``GraphStore(oracle_seed=...)``) for forward compatibility with
stochastic refinements; current selection ignores it.
"""

from __future__ import annotations

import numpy as np

from bibfs_tpu.oracle.trees import multi_source_dist

_UNREACHED = np.int64(1 << 40)  # farther than any real distance


def select_landmarks(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                     k: int, *, seed: int = 0, chunk: int | None = None,
                     return_dist: bool = False):
    """Pick ``min(k, n)`` landmark vertices (module docstring).

    ``chunk`` is both the packed-BFS batch size and the size of the
    first, purely degree-ranked batch; the default ``max(8, k // 2)``
    spends half the landmark budget on the high-degree core (the
    hot-traffic hit-rate lever) and half on farthest-point coverage
    (the bounds-quality / component-coverage lever).

    Returns the ``int64`` landmark array, or ``(landmarks, dist)`` with
    the ``int16 [n, K]`` distance matrix when ``return_dist=True`` (the
    selection passes already computed it).
    """
    k = int(min(int(k), n))
    if k < 1:
        raise ValueError(f"need at least 1 landmark, got {k}")
    if chunk is None:
        chunk = max(8, k // 2)
    del seed  # reserved (module docstring); selection is deterministic
    deg = (row_ptr[1:] - row_ptr[:-1]).astype(np.int64)
    tie = np.arange(n)  # vertex id breaks ties (module docstring)
    chosen: list[int] = []
    cols: list[np.ndarray] = []
    taken = np.zeros(n, dtype=bool)
    # min distance to any chosen landmark; unreached sorts farthest, so
    # farthest-point naturally jumps to uncovered components
    mindist = np.full(n, _UNREACHED, dtype=np.int64)
    while len(chosen) < k:
        want = min(int(chunk), k - len(chosen))
        # score: farthest first, then degree (the hot-core bias), then
        # the seeded jitter; np.lexsort keys are least-significant first
        score = np.where(taken, np.int64(-1), mindist)
        order = np.lexsort((tie, -deg, -score))
        batch = order[:want]
        batch = batch[score[batch] >= 0]  # never re-pick a landmark
        if batch.size == 0:
            break  # fewer reachable vertices than requested landmarks
        taken[batch] = True
        chosen.extend(int(v) for v in batch)
        # tier-routed (device kernel when present, host fallback) —
        # the refinement rows ARE index columns, so they must come
        # from the same routed sweep the rebuild uses
        d = multi_source_dist(n, row_ptr, col_ind, batch)
        cols.append(d)
        d64 = np.where(d < 0, _UNREACHED, d.astype(np.int64))
        np.minimum(mindist, d64.min(axis=1), out=mindist)
    landmarks = np.asarray(chosen, dtype=np.int64)
    if not return_dist:
        return landmarks
    dist = (np.concatenate(cols, axis=1) if cols
            else np.zeros((n, 0), dtype=np.int16))
    return landmarks, dist
