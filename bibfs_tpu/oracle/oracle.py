"""Per-query answering over one landmark index: bounds, exact kinds.

For a query ``(s, t)`` and landmark distance vectors ``d(L, .)`` the
triangle inequality gives, over every landmark L reaching both
endpoints::

    LB = max_L |d(s, L) - d(L, t)|     <=  d(s, t)  <=
    UB = min_L  d(s, L) + d(L, t)

(the graph is undirected, so ``d(s, L) = d(L, s)``). The oracle serves
EXACT answers in three cases and never guesses:

- **landmark** — an endpoint IS a landmark L: ``d(s, t) = d(L, other)``
  directly (this falls out of the bounds — ``d(s, L) = 0`` forces
  ``LB == UB`` — but is tagged as its own hit kind: hot-endpoint
  traffic is the tier's whole motivation);
- **tight** — ``LB == UB``: some landmark lies on a shortest path (or
  a geodesic extension of one), so the bound pair pins the distance;
- **disconnected** — the landmark reach-sets of s and t are disjoint
  and at least one is non-empty: a component containing a landmark
  cannot be the component of a vertex that landmark does not reach, so
  the pair is PROVABLY in different components — exact "no path" with
  no traversal (on sparse G(n, p) serving graphs a sizable fraction of
  all pairs, the queries whose naive answer costs a full component
  sweep).

Everything else returns either usable **bounds** (``LB < UB``: the
engine attaches UB as a search cutoff — seeding bidirectional BFS's
meet bound with a KNOWN upper bound prunes exploration past it while
staying exact) or a **miss** (neither endpoint reached by any landmark:
the oracle knows nothing). Hit kinds land in
``bibfs_oracle_hits_total{oracle,kind}``.

Oracle-served results carry ``path=None``: the tier trades path
materialization for lookup speed, exactly like a negative cache entry —
``found``/``hops`` are exact, and callers needing the vertex list fall
through to a solver.
"""

from __future__ import annotations

import numpy as np

from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.oracle.trees import LandmarkIndex
from bibfs_tpu.solvers.api import BFSResult

# consult outcomes that SERVE the query (route="oracle"); "bounds" only
# arms a cutoff and "miss" is a pure fall-through
ORACLE_SERVED_KINDS = ("landmark", "tight", "disconnected")
ORACLE_KINDS = ORACLE_SERVED_KINDS + ("bounds", "miss")


def oracle_cells(label: str) -> dict:
    """Mint (or re-fetch) the ``bibfs_oracle_hits_total`` cells for one
    oracle instance label — the store pre-mints them at graph
    registration so a scrape shows the family at zero, and carries them
    across index rebuilds so one graph's hit history survives its
    follow-the-graph swaps."""
    hits = REGISTRY.counter(
        "bibfs_oracle_hits_total",
        "Distance-oracle consults by outcome kind (landmark/tight/"
        "disconnected serve exactly; bounds arms a search cutoff; "
        "miss falls through)",
        ("oracle", "kind"),
    )
    return {k: hits.labels(oracle=label, kind=k) for k in ORACLE_KINDS}


class OracleAnswer:
    """One consult's outcome. ``result`` is an exact
    :class:`~bibfs_tpu.solvers.api.BFSResult` for the served kinds,
    None for ``bounds`` (where ``lb``/``ub`` carry the information)."""

    __slots__ = ("kind", "result", "lb", "ub")

    def __init__(self, kind: str, result: BFSResult | None = None,
                 lb: int | None = None, ub: int | None = None):
        self.kind = kind
        self.result = result
        self.lb = lb
        self.ub = ub

    def __repr__(self) -> str:
        return f"OracleAnswer({self.kind}, lb={self.lb}, ub={self.ub})"


class DistanceOracle:
    """Query answering over one immutable :class:`LandmarkIndex`.

    Stateless beyond the index reference and its metric cells, so the
    store can hot-swap oracles by pointer assignment (the
    follow-the-graph swap) while in-flight consults finish on the index
    they grabbed. ``metrics_label`` is the ``oracle=`` label its
    registry cells carry (engines/stores pass their own so one
    ``/metrics`` scrape separates instances); pass ``cells`` to carry
    the counters across index swaps of the same graph.
    """

    def __init__(self, index: LandmarkIndex, *,
                 metrics_label: str = "oracle", cells: dict | None = None):
        self.index = index
        self.metrics_label = metrics_label
        self._m = oracle_cells(metrics_label) if cells is None else cells

    @property
    def cells(self) -> dict:
        return self._m

    def consult(self, src: int, dst: int) -> OracleAnswer | None:
        """The oracle's whole per-query cost. Two tiers:

        - **landmark fast path** — an endpoint IS a landmark L: the
          answer is ONE matrix cell, ``d(L, other)`` (exact by
          definition; ``CONSULT_INF`` there proves the pair
          disconnected — L reaches every vertex of its own component).
          A dict probe plus one scalar read, no K-wide reduction: hot
          endpoints are degree-ranked and so are the first landmarks,
          so under skewed traffic this tier answers most consults;
        - **general path** — two contiguous row reads of the
          INF-encoded ``dist32`` matrix and a handful of vectorized
          reductions over K values (unreachable = ``CONSULT_INF``, so
          the UB needs no reachability mask: an unreachable landmark's
          sum is astronomically large and simply loses the min).

        Returns None on a miss (and counts it)."""
        idx = self.index
        inf = idx.CONSULT_INF
        col = idx.lm_col.get(src)
        other = dst
        if col is None:
            col = idx.lm_col.get(dst)
            other = src
        if col is not None:
            d = int(idx.dist32[other, col])
            if d < inf:
                self._m["landmark"].inc()
                return OracleAnswer(
                    "landmark",
                    BFSResult(True, d, None, None, 0.0, 0, 0),
                    lb=d, ub=d,
                )
            self._m["disconnected"].inc()
            return OracleAnswer(
                "disconnected",
                BFSResult(False, None, None, None, 0.0, 0, 0),
            )
        ds = idx.dist32[src]
        dt = idx.dist32[dst]
        su = ds + dt
        ub = int(su.min())
        if ub < inf:  # some landmark reaches BOTH endpoints
            # |ds - dt| is only a valid bound over both-reachable
            # landmarks; su < INF is exactly that set (each term is
            # either a real distance << INF or the INF sentinel)
            lb = int(np.abs(ds - dt)[su < inf].max())
            if lb == ub:
                # an endpoint that IS a landmark took the fast path
                # above, so a pinned bound here means some OTHER
                # landmark sits on (a geodesic extension of) the path
                self._m["tight"].inc()
                return OracleAnswer(
                    "tight",
                    BFSResult(True, ub, None, None, 0.0, 0, 0),
                    lb=lb, ub=ub,
                )
            self._m["bounds"].inc()
            return OracleAnswer("bounds", None, lb=lb, ub=ub)
        if (ds < inf).any() or (dt < inf).any():
            # disjoint reach-sets, one non-empty: one endpoint shares a
            # component with some landmark the other provably does not —
            # different components, exact no-path (module docstring)
            self._m["disconnected"].inc()
            return OracleAnswer(
                "disconnected",
                BFSResult(False, None, None, None, 0.0, 0, 0),
            )
        self._m["miss"].inc()
        return None

    def stats(self) -> dict:
        return {
            "index": self.index.stats(),
            "hits": {k: c.value for k, c in self._m.items()},
        }
