"""Landmark distance-oracle tier — answer hot traffic with no BFS at all.

The serving stack's third answering tier, above the
:class:`~bibfs_tpu.serve.cache.DistanceCache` and below nothing: a small
precomputed structure (K landmark BFS trees per graph snapshot) that
answers most queries at memory-lookup speed and hands the rest a
provable upper bound the solver can use as a search cutoff. Like
"Compression and Sieve" (PAPERS.md), the win comes from sieving away
traversal work before it happens, not from making the traversal faster.

- :mod:`bibfs_tpu.oracle.landmarks` — seeded landmark selection
  (degree-seeded + farthest-point refinement);
- :mod:`bibfs_tpu.oracle.trees` — the bitmask-packed multi-source BFS
  that builds all K landmark distance vectors in one pass (the MPI
  reference's v2 bitset frontiers, generalized), packaged as an
  immutable :class:`LandmarkIndex` keyed by the snapshot's content
  digest, plus exact adds-only incremental repair;
- :mod:`bibfs_tpu.oracle.oracle` — the :class:`DistanceOracle` that
  turns one index into per-query answers: ``LB = max_L |d(s,L) -
  d(L,t)|``, ``UB = min_L d(s,L) + d(L,t)``, served exact when
  ``LB == UB`` (plus endpoint-is-a-landmark and provably-disconnected
  pairs), bounds otherwise.

Lifecycle (background builds, incremental repair from live edge
updates, atomic follow-the-graph swap) lives in
:class:`bibfs_tpu.store.GraphStore`; routing (oracle consulted before
the distance cache, ``route="oracle"``) lives in the engines.
"""

from bibfs_tpu.oracle.landmarks import select_landmarks  # noqa: F401
from bibfs_tpu.oracle.oracle import (  # noqa: F401
    DistanceOracle,
    OracleAnswer,
    ORACLE_SERVED_KINDS,
    oracle_cells,
)
from bibfs_tpu.oracle.trees import (  # noqa: F401
    LandmarkIndex,
    build_index,
    multi_source_bfs,
)
