"""Device-tier solvers for the weighted and k-shortest query kinds.

Two jitted programs, both reusing the serving stack's ELL machinery:

- **delta-stepping** (:func:`delta_stepping_device`): the bucket
  relaxation loop of :mod:`bibfs_tpu.query.weighted` as ONE
  ``lax.while_loop`` program — light edges (weight <= delta) relaxed
  to a fixpoint per bucket, heavy edges once per settled bucket, every
  relaxation one ELL-wide scatter-min (``dist.at[tgt].min(cand)`` —
  the segment-min over edge relaxations) instead of the host's
  per-bucket gather/sort/unique pass. Exact for any positive delta;
  the s-t early exit (every remaining bucket's floor beyond
  ``dist[dst]``) matches the host rung's pruning. The kernel returns
  the distance VECTOR; the path descends host-side over the CSR
  weights (strictly-decreasing exact sums — integer weights are exact
  in f32 far beyond any bench graph's diameter).
- **restricted batch BFS** (:func:`restricted_batch_dists` /
  :func:`restricted_batch_paths`): one ``[n_pad, B]`` plane solves
  every spur candidate of a Yen iteration at once — per-candidate
  node masks ride a blocked plane, per-candidate banned spur edges
  are folded into the level-1 seeding host-side (every banned edge
  leaves the spur vertex, so the first hop IS the edge restriction),
  and each query column freezes the level after its ``dst`` is
  reached. Paths descend through the SAME canonical min-id rule as
  the host rung (:func:`bibfs_tpu.query.kshortest.descend_min_id`),
  so batched k-shortest output is IDENTICAL to host Yen's, not just
  equal-length.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from bibfs_tpu.ops.pallas_expand import _slot_pad, sentinel_transposed_table

#: "unreachable" on the f32 distance line (far above any real path
#: weight; +w cannot reach another finite value's range)
F_INF = np.float32(3e38)

#: unreachable in the restricted-BFS int32 planes
INF32 = 1 << 30


# ---- device delta-stepping -------------------------------------------

def _build_delta_kernel(n_pad: int, width: int):
    """The jitted single-source delta-stepping program for one ELL
    geometry. Signature ``(tgt, wts, src, dst, delta) -> (dist,
    buckets, relaxations)``: ``tgt`` int32 ``[n_pad, width]`` neighbor
    targets with dead slots pointing at the dump row ``n_pad``;
    ``wts`` f32 ``[n_pad, width]`` with ``+inf`` at dead slots (their
    candidates never win the scatter-min); ``src``/``dst``/``delta``
    traced, so one compiled program serves every query and seed of
    the geometry."""

    def delta_kernel(tgt, wts, src, dst, delta):
        light = wts <= delta
        dist0 = jnp.full((n_pad,), F_INF, jnp.float32).at[src].set(0.0)

        def in_bucket(dist, bi):
            return (dist >= bi * delta) & (dist < (bi + 1) * delta)

        def relax(dist, frontier, sel):
            """One ELL-wide relaxation pass from ``frontier`` over the
            ``sel`` edge class, formulated as a PULL: the graph is
            undirected and the weight hash symmetric, so every vertex
            can gather ``dist[nbr] + w`` over its own row and take the
            row min — the segment-min over edge relaxations as pure
            contiguous gathers (the scatter-min formulation lowers to
            element-at-a-time loops on CPU; measured ~20x slower)."""
            fr_p = jnp.concatenate(
                [frontier, jnp.zeros((1,), bool)]
            )  # dump-row slot: never a frontier source
            d_p = jnp.concatenate([dist, jnp.full((1,), F_INF)])
            cand = jnp.where(
                fr_p[tgt] & sel, d_p[tgt] + wts, F_INF
            )
            nd = jnp.minimum(dist, jnp.min(cand, axis=1))
            return nd, jnp.sum(cand < F_INF)

        def outer_cond(st):
            dist, bi, _buckets, _relaxed = st
            pending = jnp.any((dist < F_INF) & (dist >= bi * delta))
            # dst settled: every remaining vertex is provably farther
            return pending & (dist[dst] >= bi * delta)

        def outer_body(st):
            dist, bi, buckets, relaxed = st

            def light_cond(s):
                return s[1]

            def light_body(s):
                d, _changed, rel = s
                nd, cnt = relax(d, in_bucket(d, bi), light)
                return nd, jnp.any(nd < d), rel + cnt

            # light fixpoint: reinsertions within the bucket re-relax
            # (members can only be ADDED — dist never drops below the
            # bucket floor under light relaxation from inside it)
            dist, _c, relaxed = jax.lax.while_loop(
                light_cond, light_body, (dist, True, relaxed)
            )
            settled = in_bucket(dist, bi)
            had = jnp.any(settled)
            # heavy phase: once, from everything the bucket settled
            dist, cnt = relax(dist, settled, ~light)
            return (
                dist, bi + 1,
                buckets + had.astype(jnp.int32), relaxed + cnt,
            )

        dist, _bi, buckets, relaxed = jax.lax.while_loop(
            outer_cond, outer_body,
            (dist0, jnp.int32(0), jnp.int32(0), jnp.int32(0)),
        )
        return dist, buckets, relaxed

    return delta_kernel


@lru_cache(maxsize=None)
def _get_delta_kernel(n_pad: int, width: int):
    return jax.jit(_build_delta_kernel(n_pad, width))


def delta_tables(ell, seed: int):
    """The device relaxation tables for one (ELL, seed): masked
    targets (dead slots -> the dump row) and the ELL-aligned derived
    weights (:func:`bibfs_tpu.query.weighted.ell_weights` — the same
    hash the CSR derivation uses). Uploaded once and memoized per
    runtime by the serving layer."""
    from bibfs_tpu.query.weighted import ell_weights

    alive = (
        np.arange(ell.width, dtype=np.int64)[None, :]
        < ell.deg[:, None]
    )
    tgt = np.where(alive, ell.nbr.astype(np.int32), np.int32(ell.n_pad))
    wts = ell_weights(ell.nbr, ell.deg, seed)
    return jnp.asarray(tgt), jnp.asarray(wts)


def delta_stepping_device(n: int, row_ptr, col_ind, weights, tables,
                          src: int, dst: int, *,
                          delta: float | None = None):
    """Exact single-source shortest path to ``dst`` on the device tier
    (module docstring). ``weights`` is the CSR-aligned float64
    derivation (the path-descent truth and the delta default);
    ``tables`` the uploaded ``(tgt, wts)`` pair from
    :func:`delta_tables`. Returns a
    :class:`~bibfs_tpu.query.types.WeightedResult` matching the host
    rung's ``found``/``dist``/path-validity contract."""
    import time

    from bibfs_tpu.query.types import WeightedResult

    t0 = time.perf_counter()
    src, dst = int(src), int(dst)
    if delta is None:
        delta = float(weights.mean()) if weights.size else 1.0
    delta = float(delta)
    if delta <= 0:
        raise ValueError(f"delta must be > 0, got {delta}")
    tgt, wts = tables
    n_pad = int(tgt.shape[0])
    kern = _get_delta_kernel(n_pad, int(tgt.shape[1]))
    dist, buckets, relaxed = jax.block_until_ready(kern(
        tgt, wts, jnp.int32(src), jnp.int32(dst),
        jnp.float32(delta),
    ))
    dval = float(np.asarray(dist)[dst])
    found = dval < float(F_INF) / 2
    path = None
    if found:
        path = _descend_weighted(
            np.asarray(dist), row_ptr, col_ind, weights, src, dst
        )
    return WeightedResult(
        found=found,
        dist=dval if found else None,
        hops=len(path) - 1 if found else None,
        path=path,
        time_s=time.perf_counter() - t0,
        relaxations=int(relaxed),
        buckets=int(buckets),
    )


def _descend_weighted(dist, row_ptr, col_ind, weights, src, dst):
    """A shortest weighted path off the distance vector: from ``dst``,
    step to the lowest-CSR-position neighbor whose distance plus the
    edge weight lands exactly on ours (integer weights: the f32 sums
    are exact, the float64 CSR weights agree bit-for-bit)."""
    path = [dst]
    cur = dst
    while cur != src:
        lo, hi = int(row_ptr[cur]), int(row_ptr[cur + 1])
        row = col_ind[lo:hi]
        cand = dist[row] + weights[lo:hi].astype(np.float32)
        step = np.flatnonzero(
            np.isclose(cand, dist[cur], rtol=0.0, atol=1e-3)
        )
        if step.size == 0:  # cannot happen on a consistent vector
            return None
        cur = int(row[step[0]])
        path.append(cur)
    path.reverse()
    return path


# ---- batched restricted BFS (Yen spur candidates) --------------------

def _pad_candidates(b: int) -> int:
    """Candidate columns padded to sublane groups (a Yen iteration has
    path-length many candidates — pow2 rungs keep the compiled-program
    ladder bounded without 128-lane waste on short paths)."""
    b = max(8, int(b))
    return 1 << (b - 1).bit_length()


def _build_restricted_kernel(n_pad2: int, wp: int, tc: int, b: int):
    """The jitted batched restricted BFS for one padded geometry.
    Signature ``(nbr, deg, seed_dist, blocked, dsts) -> dist``:
    ``seed_dist`` int32 ``[n_pad2, b]`` carries level 0 (the spur) and
    the ALLOWED level-1 frontier per candidate (banned spur edges
    already folded out host-side); ``blocked`` int8 marks each
    candidate's banned nodes; every column freezes after the level
    that reaches its ``dst`` completes, so all distances ``<=
    dist[dst]`` are final — exactly what the canonical descent
    reads."""
    num_chunks = n_pad2 // tc

    def restricted_kernel(nbr, deg, seed_dist, blocked, dsts):
        nbr_t = sentinel_transposed_table(nbr, deg, n_pad2, n_pad2, wp)
        qi = jnp.arange(b, dtype=jnp.int32)
        frontier0 = (seed_dist == 1).astype(jnp.int8)

        def cond(st):
            return st[3]

        def body(st):
            dist, frontier, level, _go = st
            level = level + 1
            # per-candidate freeze: once dst is stamped the column
            # stops discovering (its plane below dst's level is final)
            act = (dist[dsts, qi] >= INF32).astype(jnp.int8)
            fr_p = jnp.concatenate(
                [frontier, jnp.zeros((1, b), jnp.int8)]
            )  # sentinel index n_pad2 reads the zero dump row

            def chunk(carry, c):
                dist_c2, newf, cnt = carry
                r0 = c * tc
                nbr_c = jax.lax.dynamic_slice(nbr_t, (0, r0), (wp, tc))
                anyh = fr_p[nbr_c[0]]
                for i in range(1, wp):
                    anyh = anyh | fr_p[nbr_c[i]]
                d_c = jax.lax.dynamic_slice(dist, (r0, 0), (tc, b))
                blk_c = jax.lax.dynamic_slice(blocked, (r0, 0), (tc, b))
                nf = jnp.where(
                    (d_c >= INF32) & (blk_c == 0), anyh, 0
                ) * act[None, :]
                d2 = jnp.where(nf > 0, level, d_c)
                return (
                    jax.lax.dynamic_update_slice(dist_c2, d2, (r0, 0)),
                    jax.lax.dynamic_update_slice(newf, nf, (r0, 0)),
                    cnt + jnp.sum(nf.astype(jnp.int32), axis=0),
                ), None

            (dist, newf, cnt), _ = jax.lax.scan(
                chunk,
                (dist, jnp.zeros((n_pad2, b), jnp.int8),
                 jnp.zeros((b,), jnp.int32)),
                jnp.arange(num_chunks, dtype=jnp.int32),
            )
            return dist, newf, level, jnp.any(cnt > 0)

        st = (seed_dist, frontier0, jnp.int32(1),
              jnp.any(frontier0 > 0))
        dist, _f, _lvl, _go = jax.lax.while_loop(cond, body, st)
        return dist

    return restricted_kernel


@lru_cache(maxsize=None)
def _get_restricted_kernel(n_pad2: int, wp: int, tc: int, b: int):
    return jax.jit(_build_restricted_kernel(n_pad2, wp, tc, b))


def restricted_batch_dists(g, row_ptr, col_ind, dst: int, cands):
    """Solve one Yen iteration's spur candidates as ONE batched device
    program over the uploaded serving table ``g``
    (:class:`~bibfs_tpu.solvers.dense.DeviceGraph`, plain ELL).
    ``cands`` is the ``(spur, banned_nodes set, banned_edges set)``
    list the host solver takes; returns the int32 ``[n, B]`` restricted
    distance planes (INF32 = unreached)."""
    from bibfs_tpu.query.kshortest import first_hops

    if getattr(g, "tier_meta", ()):
        raise ValueError("batched restricted BFS is plain-ELL only")
    b_pad = _pad_candidates(len(cands))
    wp = _slot_pad(g.width)
    # the per-chunk gathered block is [wp, tc, b] int8 — reuse the
    # msbfs budget discipline at the int8 itemsize
    from bibfs_tpu.ops.msbfs_device import MSBFS_CHUNK_BUDGET_BYTES

    raw = MSBFS_CHUNK_BUDGET_BYTES // max(wp * b_pad, 1)
    tc = int(max(8, min(g.n_pad, (raw // 8) * 8)))
    n_pad2 = -(-g.n_pad // tc) * tc
    seed = np.full((n_pad2, b_pad), INF32, dtype=np.int32)
    blocked = np.zeros((n_pad2, b_pad), dtype=np.int8)
    dsts = np.zeros(b_pad, dtype=np.int32)
    mask = np.zeros(g.n, dtype=bool)
    for j, (spur, banned_nodes, banned_edges) in enumerate(cands):
        spur = int(spur)
        mask[:] = False
        for v in banned_nodes:
            mask[int(v)] = True
        rows = np.fromiter(
            (int(v) for v in banned_nodes), dtype=np.int64,
            count=len(banned_nodes),
        )
        blocked[rows, j] = 1
        seed[spur, j] = 0
        hops = first_hops(
            row_ptr, col_ind, spur,
            banned_mask=mask, banned_edges=banned_edges,
        )
        seed[hops, j] = np.minimum(seed[hops, j], 1)
        dsts[j] = int(dst)
    kern = _get_restricted_kernel(n_pad2, wp, tc, b_pad)
    dist = jax.block_until_ready(kern(
        g.nbr, g.deg, jnp.asarray(seed), jnp.asarray(blocked),
        jnp.asarray(dsts),
    ))
    return np.asarray(dist)[: g.n, : len(cands)]


def restricted_batch_paths(g, n, row_ptr, col_ind, dst: int, cands):
    """The device ``spur_batch`` for
    :func:`bibfs_tpu.query.kshortest.yen_k_shortest`: batched
    restricted distance planes + the canonical min-id descent — one
    tail-path-or-None per candidate, IDENTICAL to the host solver's
    answers."""
    from bibfs_tpu.query.kshortest import descend_min_id

    if not cands:
        return []
    planes = restricted_batch_dists(g, row_ptr, col_ind, dst, cands)
    out = []
    for j, (spur, _bn, banned_edges) in enumerate(cands):
        col = planes[:, j]
        dist = np.where(col >= INF32, np.int32(-1), col)
        out.append(descend_min_id(
            row_ptr, col_ind, dist, spur, dst,
            banned_edges=banned_edges,
        ))
    return out
