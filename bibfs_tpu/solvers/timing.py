"""Shared search-timing protocol for every backend.

One rule, applied uniformly: build the graph representation ONCE, warm up
once (JIT compile / first-touch excluded), then time ``repeats`` searches
with ZERO device→host traffic between dispatches, and materialize the
result payload once at the end. A single scalar readback between two
dispatches stalls tunneled-TPU runtimes by ~200ms (measured), and the
reference likewise keeps its timed regions free of result readout
(v1/main-v1.cpp:49-82, v2/second_try.cpp:66-131, v4/mpi_bas.cpp:76-134).

The reported statistic is the MEDIAN of the repeat times, stamped into the
returned result's ``time_s`` so every consumer (CLI, sweep harness, root
bench.py) agrees on what the number means.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import numpy as np

from bibfs_tpu.solvers.api import BFSResult


def timed_repeats(
    dispatch: Callable[[], object],
    materialize: Callable[[], BFSResult] | None,
    repeats: int,
) -> tuple[list[float], BFSResult | None]:
    """Warm up, time ``repeats`` calls of ``dispatch`` (which must not read
    device results back), then call ``materialize`` once (skipped when
    None — callers that sweep several configs must defer ALL value
    readbacks past ALL timing loops; see ``time_search_only``'s account of
    the permanent post-readback dispatch degradation on tunneled runtimes).

    Returns ``(times_s, result)`` with ``result.time_s`` = median of times.
    """
    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    dispatch()  # warm-up: JIT compile / first-touch excluded from timing
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        dispatch()
        times.append(time.perf_counter() - t0)
    if materialize is None:
        return times, None
    result = materialize()
    return times, dataclasses.replace(result, time_s=float(np.median(times)))


def time_backend(
    backend: str,
    n: int,
    edges: np.ndarray,
    src: int,
    dst: int,
    *,
    repeats: int = 5,
    num_devices: int | None = None,
    mode: str = "sync",
    layout: str = "ell",
) -> tuple[list[float], BFSResult]:
    """Build the graph once for ``backend`` and run the timing protocol.

    The single entry point behind ``bibfs-solve --repeat`` and the
    ``bibfs-bench`` sweep, so all surfaces report the same statistic.
    """
    if backend == "serial":
        from bibfs_tpu.graph.csr import build_csr
        from bibfs_tpu.solvers.serial import solve_serial_csr

        row_ptr, col_ind = build_csr(n, edges)
        return timed_repeats(
            lambda: solve_serial_csr(n, row_ptr, col_ind, src, dst),
            lambda: solve_serial_csr(n, row_ptr, col_ind, src, dst),
            repeats,
        )
    if backend == "native":
        from bibfs_tpu.solvers.native import NativeGraph, solve_native_graph

        g = NativeGraph.build(n, edges)
        return timed_repeats(
            lambda: solve_native_graph(g, src, dst),
            lambda: solve_native_graph(g, src, dst),
            repeats,
        )
    if backend == "dense":
        from bibfs_tpu.solvers.dense import DeviceGraph, time_search

        g = DeviceGraph.build(n, edges, layout=layout)
        return time_search(g, src, dst, repeats=repeats, mode=mode)
    if backend == "sharded":
        from bibfs_tpu.parallel.mesh import make_1d_mesh
        from bibfs_tpu.solvers.sharded import ShardedGraph, time_search

        mesh = make_1d_mesh(num_devices)
        g = ShardedGraph.build(n, edges, mesh, layout=layout)
        return time_search(g, src, dst, repeats=repeats, mode=mode)
    raise KeyError(f"unknown backend {backend!r}")
