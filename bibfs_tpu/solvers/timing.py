"""Shared search-timing protocol for every backend.

One rule, applied uniformly: build the graph representation ONCE, warm up
once (JIT compile / first-touch excluded), then time ``repeats`` searches
with execution FORCED inside every timed interval, and materialize the
full result payload once at the end. The reported statistic is the MEDIAN
of the repeat times, stamped into the returned result's ``time_s`` so
every consumer (CLI, sweep harness, root bench.py) agrees on what the
number means.

Why forcing matters — the tunneled-runtime laziness finding (measured
2026-07-29 on the axon-tunneled v5e): on that backend
``jax.block_until_ready`` returns WITHOUT waiting for device execution.
Work queues lazily and only a device->host VALUE read forces it: five
"blocked" 100k solves enqueued in 0.45 ms, then — after a 10 s sleep that
real execution would have long finished within — the first scalar read
took 1.75 s, i.e. the solves only ran when read. Every timing loop that
trusts ``block_until_ready`` therefore measures the ENQUEUE rate (tens of
us) instead of the execution time (~170 ms/solve at 100k through the
tunnel), a ~2500x fiction. The first value read also flips the process
into a synchronous dispatch mode, so a warm-up read pins all subsequent
repeats to honest per-solve latency.

The ``force`` callback here reads ONE scalar from the dispatch result (4
bytes — negligible next to any real search, and the reference's timed
regions also end by using their result: v1/main-v1.cpp:82-93). Host
backends (serial/native) pass ``force=None``; their return values are
already real.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import numpy as np

from bibfs_tpu.solvers.api import BFSResult


def force_scalar(out) -> None:
    """Default ``force``: read one element of the first array leaf of
    ``out`` (works for a bare array, a solver output tuple — leaf 0 is the
    ``best`` distance — or any pytree), compelling the runtime to actually
    execute everything queued for it."""
    import jax

    np.asarray(jax.tree.leaves(out)[0]).ravel()[0]


def timed_repeats(
    dispatch: Callable[[], object],
    materialize: Callable[[], BFSResult] | None,
    repeats: int,
    force: Callable[[object], None] | None = None,
) -> tuple[list[float], BFSResult | None]:
    """Warm up (compile + flip any lazy runtime into its synchronous mode),
    then time ``repeats`` calls of ``dispatch``, applying ``force`` to each
    result INSIDE the timed interval so lazily-deferred execution cannot
    masquerade as speed (module docstring), then call ``materialize`` once
    (skipped when None).

    Returns ``(times_s, result)`` with ``result.time_s`` = median of times.
    """
    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    out = dispatch()  # warm-up: JIT compile / first-touch excluded
    if force is not None:
        force(out)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = dispatch()
        if force is not None:
            force(out)
        times.append(time.perf_counter() - t0)
    if materialize is None:
        return times, None
    result = materialize()
    return times, dataclasses.replace(result, time_s=float(np.median(times)))


def timed_batch_repeats(
    dispatch: Callable[[], object],
    repeats: int,
    force: Callable[[object], None] = force_scalar,
) -> tuple[list[float], object]:
    """The batch variant of :func:`timed_repeats`: warm up once, then time
    ``repeats`` whole-batch dispatches with execution forced inside every
    interval, and return ``(times_s, last_out)`` so the caller can
    materialize the final outputs once. Shared by the dense and sharded
    batch solvers so the protocol cannot diverge between them."""
    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    out = dispatch()  # warm-up: compile excluded, lazy runtime flipped
    force(out)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = dispatch()
        force(out)
        times.append(time.perf_counter() - t0)
    return times, out


def time_backend(
    backend: str,
    n: int,
    edges: np.ndarray,
    src: int,
    dst: int,
    *,
    repeats: int = 5,
    num_devices: int | None = None,
    mode: str = "sync",
    layout: str = "ell",
    rows: int | None = None,
    cols: int | None = None,
    unroll: int = 1,
) -> tuple[list[float], BFSResult]:
    """Build the graph once for ``backend`` and run the timing protocol.

    The single entry point behind ``bibfs-solve --repeat`` and the
    ``bibfs-bench`` sweep, so all surfaces report the same statistic.
    """
    if backend == "serial":
        from bibfs_tpu.graph.csr import build_csr
        from bibfs_tpu.solvers.serial import solve_serial_csr

        row_ptr, col_ind = build_csr(n, edges)
        return timed_repeats(
            lambda: solve_serial_csr(n, row_ptr, col_ind, src, dst),
            lambda: solve_serial_csr(n, row_ptr, col_ind, src, dst),
            repeats,
        )
    if backend == "native":
        from bibfs_tpu.solvers.native import NativeGraph, solve_native_graph

        g = NativeGraph.build(n, edges)
        return timed_repeats(
            lambda: solve_native_graph(g, src, dst),
            lambda: solve_native_graph(g, src, dst),
            repeats,
        )
    if backend == "dense":
        from bibfs_tpu.solvers.dense import DeviceGraph, time_search

        g = DeviceGraph.build(n, edges, layout=layout)
        return time_search(g, src, dst, repeats=repeats, mode=mode,
                           unroll=unroll)
    if backend == "sharded":
        from bibfs_tpu.parallel.mesh import make_1d_mesh
        from bibfs_tpu.solvers.sharded import (
            ShardedGraph,
            default_pad_multiple,
            time_search,
        )

        mesh = make_1d_mesh(num_devices)
        g = ShardedGraph.build(
            n, edges, mesh, layout=layout,
            pad_multiple=default_pad_multiple(
                mode, int(mesh.devices.size)
            ),
        )
        return time_search(g, src, dst, repeats=repeats, mode=mode,
                           unroll=unroll)
    if backend == "sharded2d":
        from bibfs_tpu.solvers.sharded2d import (
            Sharded2DGraph,
            time_search_2d,
        )

        g = Sharded2DGraph.build(
            n, edges, rows=rows, cols=cols, num_devices=num_devices
        )
        return time_search_2d(g, src, dst, repeats=repeats, mode=mode)
    raise KeyError(f"unknown backend {backend!r}")
