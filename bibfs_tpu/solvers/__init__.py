from bibfs_tpu.solvers.api import BFSResult, solve, SOLVERS  # noqa: F401
