"""2D-partitioned multi-chip bidirectional BFS — communication that scales
with the MESH, not just the graph.

The 1D solver (:mod:`bibfs_tpu.solvers.sharded`) ships the whole bitpacked
frontier to every device each pull level: O(n/8) wire bytes per device no
matter how many chips participate (the v2 bitset exchange done right,
second_try.cpp:53-62). That is the right design at 8 chips; at pod scale
the classic fix — Graph500-style 2D adjacency partitioning (Buluç &
Madduri; "Compression and Sieve", arxiv 1208.5542, PAPERS.md) — bounds
per-device traffic by the MESH shape:

- the adjacency is blocked over an ``R x C`` mesh: device (r, c) stores,
  for the vertices of row range r (n/R of them), only their neighbors
  inside column range c (n/C ids, stored LOCALIZED so the expansion
  gather is into a column-local frontier);
- per-vertex state (frontier/dist/parent) is 1D-sharded over all R*C
  devices in row-major linear order (device (r, c) owns slice r*C + c);
- one level = three steps, each riding ONE mesh axis:
    1. **transpose** (``ppermute`` over the flattened mesh): each device's
       owned frontier slice moves to the device whose column gather needs
       it — fixed permutation, n_loc/8 bytes;
    2. **expand** (``all_gather`` over axis ``r``, bitpacked): devices of
       grid column c reconstruct column range c's frontier — n/(8C) bytes
       per device, vs n/8 in the 1D solver;
    3. **fold** (``pmax`` over axis ``c``): per-row-range parent
       candidates merge across the row — 4*n/R bytes; every device then
       keeps exactly its owned slice (the fold chunk IS the owned slice,
       by construction of the row-major layout).

Semantics match the 1D/dense solvers exactly: level-synchronous pull,
deterministic parents (first ELL slot within a block, max across blocks),
the provably-correct ``lvl_s + lvl_t >= best`` termination, true hop
counts.

**Tiered blocks** (capability parity with the 1D/dense tiered-ELL layout,
:func:`bibfs_tpu.graph.csr.build_tiered`): a single block width pads every
(vertex, column-block) group to the max group size across the whole grid,
which on skewed (RMAT) graphs blows the table up by the hub degree. The
builder instead picks the base width minimizing total padded slots and
spills hub groups into geometric per-block overflow tiers
``(tnbr [R, C, K_pad, Wt], tids [R, C, K_pad])`` indexed by block-local
row ids; the expansion adds one small gather + scatter-max per tier. On
low-skew graphs the plan degenerates to zero tiers — identical layout and
cost to the plain blocks. ``Sharded2DGraph.padded_slots`` reports the
footprint either way.

**Pull-only, by design** (the measured case, PERF_NOTES.md): Beamer push
buys the 1D solver a frontier-size-PROPORTIONAL level cost because its
exchange is O(n) regardless; the 2D exchange is already bounded by the
mesh — O(n/C + n/R) wire bytes per level, frontier-size-independent — so
a push leg would save only block-table HBM reads at small frontiers while
adding a second (CSC-ordered) copy of every block. HBM capacity is the 2D
layout's scarce resource (it exists to fit big graphs); spending ~2x block
storage to accelerate the cheap levels inverts the trade-off.
"""

from __future__ import annotations

import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from bibfs_tpu.graph.csr import canonical_pairs
from bibfs_tpu.parallel.collectives import (
    global_min_and_argmin,
    pack_bits,
    sum_allreduce,
    unpack_bits,
)
from bibfs_tpu.parallel.mesh import (
    COL_AXIS,
    ROW_AXIS,
    make_2d_mesh,
    pcast as _pcast,
    shard_map,
)
from bibfs_tpu.solvers.api import BFSResult, register
from bibfs_tpu.solvers.dense import INF32, _device_scalar, _materialize


def _transpose_perm(R: int, C: int) -> tuple:
    """The fixed ppermute pairs moving fold slice ``s = r*C + c`` to the
    device whose column gather needs it: slice s belongs to column range
    ``s // R`` at gather position ``s % R``, i.e. grid (s % R, s // R),
    linear ``(s % R) * C + s // R``."""
    return tuple((s, (s % R) * C + s // R) for s in range(R * C))


def _2d_cond(st):
    return (
        (st["lvl_s"] + st["lvl_t"] < st["best"])
        & (st["cnt_s"] > 0)
        & (st["cnt_t"] > 0)
    )


def _make_2d_body(bnbr, bcnt, deg, tiers=(), *, R: int, C: int, mode: str):
    """The while_loop body ``st -> st`` over this device's adjacency block
    — shared by the one-shot program below and the chunked/checkpointed
    program (:mod:`bibfs_tpu.solvers.checkpoint`), so the two execution
    strategies cannot diverge. ``bnbr``/``bcnt``: [nr, W] localized
    neighbor ids + per-row TRUE group sizes; ``deg``: owned slice of true
    degrees [n_loc]; ``tiers``: per-device hub-tier blocks, a tuple of
    ``(start, tnbr [K_pad, Wt], tids [K_pad])`` with static start/shapes
    (tids are block-local row ids, -1 padding)."""
    nr, W = bnbr.shape
    n_loc = deg.shape[0]
    nc = n_loc * R  # column-range width (= n_pad / C)
    r = jax.lax.axis_index(ROW_AXIS)
    c = jax.lax.axis_index(COL_AXIS)
    s = r * C + c  # my linear fold index
    offset = (s * n_loc).astype(jnp.int32)
    ids = offset + jnp.arange(n_loc, dtype=jnp.int32)  # my global vertex ids
    perm = _transpose_perm(R, C)
    axes = (ROW_AXIS, COL_AXIS)
    cols_iota = jnp.arange(W, dtype=jnp.int32)[None, :]

    def side_step(st, side):
        fr = st[f"fr_{side}"]
        par = st[f"par_{side}"]
        dist = st[f"dist_{side}"]
        lvl = st[f"lvl_{side}"]
        scanned = sum_allreduce(jnp.sum(jnp.where(fr, deg, 0)), axes)
        # 1. transpose: my owned slice -> its column-gather position
        #    (bitpacked words; n_loc is a multiple of 32 by construction)
        words = jax.lax.ppermute(pack_bits(fr), axes, perm)
        # 2. expand: column range c's frontier via ONE all_gather on axis r
        f_col = unpack_bits(
            jax.lax.all_gather(words, ROW_AXIS, tiled=True), nc
        )
        hits = (f_col[bnbr] & (cols_iota < bcnt[:, None]))  # [nr, W]
        j_star = jnp.argmax(hits, axis=1)
        # candidate parent per row-range vertex: first hit slot, globalized;
        # -1 where this block saw no frontier neighbor
        p_loc = jnp.take_along_axis(bnbr, j_star[:, None], axis=1)[:, 0]
        cand = jnp.where(
            jnp.any(hits, axis=1), p_loc + c * nc, -1
        ).astype(jnp.int32)
        for start, tnbr, tids in tiers:  # hub overflow: gather + scatter-max
            wt = tnbr.shape[1]
            ids_c = jnp.clip(tids, 0, nr - 1)
            scnt = jnp.clip(bcnt[ids_c] - start, 0, wt)
            tvalid = (
                jnp.arange(wt, dtype=jnp.int32)[None, :] < scnt[:, None]
            ) & (tids >= 0)[:, None]
            thits = f_col[tnbr] & tvalid
            tany = jnp.any(thits, axis=1)
            tj = jnp.argmax(thits, axis=1)
            tp = jnp.take_along_axis(tnbr, tj[:, None], axis=1)[:, 0]
            tcand = jnp.where(tany, tp + c * nc, -1).astype(jnp.int32)
            tgt = jnp.where(tany, ids_c, nr)  # nr = out of bounds -> drop
            cand = cand.at[tgt].max(tcand, mode="drop")
        # 3. fold: max parent across the row; my owned slice is exactly
        #    chunk c of the row range (row-major layout), so one slice
        #    finishes the level — no second permute
        fold = jax.lax.pmax(cand, COL_AXIS)  # [nr]
        chunk = jax.lax.dynamic_slice_in_dim(fold, c * n_loc, n_loc)
        nf = (chunk >= 0) & (dist >= INF32)
        par = jnp.where(nf, chunk, par)
        dist = jnp.where(nf, lvl + 1, dist)
        cnt = sum_allreduce(jnp.sum(nf.astype(jnp.int32)), axes)
        return {
            **st,
            f"fr_{side}": nf,
            f"par_{side}": par,
            f"dist_{side}": dist,
            f"lvl_{side}": lvl + 1,
            f"cnt_{side}": cnt,
            "edges": st["edges"] + scanned,
        }

    def meet_vote(st, delta):
        both = (st["dist_s"] < INF32) & (st["dist_t"] < INF32)
        sums = jnp.where(both, st["dist_s"] + st["dist_t"], INF32)
        lmin = jnp.min(sums)
        larg = ids[jnp.argmin(sums)]
        gmin, garg = global_min_and_argmin(lmin, larg, axes)
        st["meet"] = jnp.where(gmin < st["best"], garg, st["meet"])
        st["best"] = jnp.minimum(st["best"], gmin)
        st["levels"] = st["levels"] + delta
        return st

    if mode == "sync":
        # lock-step fusion (mirrors the dense/1D dual branches): both
        # sides' word planes ride ONE transpose ppermute and ONE row-axis
        # all_gather, one block read serves both expansions, and the
        # parent folds/counts ride stacked collectives — half the
        # collective count per round, same wire bytes
        from bibfs_tpu.ops.expand import _dual_hits, pack_dual

        def body(st):
            scanned2 = sum_allreduce(
                jnp.stack([
                    jnp.sum(jnp.where(st["fr_s"], deg, 0)),
                    jnp.sum(jnp.where(st["fr_t"], deg, 0)),
                ]),
                axes,
            )
            planes = jnp.stack(
                [pack_bits(st["fr_s"]), pack_bits(st["fr_t"])]
            )  # [2, nw]
            words = jax.lax.ppermute(planes, axes, perm)
            allw = jax.lax.all_gather(words, ROW_AXIS)  # [R, 2, nw]
            # n_loc is a multiple of 32 by construction: no pad gaps
            f_col_s = unpack_bits(allw[:, 0, :].reshape(-1), nc)
            f_col_t = unpack_bits(allw[:, 1, :].reshape(-1), nc)
            packed_col = pack_dual(f_col_s, f_col_t)
            valid = cols_iota < bcnt[:, None]
            vals = packed_col[bnbr]  # ONE [nr, W] block gather, both sides
            cands = []
            for bit in (1, 2):
                hits = _dual_hits(vals, valid, bit)
                j_star = jnp.argmax(hits, axis=1)
                p_loc = jnp.take_along_axis(bnbr, j_star[:, None], axis=1)[:, 0]
                cands.append(
                    jnp.where(jnp.any(hits, axis=1), p_loc + c * nc, -1)
                    .astype(jnp.int32)
                )
            cand_s, cand_t = cands
            for start, tnbr, tids in tiers:
                wt = tnbr.shape[1]
                ids_c = jnp.clip(tids, 0, nr - 1)
                scnt = jnp.clip(bcnt[ids_c] - start, 0, wt)
                tvalid = (
                    jnp.arange(wt, dtype=jnp.int32)[None, :] < scnt[:, None]
                ) & (tids >= 0)[:, None]
                tvals = packed_col[tnbr]  # ONE tier gather, both sides
                for bit in (1, 2):
                    thits = _dual_hits(tvals, tvalid, bit)
                    tany = jnp.any(thits, axis=1)
                    tj = jnp.argmax(thits, axis=1)
                    tp = jnp.take_along_axis(tnbr, tj[:, None], axis=1)[:, 0]
                    tcand = jnp.where(tany, tp + c * nc, -1).astype(jnp.int32)
                    tgt = jnp.where(tany, ids_c, nr)  # nr -> drop
                    if bit == 1:
                        cand_s = cand_s.at[tgt].max(tcand, mode="drop")
                    else:
                        cand_t = cand_t.at[tgt].max(tcand, mode="drop")
            fold2 = jax.lax.pmax(jnp.stack([cand_s, cand_t]), COL_AXIS)
            st = dict(st)
            for i, side in enumerate(("s", "t")):
                chunk = jax.lax.dynamic_slice_in_dim(
                    fold2[i], c * n_loc, n_loc
                )
                nf = (chunk >= 0) & (st[f"dist_{side}"] >= INF32)
                st[f"par_{side}"] = jnp.where(nf, chunk, st[f"par_{side}"])
                st[f"dist_{side}"] = jnp.where(
                    nf, st[f"lvl_{side}"] + 1, st[f"dist_{side}"]
                )
                st[f"fr_{side}"] = nf
                st[f"lvl_{side}"] = st[f"lvl_{side}"] + 1
            cnt2 = sum_allreduce(
                jnp.stack([
                    jnp.sum(st["fr_s"].astype(jnp.int32)),
                    jnp.sum(st["fr_t"].astype(jnp.int32)),
                ]),
                axes,
            )
            st["cnt_s"] = cnt2[0]
            st["cnt_t"] = cnt2[1]
            st["edges"] = st["edges"] + scanned2[0] + scanned2[1]
            return meet_vote(st, 2)

    elif mode == "alt":

        def body(st):
            st = jax.lax.cond(
                st["cnt_s"] <= st["cnt_t"],
                lambda st: side_step(st, "s"),
                lambda st: side_step(st, "t"),
                st,
            )
            return meet_vote(st, 1)

    else:
        raise ValueError(
            f"sharded2d supports modes 'sync' and 'alt', got {mode!r}"
        )

    return body


def _bibfs_2d_body(
    bnbr, bcnt, deg, src, dst, tiers=(), *, R: int, C: int, mode: str
):
    """The whole-search per-device program: seed, while_loop over
    :func:`_make_2d_body`, output tuple."""
    n_loc = deg.shape[0]
    r = jax.lax.axis_index(ROW_AXIS)
    c = jax.lax.axis_index(COL_AXIS)
    offset = ((r * C + c) * n_loc).astype(jnp.int32)
    ids = offset + jnp.arange(n_loc, dtype=jnp.int32)
    axes = (ROW_AXIS, COL_AXIS)

    def seed(v):
        fr = ids == v
        return dict(
            fr=fr,
            cnt=jnp.int32(1),
            par=_pcast(
                jnp.full(n_loc, -1, jnp.int32), axes, to="varying"
            ),
            dist=jnp.where(fr, 0, INF32).astype(jnp.int32),
            lvl=jnp.int32(0),
        )

    init = {f"{key}_s": val for key, val in seed(src).items()}
    init.update({f"{key}_t": val for key, val in seed(dst).items()})
    init.update(
        best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
        meet=jnp.where(src == dst, src, -1).astype(jnp.int32),
        levels=jnp.int32(0),
        edges=jnp.int32(0),
    )
    body = _make_2d_body(bnbr, bcnt, deg, tiers, R=R, C=C, mode=mode)
    out = jax.lax.while_loop(_2d_cond, body, init)
    return (
        out["best"],
        out["meet"],
        out["par_s"],
        out["par_t"],
        out["levels"],
        out["edges"],
    )


def _2d_fn(mesh, R: int, C: int, mode: str, tier_meta: tuple = ()):
    """``tier_meta`` is the static ``(start, K_pad, Wt)`` triple per hub
    tier (the jit-cache key half); the matching device arrays ride the
    ``aux`` argument as ``((tnbr, tids), ...)``."""
    blk4 = P(ROW_AXIS, COL_AXIS, None, None)
    blk3 = P(ROW_AXIS, COL_AXIS, None)
    own = P((ROW_AXIS, COL_AXIS))
    rep = P()
    aux_spec = tuple((blk4, blk3) for _ in tier_meta)

    def sharded2d_kernel(bnbr, bcnt, deg, aux, src, dst):
        tiers = tuple(
            (start, tn[0, 0], ti[0, 0])
            for (start, _kp, _wt), (tn, ti) in zip(tier_meta, aux)
        )
        return _bibfs_2d_body(
            bnbr[0, 0], bcnt[0, 0], deg, src, dst, tiers, R=R, C=C, mode=mode
        )

    return shard_map(
        sharded2d_kernel,
        mesh=mesh,
        in_specs=(blk4, blk3, own, aux_spec, rep, rep),
        out_specs=(rep, rep, own, own, rep, rep),
    )


@lru_cache(maxsize=None)
def _compiled_2d(mesh, R: int, C: int, mode: str, tier_meta: tuple = ()):
    return jax.jit(_2d_fn(mesh, R, C, mode, tier_meta))


@lru_cache(maxsize=None)
def _compiled_2d_batch(mesh, R: int, C: int, mode: str, tier_meta: tuple = ()):
    """vmap of the 2D search over (src, dst) pairs — B block-partitioned
    searches per collective program, same contract as the 1D
    :func:`bibfs_tpu.solvers.sharded._compiled_sharded_batch`."""
    return jax.jit(
        jax.vmap(
            _2d_fn(mesh, R, C, mode, tier_meta),
            in_axes=(None, None, None, None, 0, 0),
        )
    )


class Sharded2DGraph:
    """Adjacency blocked over an R x C mesh (module docstring): device
    (r, c) holds ``bnbr[r, c]`` = localized block ELL for row range r /
    column range c; per-vertex state 1D-sharded row-major over all
    devices."""

    def __init__(self, n: int, edges: np.ndarray, mesh):
        if mesh.devices.ndim != 2:
            raise ValueError("Sharded2DGraph needs a 2D mesh (make_2d_mesh)")
        self.mesh = mesh
        R, C = mesh.devices.shape
        self.R, self.C = R, C
        pairs = canonical_pairs(n, edges)
        self.num_edges = pairs.shape[0] // 2
        # n_loc must be a multiple of the 32-bit pack word so the bitpacked
        # transpose/gather needs no per-shard padding bookkeeping
        pad = 32 * R * C
        n_pad = -(-max(n, 1) // pad) * pad
        self.n = n
        self.n_pad = n_pad
        self.n_loc = n_pad // (R * C)
        nr = n_pad // R  # row-range width
        nc = n_pad // C  # column-range width

        u, v = pairs[:, 0], pairs[:, 1]
        cb = v // nc  # column block of each directed edge's target
        gkey = u * C + cb  # consecutive groups: pairs sorted by (u, v)
        counts = np.bincount(gkey, minlength=n_pad * C)
        cmat = counts.reshape(n_pad, C)  # [vertex, col block] TRUE sizes
        if pairs.size:
            firsts = np.zeros(gkey.size, dtype=np.int64)
            starts = np.flatnonzero(np.diff(gkey)) + 1
            firsts[starts] = starts
            np.maximum.accumulate(firsts, out=firsts)
            rank_blk = np.arange(gkey.size) - firsts
            w_max = int(rank_blk.max()) + 1
        else:
            rank_blk = np.zeros(0, dtype=np.int64)
            w_max = 1

        # base width: same slot-minimizing selection as the 1D tiered
        # builder (graph/csr.build_tiered), over (vertex, col-block) group
        # sizes; the footprint model is exact (base + padded tier rows)
        from bibfs_tpu.graph.csr import (
            _BASE_WIDTHS,
            _pad_hub_count,
            _tier_plan,
        )

        def _tier_rows_pad(start: int) -> int:
            per_dev = (cmat > start).reshape(R, nr, C).sum(axis=1)  # [R, C]
            k = int(per_dev.max())
            return _pad_hub_count(k) if k else 0

        def _slots(w0: int) -> int:
            total = n_pad * C * w0  # R*C devices x nr rows x w0
            for start, width in _tier_plan(w0, w_max):
                total += R * C * _tier_rows_pad(start) * width
            return total

        cands = [w for w in _BASE_WIDTHS if w < w_max] + [w_max]
        w0 = min(cands, key=_slots)
        self.width = w0
        self.max_group = w_max

        bnbr = np.zeros((R, C, nr, w0), dtype=np.int32)
        if pairs.size:
            sel = rank_blk < w0
            bnbr[u[sel] // nr, cb[sel], u[sel] % nr, rank_blk[sel]] = (
                v[sel] - cb[sel] * nc
            )  # localized
        bcnt = (
            cmat.reshape(R, nr, C).transpose(0, 2, 1).astype(np.int32)
        )  # -> [R, C, nr]
        deg = np.zeros(n_pad, dtype=np.int32)
        deg[:n] = np.bincount(u, minlength=n)[:n]

        # geometric hub tiers: groups whose size exceeds the base width
        # spill rank range [start, start+Wt) into per-device overflow rows
        tiers_np = []
        meta = []
        for start, wt in _tier_plan(w0, w_max):
            mu, mcb = np.nonzero(cmat > start)  # members, row-major order
            mdev = (mu // nr) * C + mcb
            order = np.argsort(mdev, kind="stable")
            mu, mcb, mdev = mu[order], mcb[order], mdev[order]
            tfirst = np.zeros(mdev.size, dtype=np.int64)
            tstarts = np.flatnonzero(np.diff(mdev)) + 1
            tfirst[tstarts] = tstarts
            np.maximum.accumulate(tfirst, out=tfirst)
            k_local = np.arange(mdev.size) - tfirst  # rank within device
            k_pad = _tier_rows_pad(start)
            tnbr = np.zeros((R, C, k_pad, wt), dtype=np.int32)
            tids = np.full((R, C, k_pad), -1, dtype=np.int32)
            tids[mu // nr, mcb, k_local] = (mu % nr).astype(np.int32)
            gk = np.full((n_pad, C), -1, dtype=np.int64)
            gk[mu, mcb] = k_local
            esel = (rank_blk >= start) & (rank_blk < start + wt)
            if esel.any():
                us, cbs = u[esel], cb[esel]
                tnbr[us // nr, cbs, gk[us, cbs], rank_blk[esel] - start] = (
                    v[esel] - cbs * nc
                ).astype(np.int32)
            tiers_np.append((tnbr, tids))
            meta.append((start, k_pad, wt))
        self.tier_meta = tuple(meta)

        blk = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS, None, None))
        blk3 = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS, None))
        own = NamedSharding(mesh, P((ROW_AXIS, COL_AXIS)))
        self.bnbr = jax.device_put(bnbr, blk)
        self.bcnt = jax.device_put(bcnt, blk3)
        self.deg = jax.device_put(deg, own)
        self.aux = tuple(
            (jax.device_put(tn, blk), jax.device_put(ti, blk3))
            for tn, ti in tiers_np
        )

    @property
    def padded_slots(self) -> int:
        """Total stored neighbor slots (base blocks + tier rows) — the HBM
        footprint the tiered layout exists to bound."""
        base = self.R * self.C * (self.n_pad // self.R) * self.width
        return base + sum(
            self.R * self.C * kp * wt for (_s, kp, wt) in self.tier_meta
        )

    @classmethod
    def build(cls, n, edges, mesh=None, *, rows=None, cols=None,
              num_devices=None):
        if mesh is None:
            ndev = num_devices if num_devices is not None else len(jax.devices())
            if rows is None or cols is None:
                # squarest factorization of the device count
                rows = int(np.sqrt(ndev))
                while ndev % rows:
                    rows -= 1
                cols = ndev // rows
            elif num_devices is not None and rows * cols != num_devices:
                raise ValueError(
                    f"--grid {rows}x{cols} disagrees with "
                    f"num_devices={num_devices}"
                )
            mesh = make_2d_mesh(rows, cols)
        return cls(n, edges, mesh)


def solve_sharded2d_graph(
    g: Sharded2DGraph, src: int, dst: int, *, mode: str = "sync"
) -> BFSResult:
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    from bibfs_tpu.solvers.timing import force_scalar

    fn = _compiled_2d(g.mesh, g.R, g.C, mode, g.tier_meta)
    t0 = time.perf_counter()
    out = fn(
        g.bnbr, g.bcnt, g.deg, g.aux, _device_scalar(src), _device_scalar(dst)
    )
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    return _materialize(out, time.perf_counter() - t0)


def time_search_2d(
    g: Sharded2DGraph, src: int, dst: int, *, repeats: int = 30,
    mode: str = "sync",
) -> tuple[list[float], BFSResult]:
    from bibfs_tpu.solvers.timing import force_scalar, timed_repeats

    fn = _compiled_2d(g.mesh, g.R, g.C, mode, g.tier_meta)
    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    return timed_repeats(
        lambda: fn(g.bnbr, g.bcnt, g.deg, g.aux, src_a, dst_a),
        lambda: solve_sharded2d_graph(g, src, dst, mode=mode),
        repeats,
        force=force_scalar,
    )


def _batch_dispatch_2d(g: "Sharded2DGraph", pairs, mode: str):
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    kern = _compiled_2d_batch(g.mesh, g.R, g.C, mode, g.tier_meta)
    srcs = jnp.asarray(pairs[:, 0], dtype=jnp.int32)
    dsts = jnp.asarray(pairs[:, 1], dtype=jnp.int32)
    return pairs, lambda: jax.block_until_ready(
        kern(g.bnbr, g.bcnt, g.deg, g.aux, srcs, dsts)
    )


def solve_batch_sharded2d_graph(
    g: "Sharded2DGraph", pairs, *, mode: str = "sync"
) -> list[BFSResult]:
    """Solve many (src, dst) queries in ONE 2D-partitioned program; same
    contract as the dense/1D batch solvers (``time_s`` = whole batch)."""
    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import force_scalar

    pairs, dispatch = _batch_dispatch_2d(g, pairs, mode)
    t0 = time.perf_counter()
    out = dispatch()
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    return _materialize_batch(out, pairs.shape[0], time.perf_counter() - t0)


def time_batch_sharded2d(
    g: "Sharded2DGraph", pairs, *, repeats: int = 5, mode: str = "sync"
) -> tuple[list[float], list[BFSResult]]:
    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import timed_batch_repeats

    pairs, dispatch = _batch_dispatch_2d(g, pairs, mode)
    times, out = timed_batch_repeats(dispatch, repeats)
    return times, _materialize_batch(
        out, pairs.shape[0], float(np.median(times))
    )


def frontier_exchange_bytes_2d(n_pad: int, R: int, C: int) -> dict:
    """Per-device wire bytes per pull level, by mesh axis — the number the
    module docstring's O(n/C + n/R) claim cashes out to (compare
    :func:`bibfs_tpu.parallel.collectives.frontier_exchange_bytes` for the
    1D solver's O(n))."""
    n_loc = n_pad // (R * C)
    return {
        "transpose_ppermute": n_loc // 8,
        "expand_all_gather_r": (R - 1) * (n_loc // 8),
        "fold_pmax_c": 4 * (n_pad // R),
        "oneD_all_gather_equiv": n_pad // 8,
    }


@register("sharded2d")
def _sharded2d_backend(
    n, edges, src, dst, mode="sync", rows=None, cols=None,
    num_devices=None, **_,
):
    g = Sharded2DGraph.build(
        n, edges, rows=rows, cols=cols, num_devices=num_devices
    )
    return solve_sharded2d_graph(g, src, dst, mode=mode)
