"""2D-partitioned multi-chip bidirectional BFS — communication that scales
with the MESH, not just the graph.

The 1D solver (:mod:`bibfs_tpu.solvers.sharded`) ships the whole bitpacked
frontier to every device each pull level: O(n/8) wire bytes per device no
matter how many chips participate (the v2 bitset exchange done right,
second_try.cpp:53-62). That is the right design at 8 chips; at pod scale
the classic fix — Graph500-style 2D adjacency partitioning (Buluç &
Madduri; "Compression and Sieve", arxiv 1208.5542, PAPERS.md) — bounds
per-device traffic by the MESH shape:

- the adjacency is blocked over an ``R x C`` mesh: device (r, c) stores,
  for the vertices of row range r (n/R of them), only their neighbors
  inside column range c (n/C ids, stored LOCALIZED so the expansion
  gather is into a column-local frontier);
- per-vertex state (frontier/dist/parent) is 1D-sharded over all R*C
  devices in row-major linear order (device (r, c) owns slice r*C + c);
- one level = three steps, each riding ONE mesh axis:
    1. **transpose** (``ppermute`` over the flattened mesh): each device's
       owned frontier slice moves to the device whose column gather needs
       it — fixed permutation, n_loc/8 bytes;
    2. **expand** (``all_gather`` over axis ``r``, bitpacked): devices of
       grid column c reconstruct column range c's frontier — n/(8C) bytes
       per device, vs n/8 in the 1D solver;
    3. **fold** (``pmax`` over axis ``c``): per-row-range parent
       candidates merge across the row — 4*n/R bytes; every device then
       keeps exactly its owned slice (the fold chunk IS the owned slice,
       by construction of the row-major layout).

Semantics match the 1D/dense solvers exactly: level-synchronous pull,
deterministic parents (first ELL slot within a block, max across blocks),
the provably-correct ``lvl_s + lvl_t >= best`` termination, true hop
counts. Pull-only and plain blocks (no hub tiers, no Beamer push) — on a
2D mesh the frontier exchange is already frontier-size-independent per
level, which is what push bought the 1D solver.

Trade-off, stated honestly: block ELL padding is worse than 1D ELL (each
row range pads to the max per-block row length ACROSS blocks), so padded
slots grow by up to ~C x on low-degree graphs. 2D is the layout for when
ICI traffic, not HBM capacity, is the binding constraint.
"""

from __future__ import annotations

import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from bibfs_tpu.graph.csr import canonical_pairs
from bibfs_tpu.parallel.collectives import (
    global_min_and_argmin,
    pack_bits,
    sum_allreduce,
    unpack_bits,
)
from bibfs_tpu.parallel.mesh import COL_AXIS, ROW_AXIS, make_2d_mesh
from bibfs_tpu.solvers.api import BFSResult, register
from bibfs_tpu.solvers.dense import INF32, _device_scalar, _materialize


def _transpose_perm(R: int, C: int) -> tuple:
    """The fixed ppermute pairs moving fold slice ``s = r*C + c`` to the
    device whose column gather needs it: slice s belongs to column range
    ``s // R`` at gather position ``s % R``, i.e. grid (s % R, s // R),
    linear ``(s % R) * C + s // R``."""
    return tuple((s, (s % R) * C + s // R) for s in range(R * C))


def _2d_cond(st):
    return (
        (st["lvl_s"] + st["lvl_t"] < st["best"])
        & (st["cnt_s"] > 0)
        & (st["cnt_t"] > 0)
    )


def _make_2d_body(bnbr, bcnt, deg, *, R: int, C: int, mode: str):
    """The while_loop body ``st -> st`` over this device's adjacency block
    — shared by the one-shot program below and the chunked/checkpointed
    program (:mod:`bibfs_tpu.solvers.checkpoint`), so the two execution
    strategies cannot diverge. ``bnbr``/``bcnt``: [nr, W] localized
    neighbor ids + per-row slot counts; ``deg``: owned slice of true
    degrees [n_loc]."""
    nr, W = bnbr.shape
    n_loc = deg.shape[0]
    nc = n_loc * R  # column-range width (= n_pad / C)
    r = jax.lax.axis_index(ROW_AXIS)
    c = jax.lax.axis_index(COL_AXIS)
    s = r * C + c  # my linear fold index
    offset = (s * n_loc).astype(jnp.int32)
    ids = offset + jnp.arange(n_loc, dtype=jnp.int32)  # my global vertex ids
    perm = _transpose_perm(R, C)
    axes = (ROW_AXIS, COL_AXIS)
    cols_iota = jnp.arange(W, dtype=jnp.int32)[None, :]

    def side_step(st, side):
        fr = st[f"fr_{side}"]
        par = st[f"par_{side}"]
        dist = st[f"dist_{side}"]
        lvl = st[f"lvl_{side}"]
        scanned = sum_allreduce(jnp.sum(jnp.where(fr, deg, 0)), axes)
        # 1. transpose: my owned slice -> its column-gather position
        #    (bitpacked words; n_loc is a multiple of 32 by construction)
        words = jax.lax.ppermute(pack_bits(fr), axes, perm)
        # 2. expand: column range c's frontier via ONE all_gather on axis r
        f_col = unpack_bits(
            jax.lax.all_gather(words, ROW_AXIS, tiled=True), nc
        )
        hits = (f_col[bnbr] & (cols_iota < bcnt[:, None]))  # [nr, W]
        j_star = jnp.argmax(hits, axis=1)
        # candidate parent per row-range vertex: first hit slot, globalized;
        # -1 where this block saw no frontier neighbor
        p_loc = jnp.take_along_axis(bnbr, j_star[:, None], axis=1)[:, 0]
        cand = jnp.where(
            jnp.any(hits, axis=1), p_loc + c * nc, -1
        ).astype(jnp.int32)
        # 3. fold: max parent across the row; my owned slice is exactly
        #    chunk c of the row range (row-major layout), so one slice
        #    finishes the level — no second permute
        fold = jax.lax.pmax(cand, COL_AXIS)  # [nr]
        chunk = jax.lax.dynamic_slice_in_dim(fold, c * n_loc, n_loc)
        nf = (chunk >= 0) & (dist >= INF32)
        par = jnp.where(nf, chunk, par)
        dist = jnp.where(nf, lvl + 1, dist)
        cnt = sum_allreduce(jnp.sum(nf.astype(jnp.int32)), axes)
        return {
            **st,
            f"fr_{side}": nf,
            f"par_{side}": par,
            f"dist_{side}": dist,
            f"lvl_{side}": lvl + 1,
            f"cnt_{side}": cnt,
            "edges": st["edges"] + scanned,
        }

    def meet_vote(st, delta):
        both = (st["dist_s"] < INF32) & (st["dist_t"] < INF32)
        sums = jnp.where(both, st["dist_s"] + st["dist_t"], INF32)
        lmin = jnp.min(sums)
        larg = ids[jnp.argmin(sums)]
        gmin, garg = global_min_and_argmin(lmin, larg, axes)
        st["meet"] = jnp.where(gmin < st["best"], garg, st["meet"])
        st["best"] = jnp.minimum(st["best"], gmin)
        st["levels"] = st["levels"] + delta
        return st

    if mode == "sync":

        def body(st):
            return meet_vote(side_step(side_step(st, "s"), "t"), 2)

    elif mode == "alt":

        def body(st):
            st = jax.lax.cond(
                st["cnt_s"] <= st["cnt_t"],
                lambda st: side_step(st, "s"),
                lambda st: side_step(st, "t"),
                st,
            )
            return meet_vote(st, 1)

    else:
        raise ValueError(
            f"sharded2d supports modes 'sync' and 'alt', got {mode!r}"
        )

    return body


def _bibfs_2d_body(bnbr, bcnt, deg, src, dst, *, R: int, C: int, mode: str):
    """The whole-search per-device program: seed, while_loop over
    :func:`_make_2d_body`, output tuple."""
    n_loc = deg.shape[0]
    r = jax.lax.axis_index(ROW_AXIS)
    c = jax.lax.axis_index(COL_AXIS)
    offset = ((r * C + c) * n_loc).astype(jnp.int32)
    ids = offset + jnp.arange(n_loc, dtype=jnp.int32)
    axes = (ROW_AXIS, COL_AXIS)

    def seed(v):
        fr = ids == v
        return dict(
            fr=fr,
            cnt=jnp.int32(1),
            par=jax.lax.pcast(
                jnp.full(n_loc, -1, jnp.int32), axes, to="varying"
            ),
            dist=jnp.where(fr, 0, INF32).astype(jnp.int32),
            lvl=jnp.int32(0),
        )

    init = {f"{key}_s": val for key, val in seed(src).items()}
    init.update({f"{key}_t": val for key, val in seed(dst).items()})
    init.update(
        best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
        meet=jnp.where(src == dst, src, -1).astype(jnp.int32),
        levels=jnp.int32(0),
        edges=jnp.int32(0),
    )
    body = _make_2d_body(bnbr, bcnt, deg, R=R, C=C, mode=mode)
    out = jax.lax.while_loop(_2d_cond, body, init)
    return (
        out["best"],
        out["meet"],
        out["par_s"],
        out["par_t"],
        out["levels"],
        out["edges"],
    )


def _2d_fn(mesh, R: int, C: int, mode: str):
    blk4 = P(ROW_AXIS, COL_AXIS, None, None)
    blk3 = P(ROW_AXIS, COL_AXIS, None)
    own = P((ROW_AXIS, COL_AXIS))
    rep = P()
    return jax.shard_map(
        lambda bnbr, bcnt, deg, src, dst: _bibfs_2d_body(
            bnbr[0, 0], bcnt[0, 0], deg, src, dst, R=R, C=C, mode=mode
        ),
        mesh=mesh,
        in_specs=(blk4, blk3, own, rep, rep),
        out_specs=(rep, rep, own, own, rep, rep),
    )


@lru_cache(maxsize=None)
def _compiled_2d(mesh, R: int, C: int, mode: str):
    return jax.jit(_2d_fn(mesh, R, C, mode))


@lru_cache(maxsize=None)
def _compiled_2d_batch(mesh, R: int, C: int, mode: str):
    """vmap of the 2D search over (src, dst) pairs — B block-partitioned
    searches per collective program, same contract as the 1D
    :func:`bibfs_tpu.solvers.sharded._compiled_sharded_batch`."""
    return jax.jit(jax.vmap(_2d_fn(mesh, R, C, mode), in_axes=(None, None, None, 0, 0)))


class Sharded2DGraph:
    """Adjacency blocked over an R x C mesh (module docstring): device
    (r, c) holds ``bnbr[r, c]`` = localized block ELL for row range r /
    column range c; per-vertex state 1D-sharded row-major over all
    devices."""

    def __init__(self, n: int, edges: np.ndarray, mesh):
        if mesh.devices.ndim != 2:
            raise ValueError("Sharded2DGraph needs a 2D mesh (make_2d_mesh)")
        self.mesh = mesh
        R, C = mesh.devices.shape
        self.R, self.C = R, C
        pairs = canonical_pairs(n, edges)
        self.num_edges = pairs.shape[0] // 2
        # n_loc must be a multiple of the 32-bit pack word so the bitpacked
        # transpose/gather needs no per-shard padding bookkeeping
        pad = 32 * R * C
        n_pad = -(-max(n, 1) // pad) * pad
        self.n = n
        self.n_pad = n_pad
        self.n_loc = n_pad // (R * C)
        nr = n_pad // R  # row-range width
        nc = n_pad // C  # column-range width

        u, v = pairs[:, 0], pairs[:, 1]
        cb = v // nc  # column block of each directed edge's target
        gkey = u * C + cb  # consecutive groups: pairs sorted by (u, v)
        counts = np.bincount(gkey, minlength=n_pad * C)
        if pairs.size:
            firsts = np.zeros(gkey.size, dtype=np.int64)
            starts = np.flatnonzero(np.diff(gkey)) + 1
            firsts[starts] = starts
            np.maximum.accumulate(firsts, out=firsts)
            rank_blk = np.arange(gkey.size) - firsts
            W = int(rank_blk.max()) + 1
        else:
            rank_blk = np.zeros(0, dtype=np.int64)
            W = 1
        self.width = W
        bnbr = np.zeros((R, C, nr, W), dtype=np.int32)
        if pairs.size:
            bnbr[u // nr, cb, u % nr, rank_blk] = v - cb * nc  # localized
        bcnt = counts.reshape(n_pad, C)  # [vertex, col block]
        bcnt = (
            bcnt.reshape(R, nr, C).transpose(0, 2, 1).astype(np.int32)
        )  # -> [R, C, nr]
        deg = np.zeros(n_pad, dtype=np.int32)
        deg[:n] = np.bincount(u, minlength=n)[:n]

        blk = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS, None, None))
        blk3 = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS, None))
        own = NamedSharding(mesh, P((ROW_AXIS, COL_AXIS)))
        self.bnbr = jax.device_put(bnbr, blk)
        self.bcnt = jax.device_put(bcnt, blk3)
        self.deg = jax.device_put(deg, own)

    @classmethod
    def build(cls, n, edges, mesh=None, *, rows=None, cols=None,
              num_devices=None):
        if mesh is None:
            ndev = num_devices if num_devices is not None else len(jax.devices())
            if rows is None or cols is None:
                # squarest factorization of the device count
                rows = int(np.sqrt(ndev))
                while ndev % rows:
                    rows -= 1
                cols = ndev // rows
            elif num_devices is not None and rows * cols != num_devices:
                raise ValueError(
                    f"--grid {rows}x{cols} disagrees with "
                    f"num_devices={num_devices}"
                )
            mesh = make_2d_mesh(rows, cols)
        return cls(n, edges, mesh)


def solve_sharded2d_graph(
    g: Sharded2DGraph, src: int, dst: int, *, mode: str = "sync"
) -> BFSResult:
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    from bibfs_tpu.solvers.timing import force_scalar

    fn = _compiled_2d(g.mesh, g.R, g.C, mode)
    t0 = time.perf_counter()
    out = fn(g.bnbr, g.bcnt, g.deg, _device_scalar(src), _device_scalar(dst))
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    return _materialize(out, time.perf_counter() - t0)


def time_search_2d(
    g: Sharded2DGraph, src: int, dst: int, *, repeats: int = 30,
    mode: str = "sync",
) -> tuple[list[float], BFSResult]:
    from bibfs_tpu.solvers.timing import force_scalar, timed_repeats

    fn = _compiled_2d(g.mesh, g.R, g.C, mode)
    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    return timed_repeats(
        lambda: fn(g.bnbr, g.bcnt, g.deg, src_a, dst_a),
        lambda: solve_sharded2d_graph(g, src, dst, mode=mode),
        repeats,
        force=force_scalar,
    )


def _batch_dispatch_2d(g: "Sharded2DGraph", pairs, mode: str):
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    kern = _compiled_2d_batch(g.mesh, g.R, g.C, mode)
    srcs = jnp.asarray(pairs[:, 0], dtype=jnp.int32)
    dsts = jnp.asarray(pairs[:, 1], dtype=jnp.int32)
    return pairs, lambda: jax.block_until_ready(
        kern(g.bnbr, g.bcnt, g.deg, srcs, dsts)
    )


def solve_batch_sharded2d_graph(
    g: "Sharded2DGraph", pairs, *, mode: str = "sync"
) -> list[BFSResult]:
    """Solve many (src, dst) queries in ONE 2D-partitioned program; same
    contract as the dense/1D batch solvers (``time_s`` = whole batch)."""
    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import force_scalar

    pairs, dispatch = _batch_dispatch_2d(g, pairs, mode)
    t0 = time.perf_counter()
    out = dispatch()
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    return _materialize_batch(out, pairs.shape[0], time.perf_counter() - t0)


def time_batch_sharded2d(
    g: "Sharded2DGraph", pairs, *, repeats: int = 5, mode: str = "sync"
) -> tuple[list[float], list[BFSResult]]:
    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import timed_batch_repeats

    pairs, dispatch = _batch_dispatch_2d(g, pairs, mode)
    times, out = timed_batch_repeats(dispatch, repeats)
    return times, _materialize_batch(
        out, pairs.shape[0], float(np.median(times))
    )


def frontier_exchange_bytes_2d(n_pad: int, R: int, C: int) -> dict:
    """Per-device wire bytes per pull level, by mesh axis — the number the
    module docstring's O(n/C + n/R) claim cashes out to (compare
    :func:`bibfs_tpu.parallel.collectives.frontier_exchange_bytes` for the
    1D solver's O(n))."""
    n_loc = n_pad // (R * C)
    return {
        "transpose_ppermute": n_loc // 8,
        "expand_all_gather_r": (R - 1) * (n_loc // 8),
        "fold_pmax_c": 4 * (n_pad // R),
        "oneD_all_gather_equiv": n_pad // 8,
    }


@register("sharded2d")
def _sharded2d_backend(
    n, edges, src, dst, mode="sync", rows=None, cols=None,
    num_devices=None, **_,
):
    g = Sharded2DGraph.build(
        n, edges, rows=rows, cols=cols, num_devices=num_devices
    )
    return solve_sharded2d_graph(g, src, dst, mode=mode)
