"""Multi-chip bidirectional BFS — the v2+v4 replacement, done right.

The reference's distributed story (SURVEY.md §2 quirks Q4/Q6): every MPI
rank holds the FULL graph (Bcast, second_try.cpp:41-44, mpi_bas.cpp:39-42),
every rank's GPU redundantly expands the whole frontier (the ``u % size``
partition is compiled in but launched with ``rank=0,size=1``, comp.cu:27,99),
and per level the hosts exchange N-int arrays over 1 Gb Ethernet
(mpi_bas.cpp:107) with two host↔device round-trips (comp.cu:84-107).

Here instead:
- the ELL adjacency and all per-vertex state are 1D vertex-sharded across
  the mesh (owner-computes — each device expands only its own rows); hub
  tiers of the tiered layout (power-law graphs) are sharded by hub RANK, so
  high-degree rows parallelize across the mesh too;
- the only per-level exchange is one ``all_gather`` of the expanding side's
  BITPACKED frontier over ICI (pull: uint32 words, 32 vertices/word — n/8
  wire bytes, the v2 bitset exchange reborn, second_try.cpp:53-62) or just
  the candidate edge ids (push — ``K*width`` ints, independent of graph
  size), plus scalar ``psum``/``pmin`` votes for popcounts, meet, and
  termination (replacing five MPI_Allreduce per level, SURVEY.md §3.2);
- the whole search is ONE ``lax.while_loop`` inside ONE ``shard_map``-jitted
  program: no host in the loop at all (v2/v4 return to the host every
  level).

Scalar loop state (frontier counts, best meet distance, meet vertex, level
counters) is replicated across devices by construction — every device runs
the identical while_loop and the collectives keep them in agreement, which
is exactly the lock-step invariant the MPI versions maintained by hand.
"""

from __future__ import annotations

import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from bibfs_tpu.graph.csr import EllGraph, TieredEllGraph, build_ell, build_tiered
from bibfs_tpu.ops.expand import (
    _dual_hits,
    expand_pull,
    expand_pull_dual,
    frontier_count,
    frontier_degree_sum,
)
from bibfs_tpu.parallel.collectives import (
    all_gather_bits,
    all_gather_bits_dual,
    global_min_and_argmin,
    max_allreduce,
    sum_allreduce,
)
from bibfs_tpu.parallel.mesh import (
    VERTEX_AXIS,
    axis_size as _axis_size,
    make_1d_mesh,
    pcast as _pcast,
    shard_map,
    shard_spec,
)
from bibfs_tpu.solvers.api import BFSResult, register
from bibfs_tpu.solvers.dense import (
    INF32,
    _device_scalar,
    _materialize,
    kernel_cap,
    push_span,
)

from bibfs_tpu.solvers.dense import DENSE_MODES as SHARDED_MODES  # same matrix


def _make_shard_body(
    nbr,
    deg,
    aux,
    *,
    axis: str,
    mode: str = "sync",
    push_cap: int = 0,
    tier_meta: tuple = (),
):
    """Build the per-device while_loop body ``st -> st`` over the LOCAL
    vertex shard — shared by the one-shot program below and the
    chunked/checkpointed program (:mod:`bibfs_tpu.solvers.checkpoint`), so
    the two execution strategies cannot diverge. ``push_cap > 0`` enables
    Beamer push/pull direction optimization: frontiers at most that wide
    (whose max degree fits the static push span) skip the n-bool frontier
    all_gather entirely and instead exchange only their candidate edges
    over ICI, so per-level traffic scales with the frontier, not the
    graph."""
    n_loc = nbr.shape[0]
    width = nbr.shape[1]
    k = max(push_cap, 1)
    me = jax.lax.axis_index(axis)
    offset = (me * n_loc).astype(jnp.int32)
    ids = offset + jnp.arange(n_loc, dtype=jnp.int32)  # my global vertex ids
    hub_rank, tiers = aux if aux else (None, ())
    full_tiers = tuple(zip(tier_meta, tiers))
    span, ncov = push_span(width, tier_meta)  # shared Beamer gate rule
    push_tiers = full_tiers[:ncov]
    # pallas modes: the fused kernel runs per shard over the LOCAL table
    # with the GLOBAL gathered frontier (id_space = whole graph); tables
    # are prepared HERE — trace time, outside the while_loop — and the
    # hub-tier exchange stays the XLA collective path either way
    use_pallas = SHARDED_MODES[mode][2]
    ptables = None
    if use_pallas:
        from bibfs_tpu.ops.pallas_expand import (
            pallas_fits,
            prepare_pallas_tables,
        )

        n_glob = n_loc * _axis_size(axis)
        if pallas_fits(n_loc, n_glob, width=width):
            ptables = prepare_pallas_tables(nbr, deg, id_space=n_glob)
        else:  # chunk loop too long: degrade to the XLA pull path
            use_pallas = False

    def pull(c):
        fr, fi, _ok, par, dist, lvl = c
        scanned = sum_allreduce(frontier_degree_sum(fr, deg), axis)
        # THE per-level exchange: one BITPACKED frontier all_gather (ICI) —
        # uint32 words, 32 vertices each, n/8 wire bytes instead of n bool
        # bytes (the v2 bitset exchange, second_try.cpp:53-62,82-85)
        f_glob = all_gather_bits(fr, axis)
        visited = dist < INF32
        if use_pallas:
            from bibfs_tpu.ops.pallas_expand import run_pull

            nf0, pcand = run_pull(ptables, f_glob, visited)
        else:
            nf0, pcand = expand_pull(f_glob, visited, nbr, deg)
        par = jnp.where(nf0, pcand, par)
        nf = nf0
        for (tstart, tcount, twidth, _cpad), (tnbr, tslots, tids) in full_tiers:
            # hub rows I own (rank-sharded): gather hits from the global
            # frontier, then exchange the per-hub verdicts ([count_pad]
            # bools + ints — tiny next to the n-bool frontier) so vertex
            # owners can scatter them into their shards
            cols = jnp.arange(twidth, dtype=jnp.int32)[None, :]
            valid = cols < tslots[:, None]
            hits = f_glob[tnbr] & valid
            any_loc = jnp.any(hits, axis=1)
            j_star = jnp.argmax(hits, axis=1)
            par_loc = jnp.take_along_axis(tnbr, j_star[:, None], axis=1)[:, 0]
            # one collective per tier: parent id where hit, -1 otherwise
            par_all = jax.lax.all_gather(
                jnp.where(any_loc, par_loc, -1), axis, tiled=True
            )
            tloc = tids - offset
            own = (tloc >= 0) & (tloc < n_loc) & (par_all >= 0) & (tids >= 0)
            tclip = jnp.where(own, tloc, 0)
            new = own & (dist[tclip] >= INF32)
            t2 = jnp.where(new, tloc, n_loc)  # n_loc = out of bounds -> drop
            nf = nf.at[t2].max(jnp.ones(t2.shape, jnp.bool_), mode="drop")
            par = par.at[t2].max(par_all, mode="drop")
        dist = jnp.where(nf & (dist >= INF32), lvl + 1, dist)
        cnt = sum_allreduce(frontier_count(nf), axis)
        md = max_allreduce(jnp.max(jnp.where(nf, deg, 0)), axis)
        # the compact index list is now stale; push recomputes it on entry
        return nf, fi, jnp.bool_(False), par, dist, lvl + 1, cnt, md, scanned

    def push(c):
        fr, fi, ok, par, dist, lvl = c

        def recompact():
            # pull -> push transition: rebuild the replicated global index
            # list from the sharded boolean frontier (one small all_gather)
            loc = jnp.flatnonzero(fr, size=k, fill_value=-1).astype(jnp.int32)
            loc = jnp.where(loc >= 0, loc + offset, -1)
            allv = jax.lax.all_gather(loc, axis).ravel()  # [ndev*k]
            live = allv >= 0
            pos = jnp.cumsum(live.astype(jnp.int32)) - 1
            outpos = jnp.where(live, pos, k)
            return jnp.full(k, -1, jnp.int32).at[outpos].set(allv, mode="drop")

        fi = jax.lax.cond(ok, lambda: fi, recompact)
        # owner-computes: expand only the fidx entries whose rows I hold
        mine = (fi >= offset) & (fi < offset + n_loc)
        floc = jnp.where(mine, fi - offset, 0)
        # replicate per-entry degree (and hub rank) via ONE fused psum —
        # exactly one vertex owner contributes each entry
        if push_tiers:
            packed = sum_allreduce(
                jnp.where(
                    mine,
                    jnp.stack([deg[floc], hub_rank[floc] + 1]),
                    0,
                ),
                axis,
            )
            vd, franks = packed[0], packed[1] - 1
        else:
            vd = sum_allreduce(jnp.where(mine, deg[floc], 0), axis)  # [k]
        rows = nbr[floc]  # [k, width] local row gather (global target ids)
        cols = jnp.arange(width, dtype=jnp.int32)[None, :]
        valid = mine[:, None] & (cols < jnp.minimum(vd, width)[:, None])
        parts_rows = [rows]
        parts_valid = [valid]
        if push_tiers:
            for (tstart, tcount, twidth, cpad), (tnbr, tslots, _tids) in push_tiers:
                h_loc = tnbr.shape[0]
                r_off = (me * h_loc).astype(jnp.int32)
                mine_r = (franks >= r_off) & (franks < r_off + h_loc)
                rloc = jnp.where(mine_r, franks - r_off, 0)
                tcols = jnp.arange(twidth, dtype=jnp.int32)[None, :]
                parts_rows.append(tnbr[rloc])
                parts_valid.append(mine_r[:, None] & (tcols < tslots[rloc][:, None]))
        rows = jnp.concatenate(parts_rows, axis=1)
        valid = jnp.concatenate(parts_valid, axis=1)
        wtot = rows.shape[1]
        srcb = jnp.broadcast_to(fi[:, None], rows.shape)
        # exchange candidate targets, NOT the frontier: [ndev*k*wtot] ids.
        # The matching sources need no collective at all — fi is replicated,
        # so every device reconstructs src_all locally by tiling.
        tgt_all = jax.lax.all_gather(jnp.where(valid, rows, -1).ravel(), axis).ravel()
        ndev = tgt_all.shape[0] // (k * wtot)
        src_all = jnp.tile(srcb.ravel(), ndev)
        # scatter the candidates I own into my dist/par shard
        tloc = tgt_all - offset
        own = (tloc >= 0) & (tloc < n_loc)
        tclip = jnp.where(own, tloc, 0)
        new = own & (dist[tclip] >= INF32)
        t2 = jnp.where(new, tloc, n_loc)  # n_loc = out of bounds -> drop
        dist = dist.at[t2].min(
            jnp.broadcast_to((lvl + 1).astype(jnp.int32), t2.shape), mode="drop"
        )
        par = par.at[t2].max(src_all, mode="drop")
        # winner occurrences (disjoint across devices: each target has one
        # owner) -> global winner flags by psum -> identical compaction on
        # every device -> replicated next fidx
        win_loc = new & (par[tclip] == src_all)
        win = sum_allreduce(win_loc.astype(jnp.int32), axis) > 0
        nf = (
            jnp.zeros(n_loc, jnp.bool_)
            .at[t2]
            .max(jnp.ones(t2.shape, jnp.bool_), mode="drop")
        )
        pos = jnp.cumsum(win.astype(jnp.int32)) - 1
        outpos = jnp.where(win, pos, k)
        nfi = jnp.full(k, -1, jnp.int32).at[outpos].set(tgt_all, mode="drop")
        cnt = jnp.sum(win.astype(jnp.int32))
        md = max_allreduce(jnp.max(jnp.where(win_loc, deg[tclip], 0)), axis)
        # vd is already the psum-replicated global degree list (dead
        # entries contribute 0), so its sum needs no further collective
        scanned = jnp.sum(vd)
        return nf, nfi, cnt <= k, par, dist, lvl + 1, cnt, md, scanned

    def side_step(st, side):
        carry = (
            st[f"fr_{side}"],
            st[f"fi_{side}"],
            st[f"ok_{side}"],
            st[f"par_{side}"],
            st[f"dist_{side}"],
            st[f"lvl_{side}"],
        )
        if push_cap > 0:
            use_push = (st[f"cnt_{side}"] <= push_cap) & (
                st[f"md_{side}"] <= span
            )
            out = jax.lax.cond(use_push, push, pull, carry)
        else:
            out = pull(carry)
        nf, fi, ok, par, dist, lvl, cnt, md, scanned = out
        return {
            **st,
            f"fr_{side}": nf,
            f"fi_{side}": fi,
            f"ok_{side}": ok,
            f"par_{side}": par,
            f"dist_{side}": dist,
            f"lvl_{side}": lvl,
            f"cnt_{side}": cnt,
            f"md_{side}": md,
            "edges": st["edges"] + scanned,
        }

    def meet_vote(st, delta):
        # meet vote: local min(dist_s+dist_t) over my shard, then a global
        # pmin pair (replaces v2's word-wise AND scan + Allreduce LOR,
        # second_try.cpp:110-116, and reports the true hop count — fix Q1)
        both = (st["dist_s"] < INF32) & (st["dist_t"] < INF32)
        sums = jnp.where(both, st["dist_s"] + st["dist_t"], INF32)
        lmin = jnp.min(sums)
        larg = ids[jnp.argmin(sums)]
        gmin, garg = global_min_and_argmin(lmin, larg, axis)
        st["meet"] = jnp.where(gmin < st["best"], garg, st["meet"])
        st["best"] = jnp.minimum(st["best"], gmin)
        st["levels"] = st["levels"] + delta
        return st

    schedule = SHARDED_MODES[mode][0]
    if schedule == "sync" and push_cap == 0 and mode != "sync_unfused":
        # pull-only lock-step: ONE dual-packed frontier exchange and ONE
        # table read serve BOTH sides' expansions per round — the same
        # wire bytes as two single-side gathers but half the collective
        # count (latency is what dominates small-message ICI collectives),
        # and half the HBM table traffic (mirrors the dense fused branch)
        def body(st):
            fr_s, fr_t = st["fr_s"], st["fr_t"]
            scanned2 = sum_allreduce(
                jnp.stack([
                    frontier_degree_sum(fr_s, deg),
                    frontier_degree_sum(fr_t, deg),
                ]),
                axis,
            )
            packed = all_gather_bits_dual(fr_s, fr_t, axis)
            vis_s = st["dist_s"] < INF32
            vis_t = st["dist_t"] < INF32
            if use_pallas:
                from bibfs_tpu.ops.pallas_expand import run_pull_dual

                nf_s, pc_s, nf_t, pc_t = run_pull_dual(
                    ptables, (packed & 1) > 0, (packed & 2) > 0,
                    vis_s, vis_t,
                )
            else:
                nf_s, pc_s, nf_t, pc_t = expand_pull_dual(
                    packed, vis_s, vis_t, nbr, deg
                )
            par_s = jnp.where(nf_s, pc_s, st["par_s"])
            par_t = jnp.where(nf_t, pc_t, st["par_t"])
            for (_ts, _tc, twidth, _cp), (tnbr, tslots, tids) in full_tiers:
                # hub rows I own: per-side verdicts from ONE packed gather,
                # exchanged in ONE stacked all_gather per tier
                cols = jnp.arange(twidth, dtype=jnp.int32)[None, :]
                valid = cols < tslots[:, None]
                vals = packed[tnbr]
                verdicts = []
                for bit in (1, 2):
                    hits = _dual_hits(vals, valid, bit)
                    any_loc = jnp.any(hits, axis=1)
                    j_star = jnp.argmax(hits, axis=1)
                    p_loc = jnp.take_along_axis(
                        tnbr, j_star[:, None], axis=1
                    )[:, 0]
                    verdicts.append(jnp.where(any_loc, p_loc, -1))
                allv = jax.lax.all_gather(jnp.stack(verdicts), axis)
                # [ndev, 2, h_loc] -> per-side global rank-ordered planes
                par_all_s = allv[:, 0, :].reshape(-1)
                par_all_t = allv[:, 1, :].reshape(-1)
                tloc = tids - offset
                own0 = (tloc >= 0) & (tloc < n_loc) & (tids >= 0)
                tclip = jnp.where(own0, tloc, 0)
                for side_par_all, dist_key in (
                    (par_all_s, "dist_s"), (par_all_t, "dist_t"),
                ):
                    new = own0 & (side_par_all >= 0) & (
                        st[dist_key][tclip] >= INF32
                    )
                    t2 = jnp.where(new, tloc, n_loc)  # n_loc -> drop
                    if dist_key == "dist_s":
                        nf_s = nf_s.at[t2].max(
                            jnp.ones(t2.shape, jnp.bool_), mode="drop"
                        )
                        par_s = par_s.at[t2].max(side_par_all, mode="drop")
                    else:
                        nf_t = nf_t.at[t2].max(
                            jnp.ones(t2.shape, jnp.bool_), mode="drop"
                        )
                        par_t = par_t.at[t2].max(side_par_all, mode="drop")
            dist_s = jnp.where(nf_s & ~vis_s, st["lvl_s"] + 1, st["dist_s"])
            dist_t = jnp.where(nf_t & ~vis_t, st["lvl_t"] + 1, st["dist_t"])
            cnt2 = sum_allreduce(
                jnp.stack([frontier_count(nf_s), frontier_count(nf_t)]), axis
            )
            md2 = max_allreduce(
                jnp.stack([
                    jnp.max(jnp.where(nf_s, deg, 0)),
                    jnp.max(jnp.where(nf_t, deg, 0)),
                ]),
                axis,
            )
            st = {
                **st,
                "fr_s": nf_s, "par_s": par_s, "dist_s": dist_s,
                "cnt_s": cnt2[0], "md_s": md2[0],
                "lvl_s": st["lvl_s"] + 1, "ok_s": jnp.bool_(False),
                "fr_t": nf_t, "par_t": par_t, "dist_t": dist_t,
                "cnt_t": cnt2[1], "md_t": md2[1],
                "lvl_t": st["lvl_t"] + 1, "ok_t": jnp.bool_(False),
                "edges": st["edges"] + scanned2[0] + scanned2[1],
            }
            return meet_vote(st, 2)

    elif schedule == "sync":

        def body(st):
            return meet_vote(side_step(side_step(st, "s"), "t"), 2)

    else:

        def body(st):
            st = jax.lax.cond(
                st["cnt_s"] <= st["cnt_t"],
                lambda st: side_step(st, "s"),
                lambda st: side_step(st, "t"),
                st,
            )
            return meet_vote(st, 1)

    return body


def _shard_cond(st):
    # all scalars replicated — every device votes identically
    # (the v2 termination votes, second_try.cpp:117-128, without the
    # per-level Allreduce SUM pair: counts ride the carry)
    return (
        (st["lvl_s"] + st["lvl_t"] < st["best"])
        & (st["cnt_s"] > 0)
        & (st["cnt_t"] > 0)
    )


def _bibfs_shard_body(
    nbr,
    deg,
    aux,
    src,
    dst,
    *,
    axis: str,
    mode: str = "sync",
    push_cap: int = 0,
    tier_meta: tuple = (),
    unroll: int = 1,
):
    """The per-device program. ``nbr``/``deg`` are the LOCAL vertex shard;
    ``src``/``dst`` are replicated scalars; ``aux`` is ``()`` for plain ELL
    or ``(hub_rank_shard, ((tier_nbr_shard, tier_slots_shard,
    hub_ids_replicated), ...))`` for the tiered layout (tier tables sharded
    by hub rank). ``mode="sync"`` expands both sides every round (half the
    sequential rounds — the latency-bound default); ``mode="alt"`` expands
    the globally-smaller frontier only (fewer total edge scans, v1/v4's
    direction optimization).
    """
    n_loc = nbr.shape[0]
    k = max(push_cap, 1)
    me = jax.lax.axis_index(axis)
    offset = (me * n_loc).astype(jnp.int32)
    ids = offset + jnp.arange(n_loc, dtype=jnp.int32)  # my global vertex ids

    def seed(v):
        fr = ids == v
        return dict(
            fr=fr,
            # fi holds the replicated global frontier-index list, but its
            # provenance alternates between constants (seed), all_gather
            # products (push), and carries (pull) — pin the vma to varying
            # so every cond branch agrees (same reason as par below)
            fi=_pcast(
                jnp.full(k, -1, jnp.int32).at[0].set(v.astype(jnp.int32)),
                axis,
                to="varying",
            ),
            ok=jnp.bool_(True),
            cnt=jnp.int32(1),
            md=sum_allreduce(jnp.sum(jnp.where(fr, deg, 0)), axis),
            # parents start as constants; mark them device-varying so both
            # lax.cond branches (only one of which writes each side) agree
            par=_pcast(jnp.full(n_loc, -1, jnp.int32), axis, to="varying"),
            dist=jnp.where(fr, 0, INF32).astype(jnp.int32),
            lvl=jnp.int32(0),
        )

    init = {f"{key}_s": val for key, val in seed(src).items()}
    init.update({f"{key}_t": val for key, val in seed(dst).items()})
    init.update(
        best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
        meet=jnp.where(src == dst, src, -1).astype(jnp.int32),
        levels=jnp.int32(0),
        edges=jnp.int32(0),
    )

    from bibfs_tpu.solvers.dense import _unrolled

    body = _make_shard_body(
        nbr, deg, aux, axis=axis, mode=mode, push_cap=push_cap,
        tier_meta=tier_meta,
    )
    # the replicated-vote cond makes every device take the same lax.cond
    # branch, so collectives inside the unrolled block stay coherent
    out = jax.lax.while_loop(
        _shard_cond, _unrolled(body, unroll, _shard_cond), init)
    return (
        out["best"],
        out["meet"],
        out["par_s"],
        out["par_t"],
        out["levels"],
        out["edges"],
    )


def _sharded_fused_ok(geom: tuple | None, tier_meta: tuple) -> bool:
    """Whether the 1D mesh can run the whole-level fused kernel: plain
    ELL within the v2 key/VMEM bounds. (v1 additionally required
    per-shard rows in whole 4096-vertex tiles for its packed-word
    exchange; the v2 exchange gathers the dual row directly, so any
    shard size qualifies.)"""
    from bibfs_tpu.ops.pallas_fused import fused_fits

    if geom is None or tier_meta:
        return False
    n_loc, id_space, width = geom
    return fused_fits(n_loc, id_space=id_space, width=width)


def _sharded_fused_prog(axis: str, unroll: int = 1):
    """Per-shard whole-level-kernel program (mode "fused" on the 1D
    mesh, v2): a lock-step round is ONE bitpacked dual-frontier
    all_gather (``all_gather_bits_dual`` — both word planes in one
    collective, n/4 wire bytes), the XLA dual gather + ONE fused kernel
    over the local rows, and three scalar collectives (stacked psum,
    stacked pmax, global min/argmin meet vote) — versus the ~10 XLA op
    groups per round of the sync path. Local rows pad to the kernel's
    4096-lane tile internally; no shard-size alignment is required."""
    from bibfs_tpu.ops.pallas_fused import (
        fused_dual_level,
        key_stride,
        pad_rows,
        prepare_fused_tables,
    )

    def sharded_fused_kernel(nbr, deg, aux, src, dst):
        del aux  # plain ELL only; the router guarantees it
        n_loc = nbr.shape[0]
        ndev = _axis_size(axis)
        me = jax.lax.axis_index(axis)
        offset = (me * n_loc).astype(jnp.int32)
        n_glob = n_loc * ndev
        glob_p = pad_rows(n_glob)
        nbr_t, deg2 = prepare_fused_tables(nbr, deg, id_space=n_glob)
        n_rows_p = nbr_t.shape[1]
        ks = key_stride(n_glob)
        ids = offset + jnp.arange(n_loc, dtype=jnp.int32)

        def seed(v):
            fr = ids == v
            dv = sum_allreduce(jnp.sum(jnp.where(fr, deg, 0)), axis)
            return dict(
                dist=jnp.where(
                    jnp.pad(fr, (0, n_rows_p - n_loc)), 0, INF32
                ).astype(jnp.int32).reshape(1, n_rows_p),
                par=_pcast(
                    jnp.full((1, n_rows_p), -1, jnp.int32), axis,
                    to="varying",
                ),
                cnt=jnp.int32(1),
                md=dv,
                ds=dv,  # this frontier's global edge-scan count
                lvl=jnp.int32(0),
            )

        st = {f"{k}_s": v for k, v in seed(src).items()}
        st.update({f"{k}_t": v for k, v in seed(dst).items()})
        dual0 = ((ids == src).astype(jnp.int32)
                 | ((ids == dst).astype(jnp.int32) << 1))
        st.update(
            dual=jnp.pad(dual0, (0, n_rows_p - n_loc)).reshape(1, n_rows_p),
        )
        st.update(
            best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
            meet=jnp.where(src == dst, src, -1).astype(jnp.int32),
            levels=jnp.int32(0),
            edges=jnp.int32(0),
        )

        def body(st):
            # ONE bitpacked collective carries both sides (the round-3
            # dual exchange): returns the pack_dual-coded GLOBAL frontier.
            # The bit-extract feeding it is a single elementwise chain off
            # the carried dual row (fuses into the pack)
            loc = st["dual"][0, :n_loc]
            dual_glob = all_gather_bits_dual(
                (loc & 1) > 0, (loc & 2) > 0, axis
            ).astype(jnp.int32)
            dual_row = jnp.pad(
                dual_glob, (0, glob_p - n_glob)
            ).reshape(1, glob_p)
            (dual_l, dist_s, dist_t, par_s, par_t,
             cnt_s, cnt_t, md_s, md_t, ds_s, ds_t, mval, midx) = (
                fused_dual_level(
                    dual_row, nbr_t, deg2,
                    st["dist_s"], st["dist_t"],
                    st["par_s"], st["par_t"],
                    st["lvl_s"] + 1, st["lvl_t"] + 1, ks=ks,
                )
            )
            sums = sum_allreduce(
                jnp.stack([cnt_s, cnt_t, ds_s, ds_t]), axis
            )
            maxs = max_allreduce(jnp.stack([md_s, md_t]), axis)
            gid = jnp.where(mval < INF32, midx + offset, -1)
            gmin, garg = global_min_and_argmin(mval, gid, axis)
            take = gmin < st["best"]
            return {
                "dual": dual_l,
                "dist_s": dist_s, "dist_t": dist_t,
                "par_s": par_s, "par_t": par_t,
                "cnt_s": sums[0], "cnt_t": sums[1],
                "ds_s": sums[2], "ds_t": sums[3],
                "md_s": maxs[0], "md_t": maxs[1],
                "lvl_s": st["lvl_s"] + 1, "lvl_t": st["lvl_t"] + 1,
                "best": jnp.minimum(st["best"], gmin),
                "meet": jnp.where(take, garg, st["meet"]),
                "levels": st["levels"] + 2,
                # this round scanned the CURRENT frontiers (global degree
                # sums carried from the previous round / the seed)
                "edges": st["edges"] + st["ds_s"] + st["ds_t"],
            }

        from bibfs_tpu.solvers.dense import _unrolled

        out = jax.lax.while_loop(
            _shard_cond, _unrolled(body, unroll, _shard_cond), st)
        return (
            out["best"],
            out["meet"],
            out["par_s"][0, :n_loc],
            out["par_t"][0, :n_loc],
            out["levels"],
            out["edges"],
        )

    return sharded_fused_kernel


def _sharded_fn(
    mesh, axis: str, mode: str = "sync", push_cap: int = 0,
    tier_meta: tuple = (), geom: tuple | None = None, unroll: int = 1,
):
    """The (unjitted) shard_map'd whole-search program. Pallas modes run
    the fused kernel per shard inside the collective program (the v4
    MPI-driving-CUDA-kernels architecture, mpi_bas.cpp:96-107, reborn as
    one shard_map program). ``unroll`` runs that many collective rounds
    per while iteration (dense._unrolled over the replicated-vote cond)."""
    hybrid = SHARDED_MODES[mode][1]
    cap = push_cap if hybrid else 0
    sh = P(axis)
    rep = P()
    aux_spec = (sh, tuple((sh, sh, rep) for _ in tier_meta)) if tier_meta else ()
    if mode == "fused":
        # router (_compiled_sharded) only sends qualified geometries here
        return shard_map(
            _sharded_fused_prog(axis, unroll),
            mesh=mesh,
            in_specs=(sh, sh, aux_spec, rep, rep),
            out_specs=(rep, rep, sh, sh, rep, rep),
            check_vma=_check_vma_for(mode, geom),
        )
    def sharded_kernel(nbr, deg, aux, src, dst):
        # named def, not a lambda: the compile sentinel keys program
        # budgets on the traced callable's name — '<lambda>' is
        # exactly the anonymous label the gate rejects
        return _bibfs_shard_body(
            nbr,
            deg,
            aux,
            src,
            dst,
            axis=axis,
            mode=mode,
            push_cap=cap,
            tier_meta=tier_meta,
            unroll=unroll,
        )

    return shard_map(
        sharded_kernel,
        mesh=mesh,
        in_specs=(sh, sh, aux_spec, rep, rep),
        out_specs=(rep, rep, sh, sh, rep, rep),
        check_vma=_check_vma_for(mode, geom),
    )


def _check_vma_for(mode: str, geom: tuple | None = None) -> bool:
    """shard_map's varying-axes check stays ON except for interpret-mode
    pallas programs: the pallas HLO interpreter neither lifts literal
    constants nor propagates vma through ref loads, so EVERY mixed op in
    the kernel body trips the check (jax's own message suggests
    check_vma=False as the workaround). Disabling it off-TPU lets the
    REAL kernel body run interpreted under the CPU mesh — closing
    VERDICT r3 weak #2, where the sharded pallas modes silently tested a
    value-level re-implementation instead of the kernel. On TPU the
    compiled Mosaic call is opaque to the check and full checking stays.
    ``geom`` (per-shard ``(n_loc, id_space, width)``) keeps the check ON
    when the body will degrade to the pure-XLA path anyway (pallas_fits
    False) — the check handles that program fine and must keep guarding
    it."""
    if not SHARDED_MODES[mode][2] or jax.default_backend() == "tpu":
        return True
    if mode == "fused":
        # reached only through the router, which already verified the
        # geometry runs the fused kernel — its interpret body needs the
        # check off for the same literal-lifting reason
        return False
    if geom is not None:
        from bibfs_tpu.ops.pallas_expand import pallas_fits

        if not pallas_fits(geom[0], geom[1], width=geom[2]):
            return True  # body degrades to XLA: no kernel, keep the check
    return False


def _compiled_sharded(
    mesh, axis: str, mode: str = "sync", push_cap: int = 0,
    tier_meta: tuple = (), geom: tuple | None = None, unroll: int = 1,
):
    # resolve the Mosaic-availability fallback BEFORE the cache key (same
    # rule as dense._get_kernel): a fallen-back 'pallas' shares the
    # already-compiled 'sync' program. ``geom`` = the per-shard
    # (n_loc, id_space, width) so the probe compiles the REAL geometry.
    # mode "fused" runs the whole-level kernel per shard when the
    # geometry qualifies (_sharded_fused_ok); otherwise it degrades to
    # the round-3 per-shard kernel
    from bibfs_tpu.solvers.dense import _resolve_pallas_mode

    if mode == "fused_alt":
        # only the lock-step dual program has a sharded form
        _warn_fused_degrade(
            geom, tier_meta, "no sharded alt-schedule fused program",
            mode_from="fused_alt", mode_to="pallas_alt",
        )
        mode = "pallas_alt"
    if mode == "fused" and not _sharded_fused_ok(geom, tier_meta):
        _warn_fused_degrade(geom, tier_meta)
        mode = "pallas"
    return _compiled_sharded_resolved(
        mesh, axis, _resolve_pallas_mode(mode, geom), push_cap, tier_meta,
        geom, unroll,
    )


_FUSED_DEGRADE_WARNED: set = set()


def _warn_fused_degrade(geom, tier_meta, why: str | None = None,
                        mode_from: str = "fused",
                        mode_to: str = "pallas") -> None:
    """One stderr notice per distinct geometry/reason: a silent reroute
    would let 'fused'-labeled timings describe the round-3 kernel."""
    if why is None:
        why = ("tiered layout" if tier_meta else
               f"geometry outside the fused kernel's key/VMEM bounds "
               f"(geom={geom}; see pallas_fused.fused_fits)")
    key = (geom, why, mode_from, mode_to)
    if key in _FUSED_DEGRADE_WARNED:
        return
    _FUSED_DEGRADE_WARNED.add(key)
    import sys

    print(
        f"sharded mode {mode_from!r}: {why} — degrading to the "
        f"expansion-kernel mode {mode_to!r}",
        file=sys.stderr,
    )


@lru_cache(maxsize=None)
def _compiled_sharded_resolved(
    mesh, axis: str, mode: str = "sync", push_cap: int = 0,
    tier_meta: tuple = (), geom: tuple | None = None, unroll: int = 1,
):
    return jax.jit(
        _sharded_fn(mesh, axis, mode, push_cap, tier_meta, geom, unroll))


def _compiled_sharded_batch(
    mesh, axis: str, mode: str = "sync", push_cap: int = 0,
    tier_meta: tuple = (), geom: tuple | None = None,
):
    from bibfs_tpu.solvers.dense import _resolve_pallas_mode

    if mode == "fused_alt":
        _warn_fused_degrade(
            geom, tier_meta,
            "batch solves vmap the program (no fused batching rule)",
            mode_from="fused_alt", mode_to="pallas_alt",
        )
        mode = "pallas_alt"
    if mode == "fused":
        # UNLIKE the single-query router, batch always degrades: the
        # fused kernel's cross-grid (1,1) accumulators assume grid axis 0
        # is the vertex-tile walk, and vmap would prepend a batch grid
        # dim (same restriction as dense._get_batch_kernel)
        _warn_fused_degrade(
            geom, tier_meta,
            "batch solves vmap the program (no fused batching rule)",
        )
        mode = "pallas"
    return _compiled_sharded_batch_resolved(
        mesh, axis, _resolve_pallas_mode(mode, geom), push_cap, tier_meta,
        geom,
    )


@lru_cache(maxsize=None)
def _compiled_sharded_batch_resolved(
    mesh, axis: str, mode: str = "sync", push_cap: int = 0,
    tier_meta: tuple = (), geom: tuple | None = None,
):
    """vmap of the sharded search over (src, dst) pairs: B multi-chip
    searches advance lock-step in ONE collective program — every level's
    frontier all_gathers and vote psums are batched across queries, so the
    per-level ICI/dispatch overhead is paid once per level, not once per
    query per level. The multi-chip twin of the dense batch kernel
    (:func:`bibfs_tpu.solvers.dense._get_batch_kernel_resolved`)."""
    return jax.jit(
        jax.vmap(
            _sharded_fn(mesh, axis, mode, push_cap, tier_meta, geom),
            in_axes=(None, None, None, 0, 0),
        )
    )


class ShardedGraph:
    """Adjacency 1D-sharded across a device mesh — the framework's answer
    to ``MPI_Bcast`` full-graph replication (quirk Q6): each device holds
    only ``n_pad / ndev`` base rows. Accepts a plain :class:`EllGraph`
    (uniform degrees) or a :class:`TieredEllGraph` (power-law): hub tier
    tables are sharded by hub rank, their (tiny) rank->vertex maps
    replicated."""

    def __init__(self, g: EllGraph | TieredEllGraph, mesh=None):
        self.mesh = mesh if mesh is not None else make_1d_mesh()
        ndev = int(self.mesh.devices.size)
        if g.n_pad % ndev:
            raise ValueError(
                f"n_pad={g.n_pad} not divisible by {ndev} devices; build "
                f"with pad_multiple a multiple of the mesh size"
            )
        spec = shard_spec(self.mesh)
        rep = NamedSharding(self.mesh, P())
        self.n = g.n
        self.n_pad = g.n_pad
        self.width = g.width
        self.num_edges = g.num_edges
        self.nbr = jax.device_put(g.nbr, spec)
        self.deg = jax.device_put(g.deg, spec)
        self.tier_meta = ()
        self._aux = ()
        if isinstance(g, TieredEllGraph) and g.tiers:
            tiers = []
            meta = []
            for t in g.tiers:
                # re-pad the rank dimension so it tiles across the mesh
                cpad = -(-t.nbr.shape[0] // (8 * ndev)) * (8 * ndev)
                tnbr = np.zeros((cpad, t.nbr.shape[1]), dtype=np.int32)
                tnbr[: t.nbr.shape[0]] = t.nbr
                tids = np.full(cpad, -1, dtype=np.int32)
                tids[: min(t.count, cpad)] = g.hub_ids[: t.count]
                tslots = np.zeros(cpad, dtype=np.int32)
                tslots[: t.count] = np.clip(
                    g.deg[g.hub_ids[: t.count]] - t.start, 0, t.nbr.shape[1]
                )
                tiers.append(
                    (
                        jax.device_put(tnbr, spec),
                        jax.device_put(tslots, spec),
                        jax.device_put(tids, rep),
                    )
                )
                meta.append((t.start, t.count, t.nbr.shape[1], cpad))
            self._aux = (jax.device_put(g.hub_rank, spec), tuple(tiers))
            self.tier_meta = tuple(meta)
        elif isinstance(g, EllGraph) and g.overflow.shape[0]:
            raise NotImplementedError(
                "EllGraph has width_cap overflow edges; use build_tiered "
                "(tiered ELL) for skewed-degree graphs instead of width_cap"
            )

    @property
    def aux(self):
        return self._aux

    @classmethod
    def build(
        cls, n: int, edges: np.ndarray, mesh=None, *, layout: str = "ell",
        pad_multiple: int | None = None,
    ) -> "ShardedGraph":
        """``pad_multiple`` overrides the default ``8 * ndev`` vertex
        padding; the fused whole-level mode needs per-shard rows in whole
        4096-vertex tiles (``pad_multiple = 4096 * ndev``) — see
        :func:`_sharded_fused_ok`."""
        mesh = mesh if mesh is not None else make_1d_mesh()
        ndev = int(mesh.devices.size)
        pm = pad_multiple if pad_multiple is not None else 8 * ndev
        if pm % ndev:
            raise ValueError(
                f"pad_multiple={pm} must be a multiple of the {ndev}-device "
                "mesh"
            )
        if layout == "tiered":
            return cls(build_tiered(n, edges, pad_multiple=pm), mesh)
        if layout == "ell":
            return cls(build_ell(n, edges, pad_multiple=pm), mesh)
        raise ValueError(f"unknown layout {layout!r} (expected 'ell' or 'tiered')")


def _shard_geom(g: "ShardedGraph") -> tuple:
    """Per-shard (n_loc, id_space, width) — the geometry the pallas probe
    must compile: LOCAL rows gathering from the GLOBAL frontier."""
    ndev = int(g.mesh.devices.size)
    return (g.n_pad // ndev, g.n_pad, g.width)


def solve_sharded_graph(
    g: ShardedGraph, src: int, dst: int, *, mode: str = "sync",
    unroll: int = 1
) -> BFSResult:
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    fn = _compiled_sharded(
        g.mesh, VERTEX_AXIS, mode, kernel_cap(mode, g.n_pad), g.tier_meta,
        _shard_geom(g), unroll,
    )
    from bibfs_tpu.solvers.timing import force_scalar

    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    t0 = time.perf_counter()
    out = fn(g.nbr, g.deg, g.aux, src_a, dst_a)
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    elapsed = time.perf_counter() - t0
    return _materialize(out, elapsed)


def time_search(
    g: ShardedGraph, src: int, dst: int, *, repeats: int = 30,
    mode: str = "sync", unroll: int = 1
) -> tuple[list[float], BFSResult]:
    """Forced-execution timing loop + one materializing solve (protocol
    and rationale in :mod:`bibfs_tpu.solvers.timing`)."""
    from bibfs_tpu.solvers.timing import force_scalar, timed_repeats

    fn = _compiled_sharded(
        g.mesh, VERTEX_AXIS, mode, kernel_cap(mode, g.n_pad), g.tier_meta,
        _shard_geom(g), unroll,
    )
    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    return timed_repeats(
        lambda: fn(g.nbr, g.deg, g.aux, src_a, dst_a),
        lambda: solve_sharded_graph(g, src, dst, mode=mode, unroll=unroll),
        repeats,
        force=force_scalar,
    )


def _batch_dispatch(g: ShardedGraph, pairs, mode: str):
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    kern = _compiled_sharded_batch(
        g.mesh, VERTEX_AXIS, mode, kernel_cap(mode, g.n_pad), g.tier_meta,
        _shard_geom(g),
    )
    srcs = jnp.asarray(pairs[:, 0], dtype=jnp.int32)
    dsts = jnp.asarray(pairs[:, 1], dtype=jnp.int32)
    return pairs, lambda: jax.block_until_ready(
        kern(g.nbr, g.deg, g.aux, srcs, dsts)
    )


def solve_batch_sharded_graph(
    g: ShardedGraph, pairs, *, mode: str = "sync"
) -> list[BFSResult]:
    """Solve many (src, dst) queries in ONE multi-chip program (vmapped
    shard_map search). Same contract as
    :func:`bibfs_tpu.solvers.dense.solve_batch_graph`: each result's
    ``time_s`` is the whole-batch wall-clock."""
    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import force_scalar

    pairs, dispatch = _batch_dispatch(g, pairs, mode)
    t0 = time.perf_counter()
    out = dispatch()
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    elapsed = time.perf_counter() - t0
    return _materialize_batch(out, pairs.shape[0], elapsed)


def time_batch_sharded(
    g: ShardedGraph, pairs, *, repeats: int = 5, mode: str = "sync"
) -> tuple[list[float], list[BFSResult]]:
    """Batch solve under the shared timing protocol — the same
    :func:`bibfs_tpu.solvers.timing.timed_batch_repeats` loop the dense
    backend uses, so the two cannot diverge."""
    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import timed_batch_repeats

    pairs, dispatch = _batch_dispatch(g, pairs, mode)
    times, out = timed_batch_repeats(dispatch, repeats)
    return times, _materialize_batch(
        out, pairs.shape[0], float(np.median(times))
    )


def default_pad_multiple(mode: str, ndev: int) -> int:
    """The vertex padding a freshly built graph needs for ``mode``.
    Every current mode tiles on the int32 sublane quantum (the v2 fused
    program pads its local rows internally, so the v1-era 4096-tile
    shard alignment is gone); the hook stays so the CLI surfaces keep
    routing through one place if a mode ever needs special padding."""
    del mode
    return 8 * ndev


def solve_sharded(
    n: int,
    edges: np.ndarray,
    src: int,
    dst: int,
    *,
    num_devices: int | None = None,
    mode: str = "sync",
    layout: str = "ell",
    unroll: int = 1,
) -> BFSResult:
    mesh = make_1d_mesh(num_devices)
    g = ShardedGraph.build(
        n, edges, mesh, layout=layout,
        pad_multiple=default_pad_multiple(mode, int(mesh.devices.size)),
    )
    return solve_sharded_graph(g, src, dst, mode=mode, unroll=unroll)


@register("sharded")
def _sharded_backend(
    n, edges, src, dst, num_devices=None, mode="sync", layout="ell",
    unroll=1, **_
):
    return solve_sharded(
        n, edges, src, dst, num_devices=num_devices, mode=mode,
        layout=layout, unroll=unroll
    )
