"""The single solver API all backends implement.

The reference implements "the API" four separate times as standalone mains
with a shared CLI contract ``<exe> <graph.bin> <src> <dst>`` and scraped
stdout (SURVEY.md §1-L2). Here every backend is a function returning a
:class:`BFSResult`, so correctness (hop/path parity) is asserted in code
instead of eyeballed from logs — and hop counts are TRUE hop counts
(the reference's v2 reports round counts, second_try.cpp:107,134 — quirk Q1
— which this framework fixes rather than reproduces).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np


@dataclasses.dataclass
class BFSResult:
    found: bool
    hops: Optional[int]  # true shortest-path edge count (None if no path)
    path: Optional[list[int]]  # [src, ..., dst] (None if no path)
    meet: Optional[int]  # meeting vertex of the two searches
    time_s: float  # search loop only, matching reference timed regions
    levels: int  # number of frontier expansions performed
    edges_scanned: int  # directed edges examined (for TEPS)
    # per-level telemetry (bibfs_tpu/obs/telemetry.py): None unless the
    # solve was passed the opt-in ``telemetry=`` hook, in which case it
    # holds {"levels": [{level, side, dir, frontier, edges}, ...],
    # "meet_level": int|None, "meet": int|None}
    level_stats: Optional[dict] = None

    @property
    def teps(self) -> float:
        return self.edges_scanned / self.time_s if self.time_s > 0 else float("inf")

    def validate_path(self, n: int, edges: np.ndarray, src: int, dst: int) -> None:
        """Assert the reported path is a real path of the reported length.

        Scales to multi-million-node graphs: validation is CSR binary
        search per path edge (O(hops * log deg)), not a Python edge set
        (O(M) objects per call)."""
        if not self.found:
            return
        from bibfs_tpu.graph.csr import build_csr

        assert validate_path(
            build_csr(n, edges), self.path, src, dst, hops=self.hops
        ), f"invalid path {self.path} for src={src} dst={dst}"


def validate_path(csr, path, src, dst, hops=None) -> bool:
    """True iff ``path`` is a real src->dst walk in the CSR adjacency.

    ``csr`` is the ``(row_ptr, col_ind)`` pair from
    :func:`bibfs_tpu.graph.csr.build_csr`, whose rows are ascending —
    each path edge is checked with a binary search into its source row,
    so validation costs O(len(path) * log max_deg) regardless of graph
    size (usable in the bench gate at 10M nodes). ``hops`` additionally
    pins the claimed length.
    """
    if path is None or len(path) == 0:
        return False
    if path[0] != src or path[-1] != dst:
        return False
    if hops is not None and hops != len(path) - 1:
        return False
    row_ptr, col_ind = csr
    n = row_ptr.shape[0] - 1
    p = np.asarray(path, dtype=np.int64)
    if p.min() < 0 or p.max() >= n:
        return False
    for a, b in zip(p[:-1], p[1:]):
        row = col_ind[row_ptr[a] : row_ptr[a + 1]]
        i = np.searchsorted(row, b)
        if i >= row.size or row[i] != b:
            return False
    return True


SOLVERS: dict[str, Callable] = {}

# backend name -> implementing module, imported lazily so that requesting
# one backend never pays (or crashes on) another backend's dependencies
BACKEND_MODULES = {
    "serial": "bibfs_tpu.solvers.serial",
    "native": "bibfs_tpu.solvers.native",
    "dense": "bibfs_tpu.solvers.dense",
    "sharded": "bibfs_tpu.solvers.sharded",
    "sharded2d": "bibfs_tpu.solvers.sharded2d",
}


def register(name: str):
    def deco(fn):
        SOLVERS[name] = fn
        return fn

    return deco


def solve(
    backend: str, n: int, edges: np.ndarray, src: int, dst: int, **kwargs
) -> BFSResult:
    """Uniform entry: build whatever representation the backend needs and run.

    Backends are registered lazily; importing this module does not pull in
    JAX. Use the backend modules directly to control graph-build vs search
    timing separately (the reference times only the search loop).
    """
    if backend not in SOLVERS:
        if backend not in BACKEND_MODULES:
            raise KeyError(
                f"unknown backend {backend!r}; have {sorted(BACKEND_MODULES)}"
            )
        import importlib

        try:
            importlib.import_module(BACKEND_MODULES[backend])
        except (ImportError, OSError) as e:
            # missing JAX stack / missing C++ toolchain — report it against
            # the requested backend; the others remain usable
            raise KeyError(f"backend {backend!r} unavailable: {e}") from e
    return SOLVERS[backend](n, edges, src, dst, **kwargs)


def solve_many(
    n: int, edges: np.ndarray, pairs, *, pipelined: bool = False,
    return_errors: bool = False, **engine_kwargs,
) -> list:
    """Serve a query list through the adaptive micro-batching engine.

    The multi-query counterpart of :func:`solve`: one call builds a
    :class:`bibfs_tpu.serve.QueryEngine` (shape-bucketed device graph +
    distance/result cache), routes the queries through its calibrated
    batch-vs-latency crossover (batched device program at or above it,
    per-query host dispatch below), and returns one :class:`BFSResult`
    per pair. ``pairs`` may mix bare ``(src, dst)`` pairs with typed
    taxonomy queries (:mod:`bibfs_tpu.query` — multi-source, weighted,
    k-shortest), whose slots then carry their kind's result type.
    ``pipelined=True`` serves through the asynchronous
    :class:`bibfs_tpu.serve.PipelinedQueryEngine` instead (background
    deadline flusher, device dispatch overlapped with host-side finish;
    extra knobs like ``max_wait_ms`` pass through) — worth it for big
    lists on accelerator substrates, torn down before returning. Keep
    an engine of your own when serving repeat traffic — this
    convenience rebuilds the caches per call (the compiled executables
    themselves persist process-wide either way).

    A query that is INVALID on its own (out-of-range node id, bad
    arity) never fails its batch-mates: its slot carries a structured
    ``kind='invalid'`` :class:`bibfs_tpu.serve.resilience.QueryError`
    and every other query still resolves — one bad query costs one
    slot, never its batch. ``return_errors=True`` extends that
    partial-failure contract to EVERY failure kind (``timeout`` /
    ``capacity`` / ``internal``); the default re-raises the first
    non-invalid failure, matching the pre-resilience contract for
    real solver errors.
    """
    if pipelined:
        from bibfs_tpu.serve import PipelinedQueryEngine

        with PipelinedQueryEngine(n, edges, **engine_kwargs) as eng:
            results = eng.query_many(pairs, return_errors=True)
    else:
        from bibfs_tpu.serve import QueryEngine

        results = QueryEngine(n, edges, **engine_kwargs).query_many(
            pairs, return_errors=True
        )
    if not return_errors:
        from bibfs_tpu.serve.resilience import QueryError

        for r in results:
            if isinstance(r, QueryError) and r.kind != "invalid":
                raise r
    return results


def solve_query(n: int, edges: np.ndarray, query, *,
                backend: str = "serial", **kwargs):
    """Solve ONE typed taxonomy query (:mod:`bibfs_tpu.query`) over an
    inline graph, host-tier: the single-shot counterpart of threading
    a :class:`~bibfs_tpu.query.Query` through a serving engine's
    ``submit_query``. A :class:`~bibfs_tpu.query.PointToPoint` routes
    through :func:`solve` with ``backend`` (any registered backend);
    the other kinds solve on their host implementations
    (:mod:`bibfs_tpu.query.host`). ``AsOf`` needs a store to resolve
    versions against — use a store-backed engine's ``submit_query``.
    """
    from bibfs_tpu.query.host import solve_query_csr
    from bibfs_tpu.query.types import AsOf, PointToPoint, coerce_query

    q = coerce_query(query)
    if isinstance(q, PointToPoint):
        return solve(backend, n, edges, q.src, q.dst, **kwargs)
    if isinstance(q, AsOf):
        raise ValueError(
            "AsOf queries resolve against a store's version history; "
            "serve them through QueryEngine(store=...).submit_query"
        )
    from bibfs_tpu.graph.csr import build_csr

    row_ptr, col_ind = build_csr(n, edges)
    q.validate(n)
    return solve_query_csr(n, row_ptr, col_ind, q)
