"""The single solver API all backends implement.

The reference implements "the API" four separate times as standalone mains
with a shared CLI contract ``<exe> <graph.bin> <src> <dst>`` and scraped
stdout (SURVEY.md §1-L2). Here every backend is a function returning a
:class:`BFSResult`, so correctness (hop/path parity) is asserted in code
instead of eyeballed from logs — and hop counts are TRUE hop counts
(the reference's v2 reports round counts, second_try.cpp:107,134 — quirk Q1
— which this framework fixes rather than reproduces).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np


@dataclasses.dataclass
class BFSResult:
    found: bool
    hops: Optional[int]  # true shortest-path edge count (None if no path)
    path: Optional[list[int]]  # [src, ..., dst] (None if no path)
    meet: Optional[int]  # meeting vertex of the two searches
    time_s: float  # search loop only, matching reference timed regions
    levels: int  # number of frontier expansions performed
    edges_scanned: int  # directed edges examined (for TEPS)

    @property
    def teps(self) -> float:
        return self.edges_scanned / self.time_s if self.time_s > 0 else float("inf")

    def validate_path(self, n: int, edges: np.ndarray, src: int, dst: int) -> None:
        """Assert the reported path is a real path of the reported length."""
        if not self.found:
            return
        assert self.path is not None and self.hops == len(self.path) - 1
        assert self.path[0] == src and self.path[-1] == dst
        es = set()
        for u, v in np.asarray(edges).reshape(-1, 2):
            es.add((int(u), int(v)))
            es.add((int(v), int(u)))
        for a, b in zip(self.path, self.path[1:]):
            assert (a, b) in es, f"path edge ({a},{b}) not in graph"


SOLVERS: dict[str, Callable] = {}
_IMPORT_ERRORS: dict[str, Exception] = {}


def register(name: str):
    def deco(fn):
        SOLVERS[name] = fn
        return fn

    return deco


def solve(
    backend: str, n: int, edges: np.ndarray, src: int, dst: int, **kwargs
) -> BFSResult:
    """Uniform entry: build whatever representation the backend needs and run.

    Backends are registered lazily; importing this module does not pull in
    JAX. Use the backend modules directly to control graph-build vs search
    timing separately (the reference times only the search loop).
    """
    _ensure_registered()
    if backend not in SOLVERS:
        if backend in _IMPORT_ERRORS:
            raise KeyError(
                f"backend {backend!r} unavailable: {_IMPORT_ERRORS[backend]}"
            )
        raise KeyError(f"unknown backend {backend!r}; have {sorted(SOLVERS)}")
    return SOLVERS[backend](n, edges, src, dst, **kwargs)


def _ensure_registered():
    import bibfs_tpu.solvers.serial  # noqa: F401

    if "dense" not in SOLVERS and "dense" not in _IMPORT_ERRORS:
        try:
            import bibfs_tpu.solvers.dense  # noqa: F401
            import bibfs_tpu.solvers.sharded  # noqa: F401
        except ImportError as e:
            # a missing or broken JAX stack must not break the host
            # backends; the stashed error resurfaces if a JAX backend is
            # actually requested. Non-import bugs in our modules still raise.
            _IMPORT_ERRORS["dense"] = e
            _IMPORT_ERRORS["sharded"] = e
    if "native" not in SOLVERS:
        try:
            import bibfs_tpu.solvers.native  # noqa: F401
        except ModuleNotFoundError:
            pass  # native .so not built — optional backend
        except OSError as e:
            import warnings

            warnings.warn(f"native backend unavailable: {e}", stacklevel=2)
