"""Batch-minor batched bidirectional BFS: per-query state as the MINOR
(lane) axis, so the expansion gather moves contiguous lines.

Why this exists (measured, `TPU_SESSION.jsonl` item ``batch``,
2026-07-31): the vmapped batch kernel (`dense._get_batch_kernel_resolved`)
lays state out batch-MAJOR — ``frontier[B, n]`` — so its per-level
expansion is a batched arbitrary-index gather ``frontier[b, nbr[v, j]]``:
every (query, vertex, slot) fetches ONE scattered int32, and TPU gathers
issue roughly element-at-a-time. That is the 26.8 ms/query batch
asymptote: 1.78 ms/level/query of almost pure gather time at B=1024.

Here the SAME lock-step sync schedule runs over ``[n_pad, B]`` state.
Every query shares one neighbor table, so the expansion becomes

    vals[j, v, :] = dual[nbr_t[j, v], :]        # one row per index

— a gather of CONTIGUOUS ``B``-wide lane lines (B a multiple of 128):
each of the ``Wp * n_pad`` indices now serves ALL queries at once, and
the gather's cost model flips from per-element to per-row bandwidth.
Everything downstream (any-hit, the key-min parent claim, dist/par
selects, counts, the meet vote) is elementwise/reduce work with B on the
lane axis — exactly what the VPU tiles natively.

The level is chunked over the vertex axis (``lax.scan`` +
``dynamic_update_slice``) so the ``[Wp, Tc, B]`` gathered block stays
inside a fixed working-set budget at any graph size; the whole multi-
query search is still ONE ``lax.while_loop`` in ONE dispatch.

Semantics match the vmapped batch path: all queries advance lock-step
(both sides per round), finished queries freeze via masking, termination
is the proven ``lvl_s + lvl_t >= best`` vote per query, and the outputs
are per-query ``(best, meet, par_s, par_t, levels, edges)`` exactly as
`dense._materialize_batch` expects.

Tiered (power-law) layouts are supported in int32 mode: each hub tier
runs as its own slab-chunked row-gather pass scattering discoveries
onto the planes (visited tests on the updated dist planes keep claims
single), with counts and the meet vote recomputed plane-wide at level
end. Mode "minor8" stays plain-ELL — its slot-coded parents have no
tier decode.

Mode "minor8" keeps the same program with int8 dual/dist planes — the
gather source and the per-level reread, i.e. the two dominant traffic
terms, at a quarter the bytes. int8 dist caps stampable levels at 126
(:data:`INF8`), so the loop also stops at round :data:`MAX_RND8` and
returns a per-query ``capped`` flag; :func:`batch_dispatch`
transparently re-solves flagged queries with the int32 kernel, so the
mode is exact on ANY graph (the cap only costs a refill on searches
deeper than ~250 hops). Parent planes are int8 too: they hold ELL
SLOTS (the key-min yields ``key // ks`` for free), and the host
decodes ``nbr[v, slot]`` to vertex ids in the untimed finish hook —
every loop plane is one byte per (vertex, query).

Reference parity anchor: the reference has no batch mode at all — its
harness launches one process per query (benchmark_test.sh:44-59); the
batch solvers are the amortized-throughput regime the TPU design adds.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from bibfs_tpu.ops.pallas_expand import _slot_pad, sentinel_transposed_table

INF32 = 1 << 30
_BIG = 2147483647  # int32 max: never wins a min

# int8 plane variant (mode "minor8"): the dual-frontier and dist planes
# — the per-level gather source and the per-level reread — drop to one
# byte per (vertex, query), quartering the dominant traffic terms.
# INF8 = 127 is the unvisited sentinel, so the deepest stampable level
# is 126; rounds only start while rnd < MAX_RND8 = 126 and each stamps
# lvl = rnd + 1 <= 126. Queries still live at the cap come back flagged
# and the dispatch transparently re-solves them with the int32 kernel
# (deep searches are rare AND cheap per query — they are narrow)
INF8 = 127
MAX_RND8 = 126

# lane quantum: pad the batch axis so every row is whole vreg lanes
LANES = 128

# working-set budget for one chunk: the gathered [Wp, Tc, B] block PLUS
# its same-shape int32 key-select/meet intermediates, charged together
# at (itemsize + 4) bytes/element in chunk_rows/minor_fits — so this
# constant IS the real per-chunk ceiling, not a per-block one.
# Tuned by measurement: the first budget (192 MiB, block-only charge)
# ran chunks with ~384 MiB true working sets and its CPU numbers set
# the baseline; halving the chunks to honor 192 MiB cost ~2x per query,
# so the ceiling is set to what was actually validated. Deliberately
# well under HBM so the while-carry state (7 [n_pad, B] planes) keeps
# the headroom.
CHUNK_BUDGET_BYTES = 384 * 2**20


def pad_batch(b: int) -> int:
    """Queries padded up to whole 128-lane groups (dummy pads are
    src==dst==0 queries: best=0 at init, frozen before round one)."""
    return max(LANES, -(-b // LANES) * LANES)


def chunk_rows(wp: int, b_pad: int, n_pad: int, itemsize: int = 4) -> int:
    """Vertex rows per scan chunk: the largest sublane-quantum multiple
    whose per-chunk working set fits the budget (always >= 8; a
    too-wide geometry is rejected by :func:`minor_fits` instead).
    ``itemsize`` is the plane element size (1 under "minor8"); the
    key-select intermediates (``where(hit, keys, BIG)`` and the meet
    sums) are int32 at the same ``[Wp, Tc, B]`` shape REGARDLESS of the
    plane dtype, so the budget charges ``itemsize + 4`` bytes per
    element — otherwise the int8 mode's 4x-larger chunks would blow the
    budget through their int32 intermediates."""
    raw = CHUNK_BUDGET_BYTES // (wp * b_pad * (itemsize + 4))
    return int(max(8, min(n_pad, (raw // 8) * 8)))


def minor_fits(n_pad: int, width: int, b: int, itemsize: int = 4) -> bool:
    """Whether the batch-minor path handles this (graph, batch) shape:
    the key-min parent encoding ``(Wp-1)*KS + sentinel`` must stay in
    int32 (same bound as the fused kernel's, pallas_fused.fused_fits),
    and one 8-row chunk must fit the working-set budget under the SAME
    per-element charge :func:`chunk_rows` uses (``itemsize + 4``: the
    key-select/meet intermediates are int32 regardless of plane dtype)."""
    wp = _slot_pad(width)
    ks = n_pad + 1
    if wp * ks >= (1 << 31):
        return False
    return wp * 8 * pad_batch(b) * (itemsize + 4) <= CHUNK_BUDGET_BYTES


def _level_scan(dual, st, nbr_t, deg2, *, tc: int, ks: int, lvl, active_i,
                inf_d: int = INF32, slot_par: bool = False):
    """One lock-step level over all queries: scan the vertex axis in
    ``tc``-row chunks. ``dual [n_pad, B]`` is the round's read-only
    frontier (bit 0 = source side, bit 1 = target side); ``st`` carries
    the dist/par planes being rewritten. The dual and dist planes may be
    int8 (``inf_d`` = INF8) — the int8 variant's whole point is that
    these two are the per-level gather source and reread. Returns the
    updated planes plus the per-query reductions."""
    dist_s, dist_t, par_s, par_t = st
    n_pad, b = dual.shape
    pdt = dual.dtype  # plane dtype: int32, or int8 under "minor8"
    wp = nbr_t.shape[0]
    num_chunks = n_pad // tc
    zb = jnp.zeros((b,), jnp.int32)
    active_p = active_i.astype(pdt)
    key = (
        jax.lax.broadcasted_iota(jnp.int32, (wp, tc), 0) * ks
    )  # + nbr_c per chunk

    def chunk(carry, c):
        dual_n, ds, dt, ps, pt, cs, ct, sc, mval, midx = carry
        r0 = c * tc
        nbr_c = jax.lax.dynamic_slice(nbr_t, (0, r0), (wp, tc))
        deg_c = jax.lax.dynamic_slice(deg2, (r0,), (tc,))[:, None]
        dual_c = jax.lax.dynamic_slice(dual, (r0, 0), (tc, b))
        # THE gather: one contiguous B-wide row per (slot, vertex) index;
        # the sentinel index n_pad is out of range and reads 0 (fill)
        vals = jnp.take(dual, nbr_c, axis=0, mode="fill", fill_value=0)
        keys = key + nbr_c  # [wp, tc] static per chunk

        def side(bit, d_c, p_c):
            hit = jax.lax.shift_right_logical(vals, pdt.type(bit)) & pdt.type(1)
            anyh = jnp.max(hit, axis=0)  # [tc, b]
            nf = jnp.where(d_c < inf_d, pdt.type(0), anyh) * active_p[None, :]
            kmin = jnp.min(
                jnp.where(hit > 0, keys[:, :, None], _BIG), axis=0
            )
            d2 = jnp.where(nf > 0, lvl.astype(pdt), d_c)
            # the key encodes slot*ks + nbr: % decodes the parent VERTEX,
            # // decodes the parent SLOT (an int8 — the "minor8" par
            # planes store slots and the host decodes nbr[v, slot] at
            # materialization, outside the timed region)
            psel = (kmin // ks).astype(jnp.int8) if slot_par else kmin % ks
            p2 = jnp.where(nf > 0, psel, p_c)
            # scanned edges: this side's OLD frontier rows in this chunk
            fr_old = jax.lax.shift_right_logical(dual_c, pdt.type(bit)) & pdt.type(1)
            return nf, d2, p2, jnp.sum(fr_old.astype(jnp.int32) * deg_c, axis=0)

        ds_c = jax.lax.dynamic_slice(ds, (r0, 0), (tc, b))
        dt_c = jax.lax.dynamic_slice(dt, (r0, 0), (tc, b))
        ps_c = jax.lax.dynamic_slice(ps, (r0, 0), (tc, b))
        pt_c = jax.lax.dynamic_slice(pt, (r0, 0), (tc, b))
        nf_s, ds2, ps2, sc_s = side(0, ds_c, ps_c)
        nf_t, dt2, pt2, sc_t = side(1, dt_c, pt_c)

        # meet vote on the post-update planes (exact level-synchronously);
        # int32 arithmetic — int8 dist sums would wrap at 127
        both = (ds2 < inf_d) & (dt2 < inf_d)
        sums = jnp.where(
            both, ds2.astype(jnp.int32) + dt2.astype(jnp.int32), INF32
        )
        mv = jnp.min(sums, axis=0)
        rowid = r0 + jax.lax.broadcasted_iota(jnp.int32, sums.shape, 0)
        mi = jnp.min(jnp.where(sums == mv[None, :], rowid, _BIG), axis=0)
        # chunks walk ids in order, so strict < keeps the lowest-id argmin
        take = mv < mval
        carry = (
            jax.lax.dynamic_update_slice(
                dual_n, nf_s | jax.lax.shift_left(nf_t, pdt.type(1)), (r0, 0)
            ),
            jax.lax.dynamic_update_slice(ds, ds2, (r0, 0)),
            jax.lax.dynamic_update_slice(dt, dt2, (r0, 0)),
            jax.lax.dynamic_update_slice(ps, ps2, (r0, 0)),
            jax.lax.dynamic_update_slice(pt, pt2, (r0, 0)),
            # int32 accumulation: an int8 nf plane sum wraps past 127 rows
            cs + jnp.sum(nf_s, axis=0, dtype=jnp.int32),
            ct + jnp.sum(nf_t, axis=0, dtype=jnp.int32),
            sc + (sc_s + sc_t) * active_i,
            jnp.where(take, mv, mval),
            jnp.where(take, mi, midx),
        )
        return carry, None

    init = (
        jnp.zeros_like(dual), dist_s, dist_t, par_s, par_t,
        zb, zb, zb, jnp.full((b,), INF32, jnp.int32),
        jnp.full((b,), -1, jnp.int32),
    )
    out, _ = jax.lax.scan(
        chunk, init, jnp.arange(num_chunks, dtype=jnp.int32)
    )
    return out


def tier_slab_rows(tw: int, b_pad: int) -> int:
    """Hub rows per tier-pass slab (same budget discipline as
    :func:`chunk_rows`; tier vals gathers are int32-keyed either way,
    so the charge is a flat 8 bytes/element)."""
    raw = CHUNK_BUDGET_BYTES // (tw * b_pad * 8)
    return int(max(8, (raw // 8) * 8))


def _tier_pass(dual_old, planes, tnbr_m, ids, tw: int, cc: int, *,
               ks: int, lvl, active_i):
    """One hub tier's contribution to the level: slab-scan the tier
    table, row-gather the OLD dual frontier at every tier slot, and
    scatter the per-side discoveries into the planes. ``tnbr_m`` is the
    sentinel-masked tier table ([count_pad, tw], dead slots = n_pad2 →
    gather reads 0), ``ids`` the -1-padded hub vertex ids. ``planes`` =
    (nfh_s, nfh_t, dist_s, dist_t, par_s, par_t); visited tests read
    the UPDATED dist planes, so base- or earlier-tier-discovered
    vertices are not re-claimed (their parent stands)."""
    count_pad = tnbr_m.shape[0]
    num_slabs = count_pad // cc
    col = jax.lax.broadcasted_iota(jnp.int32, (cc, tw), 1)
    n_pad2 = ks - 1

    def slab(carry, si):
        nfh_s, nfh_t, ds, dtp, ps, pt = carry
        r0 = si * cc
        tn = jax.lax.dynamic_slice(tnbr_m, (r0, 0), (cc, tw))
        ids_c = jax.lax.dynamic_slice(ids, (r0,), (cc,))
        tgt = jnp.where(ids_c >= 0, ids_c, n_pad2)  # n_pad2 drops
        safe = jnp.where(ids_c >= 0, ids_c, 0)
        vals = jnp.take(dual_old, tn, axis=0, mode="fill", fill_value=0)
        keys = col * ks + tn  # first-hit slot wins the key-min

        def side(bit, d, p, nfh):
            hit = jax.lax.shift_right_logical(vals, bit) & 1
            anyh = jnp.max(hit, axis=1)  # [cc, B]
            drow = jnp.take(d, safe, axis=0)
            hub_new = jnp.where(drow < INF32, 0, anyh) * active_i[None, :]
            kmin = jnp.min(
                jnp.where(hit > 0, keys[:, :, None], _BIG), axis=1
            )
            d = d.at[tgt].min(
                jnp.where(hub_new > 0, lvl, INF32), mode="drop"
            )
            p = p.at[tgt].max(
                jnp.where(hub_new > 0, kmin % ks, -1), mode="drop"
            )
            nfh = nfh.at[tgt].max(hub_new, mode="drop")
            return d, p, nfh

        ds, ps, nfh_s = side(0, ds, ps, nfh_s)
        dtp, pt, nfh_t = side(1, dtp, pt, nfh_t)
        return (nfh_s, nfh_t, ds, dtp, ps, pt), None

    out, _ = jax.lax.scan(
        slab, planes, jnp.arange(num_slabs, dtype=jnp.int32)
    )
    return out


def _build_minor_kernel(n: int, n_pad2: int, wp: int, tc: int, b: int,
                        dt8: bool = False, tier_meta: tuple = ()):
    """The jitted whole-batch search for one (graph, batch) geometry.
    ``n`` is kept for call-site compatibility but never enters the
    program — the kernel is a pure function of the PADDED geometry, which
    is what lets the serve layer's shape buckets share one compiled
    program across real graph sizes.
    Signature ``(nbr, deg, aux, srcs, dsts) -> (best, meet, par_s
    [B, n_pad], par_t, levels, edges)`` — ``aux`` is the tier pytree
    (``((tier_nbr, hub_ids), ...)``, empty for plain ELL), and the
    outputs share the vmapped batch kernel's contract, so
    `dense._materialize_batch` serves both.

    ``dt8`` selects all-int8 loop planes (mode "minor8"): dual/dist
    directly, parents as ELL SLOTS (decoded to vertex ids by the host
    finish hook — the raw dt8 ``par_s``/``par_t`` outputs are NOT
    vertex ids), at the cost of a depth cap (round :data:`MAX_RND8`).
    The dt8 kernel returns a seventh output — ``capped bool[B]``,
    queries whose search was still live at the cap — which the finish
    hook re-solves via the int32 kernel.

    ``tier_meta`` (``(start, count, width)`` triples, int32 planes
    only) adds the hub-tier passes: the base scan runs first, each
    tier's slab scan scatters its discoveries on top (visited tests on
    the updated dist planes keep claims single), and the counts + meet
    vote are recomputed plane-wide at level end — the scan-integrated
    reductions cannot see the scattered hub updates."""
    ks = n_pad2 + 1
    pdt = jnp.int8 if dt8 else jnp.int32
    inf_d = INF8 if dt8 else INF32
    if tier_meta and dt8:
        raise ValueError("tiered batch-minor is int32-plane only")

    def minor_kernel(nbr, deg, aux, srcs, dsts):
        n_rows = nbr.shape[0]
        nbr_t = sentinel_transposed_table(
            nbr, deg, n_pad2, n_pad2, wp
        )  # [wp, n_pad2], sentinel = n_pad2 reads fill 0
        deg2 = jnp.pad(deg.astype(jnp.int32), (0, n_pad2 - n_rows))
        # sentinel-mask + pad the tier tables ONCE per solve: dead
        # slots (past this hub's degree, past the tier's live count, or
        # pad rows) read dual row n_pad2 = 0, exactly like the base
        # table's sentinel (ops/expand._tier_valid semantics)
        tier_tabs = []
        for (start, count, tw), (tnbr, hub_ids) in zip(tier_meta, aux):
            count_pad = tnbr.shape[0]
            cc = min(tier_slab_rows(tw, b), count_pad)
            rank = jnp.arange(count_pad, dtype=jnp.int32)
            ids_c = jnp.clip(hub_ids, 0, n_pad2 - 1)
            slot_count = jnp.clip(deg2[ids_c] - start, 0, tw)
            cols = jnp.arange(tw, dtype=jnp.int32)[None, :]
            valid = (
                (rank < count)[:, None]
                & (hub_ids >= 0)[:, None]
                & (cols < slot_count[:, None])
            )
            tnbr_m = jnp.where(valid, tnbr.astype(jnp.int32), n_pad2)
            pad_rows_t = -(-count_pad // cc) * cc - count_pad
            tnbr_m = jnp.pad(tnbr_m, ((0, pad_rows_t), (0, 0)),
                             constant_values=n_pad2)
            ids_p = jnp.pad(hub_ids.astype(jnp.int32), (0, pad_rows_t),
                            constant_values=-1)
            tier_tabs.append((tnbr_m, ids_p, tw, cc))
        qi = jnp.arange(b, dtype=jnp.int32)
        zplane = jnp.zeros((n_pad2, b), pdt)
        dual0 = zplane.at[srcs, qi].add(1).at[dsts, qi].add(2)
        inf_plane = jnp.full((n_pad2, b), inf_d, pdt)
        # dt8 par planes hold SLOTS (int8, host-decoded) — with them the
        # whole per-level loop state is one byte per (vertex, query)
        neg_plane = jnp.full((n_pad2, b), -1, pdt)
        st0 = dict(
            dual=dual0,
            dist_s=inf_plane.at[srcs, qi].set(0),
            dist_t=inf_plane.at[dsts, qi].set(0),
            par_s=neg_plane,
            par_t=neg_plane,
            best=jnp.where(srcs == dsts, 0, INF32).astype(jnp.int32),
            meet=jnp.where(srcs == dsts, srcs, -1).astype(jnp.int32),
            cnt_s=jnp.ones((b,), jnp.int32),
            cnt_t=jnp.ones((b,), jnp.int32),
            levels=jnp.zeros((b,), jnp.int32),
            edges=jnp.zeros((b,), jnp.int32),
            rnd=jnp.int32(0),
        )

        def wants_to_run(st):
            return (
                (2 * st["rnd"] < st["best"])
                & (st["cnt_s"] > 0)
                & (st["cnt_t"] > 0)
            )

        def active_of(st):
            act = wants_to_run(st)
            if dt8:
                act = act & (st["rnd"] < MAX_RND8)
            return act

        def cond(st):
            return jnp.any(active_of(st))

        def body(st):
            active_i = active_of(st).astype(jnp.int32)
            lvl = st["rnd"] + 1
            dual_n, ds, dt, ps, pt, cs, ct, sc, mval, midx = _level_scan(
                st["dual"],
                (st["dist_s"], st["dist_t"], st["par_s"], st["par_t"]),
                nbr_t, deg2, tc=tc, ks=ks, lvl=lvl, active_i=active_i,
                inf_d=inf_d, slot_par=dt8,
            )
            if tier_tabs:
                zp = jnp.zeros((n_pad2, b), jnp.int32)
                planes = (zp, zp, ds, dt, ps, pt)
                for tnbr_m, ids_p, tw, cc in tier_tabs:
                    planes = _tier_pass(
                        st["dual"], planes, tnbr_m, ids_p, tw, cc,
                        ks=ks, lvl=lvl, active_i=active_i,
                    )
                nfh_s, nfh_t, ds, dt, ps, pt = planes
                dual_n = dual_n | nfh_s | jax.lax.shift_left(nfh_t, 1)
                # the in-scan reductions cannot see the hub scatters:
                # recompute counts + the meet vote plane-wide
                cs = jnp.sum(dual_n & 1, axis=0)
                ct = jnp.sum(
                    jax.lax.shift_right_logical(dual_n, 1) & 1, axis=0
                )
                both = (ds < INF32) & (dt < INF32)
                sums = jnp.where(both, ds + dt, INF32)
                mval = jnp.min(sums, axis=0)
                rowid = jax.lax.broadcasted_iota(
                    jnp.int32, sums.shape, 0
                )
                midx = jnp.min(
                    jnp.where(sums == mval[None, :], rowid, _BIG), axis=0
                )
            take = mval < st["best"]
            return dict(
                dual=dual_n, dist_s=ds, dist_t=dt, par_s=ps, par_t=pt,
                best=jnp.minimum(st["best"], mval),
                meet=jnp.where(take, midx, st["meet"]),
                cnt_s=cs, cnt_t=ct,
                levels=st["levels"] + 2 * active_i,
                edges=st["edges"] + sc,
                rnd=lvl,
            )

        out = jax.lax.while_loop(cond, body, st0)
        res = (
            out["best"], out["meet"],
            out["par_s"].T, out["par_t"].T,
            out["levels"], out["edges"],
        )
        if dt8:
            # still-live-at-cap queries: their answers are not final
            return res + (wants_to_run(out),)
        return res

    return minor_kernel


def _get_minor_kernel(n: int, n_pad2: int, wp: int, tc: int, b: int,
                      dt8: bool = False, tier_meta: tuple = ()):
    """Jitted kernel cache. ``n`` is accepted for call-site compatibility
    but is NOT part of the cache key: the compiled program reads only the
    padded geometry (``_build_minor_kernel`` never closes over ``n``), and
    keying on it would recompile per graph SIZE even when the serve
    layer's shape buckets (bibfs_tpu/serve/buckets.py) hand several sizes
    the same padded shape on purpose."""
    return _get_minor_kernel_shape(n_pad2, wp, tc, b, dt8, tier_meta)


@lru_cache(maxsize=None)
def _get_minor_kernel_shape(n_pad2: int, wp: int, tc: int, b: int,
                            dt8: bool = False, tier_meta: tuple = ()):
    return jax.jit(
        _build_minor_kernel(0, n_pad2, wp, tc, b, dt8, tier_meta)
    )


# Below this many queries 'auto' keeps the vmapped path: the minor
# planes pad every batch to 128 lanes (pad_batch), so a tiny batch pays
# the full plane for a handful of queries. MEASURED crossover (CPU,
# n=30k gnp-2.2, timed_batch_repeats, us/query sync vs minor8):
#   B=8: 7.8k vs 42.5k (sync 5.4x better)   B=16: 9.5k vs 22.4k (2.4x)
#   B=32: 25.9k vs 22.6k (minor8 1.15x)     B=64: 35.6k vs 7.6k (4.7x)
# — the naive 128/B-waste-vs-11x-win model put the crossover at ~12,
# but the layout's win itself shrinks at small B, and the break-even is
# B ~= 32. (TPU may cross earlier — minor targets the device's gather
# penalty — but 'auto' routes by what is measured, not hoped.)
SMALL_BATCH_SYNC = 32


def small_batch_threshold() -> int:
    """The routed batch-vs-latency crossover for this platform.

    Mirrors ``dense._auto_push_cap``'s discipline: when
    ``calibration.json`` carries a measured ``batch_crossover`` for the
    current platform (the round-5 A/B: the table at
    :data:`SMALL_BATCH_SYNC`), route on it; a malformed or absent entry
    falls back to the committed measured default. Shared by
    ``auto_batch_mode`` and the serving engine's micro-batcher
    (bibfs_tpu/serve/engine.py), so the two layers cannot disagree about
    where batching starts to pay."""
    from bibfs_tpu.utils.calibrate import load_calibration

    cal = load_calibration() or {}
    crossover = cal.get("batch_crossover")
    if isinstance(crossover, int) and crossover > 0:
        return crossover
    return SMALL_BATCH_SYNC


def auto_batch_mode(g, num_pairs: int) -> str:
    """The best eligible batch mode for this (graph, batch) shape, in
    measured-preference order: ``minor8`` (all-int8 planes) when the
    graph is plain-ELL and the geometry fits, else ``minor`` (int32
    planes, tiered supported), else the vmapped ``sync`` path. Batches
    under :func:`small_batch_threshold` queries stay on the vmapped
    path — the minor layout pads to 128 lanes, and the MEASURED
    break-even (the A/B table at :data:`SMALL_BATCH_SYNC`, routed
    through the per-platform calibration when present) is B ~= 32. This
    is what ``solve_batch_graph(mode="auto")`` resolves through — the
    explicit mode names remain for measurement work (every A/B in
    PERF_NOTES pins its modes)."""
    if num_pairs < small_batch_threshold():
        return "sync"
    for mode, dt8 in (("minor8", True), ("minor", False)):
        try:
            _minor_geometry(g, num_pairs, dt8)
            return mode
        except ValueError:
            continue
    return "sync"


def _minor_geometry(
    g, num_pairs: int, dt8: bool = False
) -> tuple[int, int, int, int]:
    """(n_pad2, wp, tc, b_pad) for a DeviceGraph + batch size, after the
    fit checks. Vertex padding is to whole chunks so the scan covers the
    plane exactly; pad rows read sentinel slots only and stay inert."""
    if g.tier_meta and dt8:
        raise ValueError(
            "minor8 is plain-ELL only (slot-coded parents have no tier "
            "decode); tiered graphs batch through mode='minor' or 'sync'"
        )
    b_pad = pad_batch(num_pairs)
    wp = _slot_pad(g.width)
    if not minor_fits(g.n_pad, g.width, num_pairs,
                      itemsize=1 if dt8 else 4):
        raise ValueError(
            f"batch-minor geometry does not fit (n_pad={g.n_pad}, "
            f"width={g.width}, batch={num_pairs}); use the vmapped path"
        )
    if dt8 and wp > 127:
        # dt8 par planes store ELL slots in int8 (-1 = unclaimed)
        raise ValueError(
            f"minor8 stores parent slots in int8; width {g.width} "
            f"(padded {wp}) exceeds 127 — use mode='minor'"
        )
    tc = chunk_rows(wp, b_pad, g.n_pad, itemsize=1 if dt8 else 4)
    n_pad2 = -(-g.n_pad // tc) * tc
    # the kernel's key stride is n_pad2 + 1 (sentinel included), which
    # chunk rounding can push past what minor_fits checked with n_pad
    if wp * (n_pad2 + 1) >= (1 << 31):
        raise ValueError(
            f"batch-minor parent key overflows int32 after chunk "
            f"rounding (n_pad2={n_pad2}, wp={wp}); use the vmapped path"
        )
    for start, count, tw in g.tier_meta:
        # tier keys are col*ks + nbr, and one 8-row tier slab must fit
        if tw * (n_pad2 + 1) >= (1 << 31) or (
            tw * 8 * b_pad * 8 > CHUNK_BUDGET_BYTES
        ):
            raise ValueError(
                f"batch-minor tier (start={start}, width={tw}) does not "
                f"fit this batch; use the vmapped path"
            )
    return n_pad2, wp, tc, b_pad


# mesh axis name for the data-parallel batch (queries sharded, graph
# replicated); distinct from the vertex axis so a combined (vertex x
# query) mesh stays expressible later
QUERY_AXIS = "q"


def dp_batch_dispatch(g, pairs, mesh=None, dt8: bool = False):
    """Data-parallel batch over a device mesh: the batch axis is sharded
    across devices, the graph is replicated, and each device runs the
    whole batch-minor search on its query slice — ZERO collectives, so
    batch throughput scales linearly with chips (the scaling-book "pure
    data parallelism" regime; the reference's nearest analog is one
    PROCESS per query, benchmark_test.sh:44-59). One jitted shard_map
    program; the same output contract as :func:`batch_dispatch`.

    ``dt8`` uses the int8-plane kernel per shard; ``finish`` decodes the
    slot-parent planes and re-solves depth-capped queries (rare by
    construction) through the single-device int32 kernel."""
    from bibfs_tpu.parallel.mesh import make_1d_mesh

    if mesh is None:
        mesh = make_1d_mesh(axis=QUERY_AXIS)
    ndev = mesh.devices.size
    # each device's slice is lane-padded independently
    b_loc = pad_batch(-(-len(pairs) // ndev))
    b_pad = b_loc * ndev
    n_pad2, wp, tc, _ = _minor_geometry(g, b_loc, dt8)
    dp = _get_dp_program(mesh, g.n, n_pad2, wp, tc, b_loc, dt8,
                         g.tier_meta)
    srcs_a, dsts_a = _padded_queries(pairs, b_pad)
    thunk = lambda: jax.block_until_ready(  # noqa: E731
        dp(g.nbr, g.deg, g.tiers, srcs_a, dsts_a)
    )
    if not dt8:
        return pairs, thunk, lambda out: out
    return pairs, thunk, lambda out: _finish_dt8(g, pairs, out)


def solve_batch_dp(g, pairs, mesh=None, *, dt8: bool = False):
    """Data-parallel batch solve (see :func:`dp_batch_dispatch`).
    Returns one :class:`BFSResult` per pair; ``time_s`` is the whole-
    batch wall clock, as in `dense.solve_batch_graph`."""
    import time as _time

    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import force_scalar

    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    pairs, run, finish = dp_batch_dispatch(g, pairs, mesh, dt8)
    t0 = _time.perf_counter()
    out = run()
    force_scalar(out)  # block_until_ready lies on the tunneled backend
    elapsed = _time.perf_counter() - t0
    return _materialize_batch(finish(out), len(pairs), elapsed)


def time_batch_dp(g, pairs, mesh=None, *, repeats: int = 5,
                  dt8: bool = False):
    """`dense.time_batch_graph` protocol over the data-parallel batch."""
    from bibfs_tpu.solvers.dense import _materialize_batch
    from bibfs_tpu.solvers.timing import timed_batch_repeats

    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    pairs, run, finish = dp_batch_dispatch(g, pairs, mesh, dt8)
    times, out = timed_batch_repeats(run, repeats)
    return times, _materialize_batch(
        finish(out), len(pairs), float(np.median(times))
    )


def _refill_capped(g, pairs, out):
    """Re-solve the dt8 kernel's depth-capped queries (``out[-1]`` flag)
    through the int32 kernel and splice their rows into the outputs."""
    capped = np.asarray(out[-1])
    if not capped.any():
        return out[:-1]
    # deep queries: finish them on the un-capped int32 path (narrow
    # searches — per-level work is tiny by the time depth matters)
    idx = np.flatnonzero(capped[: len(pairs)])
    sub = pairs[idx]
    try:
        _, sub_thunk, _sub_finish = batch_dispatch(g, sub, dt8=False)
    except ValueError:
        # shapes where int8 planes fit (itemsize+4 = 5 B/elem charge)
        # but int32 ones do not (8 B/elem): finish on the vmapped sync
        # kernel, which shares the 6-tuple output contract
        from bibfs_tpu.solvers.dense import _batch_dispatch

        _, sub_thunk, _sub_finish = _batch_dispatch(g, sub, "sync")
    # apply the fallback dispatch's OWN finish hook unconditionally: it is
    # the identity on today's int32/sync paths, but assuming so here would
    # silently corrupt the splice the day either path gains a real finish
    # step (ADVICE r5 #2)
    sub_out = _sub_finish(sub_thunk())
    outs = [np.array(o) for o in out[:-1]]  # writable copies
    for o, so in zip(outs, sub_out):
        so = np.asarray(so)[: len(sub)]
        if o.ndim == 2:
            # parent planes: the two kernels may pad the vertex axis
            # differently (chunk size depends on the plane itemsize);
            # columns beyond the common width are pad rows (-1) in both
            w = min(o.shape[1], so.shape[1])
            o[idx, :w] = so[:, :w]
        else:
            o[idx] = so
    return tuple(outs)


def _get_dp_program(mesh, n: int, n_pad2: int, wp: int, tc: int,
                    b_loc: int, dt8: bool, tier_meta: tuple = ()):
    """Shape-keyed like `_get_minor_kernel`: ``n`` never enters the
    program, so it is dropped from the cache key."""
    return _get_dp_program_shape(mesh, n_pad2, wp, tc, b_loc, dt8,
                                 tier_meta)


@lru_cache(maxsize=None)
def _get_dp_program_shape(mesh, n_pad2: int, wp: int, tc: int,
                          b_loc: int, dt8: bool, tier_meta: tuple = ()):
    """The jitted shard_map program, cached like `_get_minor_kernel` —
    a fresh jit(shard_map(closure)) per call would retrace the whole
    while_loop program every solve. Mesh objects hash by their device
    grid + axis names, which is exactly the program identity here. The
    tier aux pytree (replicated, like the graph) rides along so tiered
    graphs keep their hub edges under the mesh too."""
    from jax.sharding import PartitionSpec as P

    from bibfs_tpu.parallel.mesh import shard_map

    (axis,) = mesh.axis_names
    kern = _build_minor_kernel(0, n_pad2, wp, tc, b_loc, dt8, tier_meta)

    def dp_minor_kernel(nbr, deg, aux, srcs, dsts):
        # named wrapper: the compile sentinel's program label — a dp
        # program must not report as the single-device minor kernel
        return kern(nbr, deg, aux, srcs, dsts)
    sh, rep = P(axis), P()
    aux_spec = tuple((rep, rep) for _ in tier_meta)
    nouts = 7 if dt8 else 6
    # check_vma=False: the kernel's scan carry seeds some planes from
    # REPLICATED graph data (unvarying) and rewrites them with
    # query-VARYING updates, which the vma checker rejects even though
    # it is exactly the intent. The check exists to validate collective
    # placement, and this program contains ZERO collectives — there is
    # nothing for it to protect here.
    return jax.jit(
        shard_map(
            dp_minor_kernel, mesh=mesh,
            in_specs=(rep, rep, aux_spec, sh, sh),
            out_specs=(sh,) * nouts,
            check_vma=False,
        )
    )


def _padded_queries(pairs, b_pad: int):
    srcs = np.zeros(b_pad, np.int32)
    dsts = np.zeros(b_pad, np.int32)
    srcs[: len(pairs)] = pairs[:, 0]
    dsts[: len(pairs)] = pairs[:, 1]
    return jnp.asarray(srcs), jnp.asarray(dsts)


def batch_dispatch(g, pairs, dt8: bool = False):
    """`dense._batch_dispatch` contract for mode='minor'/'minor8':
    returns ``(pairs, thunk, finish)``. The thunk runs the whole batch
    on-device and blocks (the TIMED unit); ``finish(out)`` converts the
    raw device output into the standard 6-tuple OUTSIDE the timed
    region — for ``dt8`` that means decoding the int8 slot-parent
    planes to vertex ids on the host and re-solving any depth-capped
    queries through the int32 kernel. ``pairs`` arrive already
    normalized and range-checked by the shared `dense._batch_dispatch`
    entry."""
    n_pad2, wp, tc, b_pad = _minor_geometry(g, len(pairs), dt8)
    kern = _get_minor_kernel(g.n, n_pad2, wp, tc, b_pad, dt8, g.tier_meta)
    aux = g.tiers  # ((tier_nbr, hub_ids), ...) — () for plain ELL
    srcs_a, dsts_a = _padded_queries(pairs, b_pad)
    thunk = lambda: jax.block_until_ready(  # noqa: E731
        kern(g.nbr, g.deg, aux, srcs_a, dsts_a)
    )
    if not dt8:
        return pairs, thunk, lambda out: out
    return pairs, thunk, lambda out: _finish_dt8(g, pairs, out)


def blocked_batch_dispatch(g, pairs, dt=None):
    """Dispatch one flush through the blocked-matmul kernel — the
    batched variant of the MXU-native expansion (``graph/blocked.py``,
    ``ops/blocked_expand.py``): ONE ``[n_pad, 2B]`` dual-side frontier
    plane rides each adjacency sweep, so the whole flush amortizes the
    blocked table exactly the way the dp-mesh batch amortizes its
    L2-resident shard plane. ``g`` is a
    :class:`~bibfs_tpu.solvers.dense.BlockedDeviceGraph`; returns
    ``(pairs, thunk)`` — the thunk is the TIMED unit, and the untimed
    epilogue (`dense._materialize_blocked_batch`) reconstructs paths
    from the dist planes over the host CSR."""
    from bibfs_tpu.ops.blocked_expand import (
        chunk_block_rows,
        resolve_plane_dtype,
    )
    from bibfs_tpu.solvers.dense import _get_blocked_kernel

    dt = resolve_plane_dtype(dt)
    b_pad = pad_batch(len(pairs))
    rc = min(
        chunk_block_rows(g.bwidth, 2 * b_pad, dt.itemsize, g.tile),
        g.nblocks,
    )
    kern = _get_blocked_kernel(g.nblocks, g.bwidth, b_pad, dt, rc, g.tile)
    srcs_a, dsts_a = _padded_queries(pairs, b_pad)
    thunk = lambda: jax.block_until_ready(  # noqa: E731
        kern(g.tab, g.bcol, g.deg, srcs_a, dsts_a)
    )
    return pairs, thunk


def _finish_dt8(g, pairs, out):
    """The untimed dt8 epilogue: slot-parent decode + capped refill."""
    out = _decode_slot_parents(g, out)
    return _refill_capped(g, pairs, out)


def _decode_slot_parents(g, out):
    """Decode the dt8 kernel's int8 slot-parent planes ([B, n_pad2],
    slot s means parent = nbr[v, s]) to int32 vertex-id planes on the
    host. The kernel only stamps slots of real hits (the sentinel table
    never produces one), so any slot >= 0 indexes a live ELL entry."""
    best, meet, ps, pt, levels, edges = out[:6]
    nbr_host = np.asarray(g.nbr)  # [n_pad, width]
    n_pad = nbr_host.shape[0]
    rows = np.arange(n_pad)[None, :]

    def decode(slot_plane):
        s = np.asarray(slot_plane)
        dec = np.full(s.shape, -1, np.int32)
        # int32 suffices (slots < 128, vertex ids < 2^31): at B=4096 on
        # a 100k graph an int64 widening would transiently cost ~3 GB
        # of host RAM per plane for a ~0.4 GB int8 input
        s_n = s[:, :n_pad].astype(np.int32)
        s_c = np.clip(s_n, 0, nbr_host.shape[1] - 1)
        dec[:, :n_pad] = np.where(s_n >= 0, nbr_host[rows, s_c], -1)
        return dec

    return (best, meet, decode(ps), decode(pt), levels, edges) + out[6:]
