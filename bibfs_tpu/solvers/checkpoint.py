"""Checkpoint/resume for long searches — chunked execution with periodic
host sync and crash-durable state snapshots.

The reference has no checkpointing of any kind (SURVEY.md §5: runs are
seconds-long, state is never persisted) and its device solvers return to
the host every level anyway (quirk Q5). This framework's solvers are the
opposite extreme: the WHOLE search is one ``lax.while_loop`` and the host
syncs once at the end. At 10M-node scale (the regime the reference's own
README names as the goal it never reached) a search is long enough that a
preemption, an OOM on a later level, or a dropped TPU tunnel loses
everything. This module adds the middle strategy:

- run the SAME loop body (``solvers.dense._make_body`` /
  ``solvers.sharded._make_shard_body`` — shared code, so the chunked
  search cannot diverge algorithmically from the one-shot search) in
  bounded chunks of ``chunk`` levels per dispatch via
  ``lax.while_loop((cond & steps < chunk))``;
- between chunks, read the three termination scalars on the host (one
  tiny D2H — this is also the "periodic host sync" pattern from
  SURVEY.md §2's TPU mapping) and atomically snapshot the carry to an
  ``.npz`` (write-temp + ``os.replace``, so a crash mid-write never
  corrupts the previous checkpoint);
- on restart, :func:`resume` reloads the snapshot and continues from the
  exact level where the last completed chunk ended.

The snapshot holds only the PORTABLE carry — per-vertex
frontier/parent/distance arrays plus replicated scalars; the transient
push-path compaction (``fi``/``ok``) is rebuilt on chunk entry. That makes
checkpoints **backend- and mesh-elastic** across all three device
substrates: a search checkpointed from the single-chip dense solver
resumes on a 1D vertex-sharded mesh of any divisor size OR on a 2D
block-partitioned mesh (and any direction between the three), because
state is re-padded and re-sharded to fit the resuming graph; hybrid
(Beamer) schedules degrade to their underlying pull schedule on the
pull-only 2D leg. The reference's closest analog is "rerun the binary"
(MPI_Abort on failure, second_try.cpp:35).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bibfs_tpu.parallel.mesh import pcast as _pcast
from bibfs_tpu.parallel.mesh import shard_map as _shard_map
from bibfs_tpu.solvers.api import BFSResult
from bibfs_tpu.solvers.dense import (
    DENSE_MODES,
    INF32,
    DeviceGraph,
    _cond,
    _make_body,
    _materialize,
    kernel_cap,
)

CKPT_VERSION = 1
# the portable carry: everything the search needs across a chunk boundary.
# fi/ok (push-path compaction) are deliberately absent — they are rebuilt
# on chunk entry, which keeps snapshots mesh-size independent.
_VERTEX_KEYS = ("fr_s", "fr_t", "par_s", "par_t", "dist_s", "dist_t")
_SCALAR_KEYS = (
    "cnt_s", "cnt_t", "md_s", "md_t", "lvl_s", "lvl_t",
    "best", "meet", "levels", "edges",
)
_STATE_KEYS = _VERTEX_KEYS + _SCALAR_KEYS


# ---------------------------------------------------------------- kernels


def _strip(st: dict) -> dict:
    return {k: v for k, v in st.items() if k in _STATE_KEYS}


def _with_transients(st: dict, k: int, *, axis: str | None = None) -> dict:
    """Re-add the transient push-compaction state dropped at the chunk
    boundary: ``ok=False`` makes the push path rebuild its index list from
    the boolean frontier on first use."""
    st = dict(st)
    for side in ("s", "t"):
        fi = jnp.full(k, -1, jnp.int32)
        if axis is not None:
            # same vma pinning as the sharded seed: fi's provenance
            # alternates between constants and all_gather products across
            # cond branches, so pin it to device-varying
            fi = _pcast(fi, axis, to="varying")
        st[f"fi_{side}"] = fi
        st[f"ok_{side}"] = jnp.bool_(False)
    return st


@lru_cache(maxsize=None)
def _prepare_tables_jit():
    """One process-wide jitted table-prep program (a fresh ``jax.jit``
    per drive would re-trace + re-compile on every resume)."""
    from bibfs_tpu.ops.pallas_expand import prepare_pallas_tables

    return jax.jit(prepare_pallas_tables)


@lru_cache(maxsize=None)
def _dense_chunk_kernel(mode: str, push_cap: int, tier_meta: tuple, chunk: int):
    """jitted ``(nbr, deg, aux, state) -> state`` advancing at most
    ``chunk`` rounds of the dense search."""
    cap = push_cap if DENSE_MODES[mode][1] else 0
    k = max(cap, 1)

    def dense_chunk_kernel(nbr, deg, aux, st):
        body = _make_body(mode, cap, tier_meta, nbr, deg, aux)

        def cond2(c):
            return _cond(c[0]) & (c[1] < chunk)

        def body2(c):
            return body(c[0]), c[1] + 1

        st, _steps = jax.lax.while_loop(
            cond2, body2, (_with_transients(st, k), jnp.int32(0))
        )
        return _strip(st)

    # donate the state: the caller replaces its reference on every chunk
    # (st = step(st)), so the previous buffers are dead — without donation
    # each dispatch holds TWO full copies of the vertex state, which is
    # what pushed the scale-24 dense run over single-chip HBM
    return jax.jit(dense_chunk_kernel, donate_argnums=3)


@lru_cache(maxsize=None)
def _sharded_chunk_kernel(
    mesh, axis: str, mode: str, push_cap: int, tier_meta: tuple, chunk: int,
    geom: tuple | None = None,
):
    """shard_map'd ``(nbr, deg, aux, state) -> state`` advancing at most
    ``chunk`` rounds of the multi-chip search. Vertex state shards with the
    graph; scalars stay replicated."""
    from bibfs_tpu.solvers.sharded import (
        SHARDED_MODES,
        _make_shard_body,
        _shard_cond,
    )

    hybrid = SHARDED_MODES[mode][1]
    cap = push_cap if hybrid else 0
    k = max(cap, 1)
    sh = P(axis)
    rep = P()
    aux_spec = (sh, tuple((sh, sh, rep) for _ in tier_meta)) if tier_meta else ()
    st_spec = {key: sh for key in _VERTEX_KEYS}
    st_spec.update({key: rep for key in _SCALAR_KEYS})

    def sharded_chunk_kernel(nbr, deg, aux, st):
        body = _make_shard_body(
            nbr, deg, aux, axis=axis, mode=mode, push_cap=cap,
            tier_meta=tier_meta,
        )

        def cond2(c):
            return _shard_cond(c[0]) & (c[1] < chunk)

        def body2(c):
            return body(c[0]), c[1] + 1

        st, _steps = jax.lax.while_loop(
            cond2, body2, (_with_transients(st, k, axis=axis), jnp.int32(0))
        )
        return _strip(st)

    from bibfs_tpu.solvers.sharded import _check_vma_for

    return jax.jit(
        _shard_map(
            sharded_chunk_kernel,
            mesh=mesh,
            in_specs=(sh, sh, aux_spec, st_spec),
            out_specs=dict(st_spec),
            # off only for interpret-mode pallas programs (see
            # sharded._check_vma_for): the real kernel body must run
            check_vma=_check_vma_for(mode, geom),
        ),
        donate_argnums=3,  # same dead-previous-state rule as the dense leg
    )


@lru_cache(maxsize=None)
def _sharded2d_chunk_kernel(
    mesh, R: int, C: int, mode: str, tier_meta: tuple, chunk: int
):
    """shard_map'd ``(bnbr, bcnt, deg, aux, state) -> state`` advancing at
    most ``chunk`` rounds of the 2D-partitioned search. The portable
    carry's ``md_*`` (Beamer gate input, unused by the pull-only 2D body)
    is dropped on entry and recomputed from the live frontier on exit, so
    a snapshot leaving a 2D mesh resumes correctly on a Beamer-routed
    backend."""
    from bibfs_tpu.parallel.mesh import COL_AXIS, ROW_AXIS
    from bibfs_tpu.solvers.sharded2d import _2d_cond, _make_2d_body

    # the 2D path is pull-only: hybrid/pallas schedules degrade to their
    # base schedule (DENSE_MODES' first column) when a snapshot written
    # under them resumes on a 2D mesh — the level-synchronous carry is
    # schedule-portable (the caller also remaps pre-cache-key; this is
    # belt-and-braces for direct callers)
    mode2d = DENSE_MODES[mode][0]
    axes = (ROW_AXIS, COL_AXIS)
    blk4 = P(ROW_AXIS, COL_AXIS, None, None)
    blk3 = P(ROW_AXIS, COL_AXIS, None)
    own = P((ROW_AXIS, COL_AXIS))
    rep = P()
    aux_spec = tuple((blk4, blk3) for _ in tier_meta)
    st_spec = {key: own for key in _VERTEX_KEYS}
    st_spec.update({key: rep for key in _SCALAR_KEYS})

    def sharded2d_chunk_kernel(bnbr, bcnt, deg, aux, st):
        tiers = tuple(
            (start, tn[0, 0], ti[0, 0])
            for (start, _kp, _wt), (tn, ti) in zip(tier_meta, aux)
        )
        body = _make_2d_body(
            bnbr[0, 0], bcnt[0, 0], deg, tiers, R=R, C=C, mode=mode2d
        )
        loop_st = {k: v for k, v in st.items() if not k.startswith("md_")}

        def cond2(c2):
            return _2d_cond(c2[0]) & (c2[1] < chunk)

        def body2(c2):
            return body(c2[0]), c2[1] + 1

        out, _steps = jax.lax.while_loop(cond2, body2, (loop_st, jnp.int32(0)))
        for side in ("s", "t"):
            out[f"md_{side}"] = jax.lax.pmax(
                jnp.max(jnp.where(out[f"fr_{side}"], deg, 0)), axes
            )
        return out

    return jax.jit(
        _shard_map(
            sharded2d_chunk_kernel,
            mesh=mesh,
            in_specs=(blk4, blk3, own, aux_spec, dict(st_spec)),
            out_specs=dict(st_spec),
        ),
        donate_argnums=4,  # same dead-previous-state rule as the dense leg
    )


# ------------------------------------------------------- state lifecycle


def _init_state_np(n_pad: int, src: int, dst: int, deg_src: int, deg_dst: int):
    """Fresh portable carry as host arrays (level 0, both seeds placed)."""
    st = {}
    for side, v, d in (("s", src, deg_src), ("t", dst, deg_dst)):
        fr = np.zeros(n_pad, dtype=bool)
        fr[v] = True
        dist = np.full(n_pad, INF32, dtype=np.int32)
        dist[v] = 0
        st[f"fr_{side}"] = fr
        st[f"par_{side}"] = np.full(n_pad, -1, dtype=np.int32)
        st[f"dist_{side}"] = dist
        st[f"cnt_{side}"] = np.int32(1)
        st[f"md_{side}"] = np.int32(d)
        st[f"lvl_{side}"] = np.int32(0)
    st["best"] = np.int32(0 if src == dst else INF32)
    st["meet"] = np.int32(src if src == dst else -1)
    st["levels"] = np.int32(0)
    st["edges"] = np.int32(0)
    return st


def _refit(state: dict, n_pad: int) -> dict:
    """Re-pad the per-vertex arrays to a new padded size (mesh elasticity:
    dense pads to 8, an 8-device mesh to 64). Padded rows are inert by
    construction (degree 0, unreachable), so growing adds inert rows and
    shrinking requires the dropped tail to be inert."""
    old = state["fr_s"].shape[0]
    if old == n_pad:
        return state
    out = dict(state)
    fills = {"fr": False, "par": -1, "dist": INF32}
    for key in _VERTEX_KEYS:
        arr = state[key]
        fill = fills[key.split("_")[0]]
        if n_pad > old:
            out[key] = np.concatenate(
                [arr, np.full(n_pad - old, fill, dtype=arr.dtype)]
            )
        else:
            tail = arr[n_pad:]
            inert = (
                not tail.any()
                if key.startswith("fr")
                else (tail >= INF32).all() if key.startswith("dist")
                else True
            )
            if not inert:
                raise ValueError(
                    f"cannot shrink checkpoint state from n_pad={old} to "
                    f"{n_pad}: {key} has live entries in the dropped tail"
                )
            out[key] = np.ascontiguousarray(arr[:n_pad])
    return out


def _vertex_sharding(g):
    """The NamedSharding of per-vertex state on ``g``'s mesh: 1D over the
    vertex axis, or row-major over both axes of a 2D mesh (the fold
    layout of :mod:`bibfs_tpu.solvers.sharded2d`)."""
    from jax.sharding import NamedSharding

    from bibfs_tpu.parallel.mesh import COL_AXIS, ROW_AXIS, shard_spec

    if g.mesh.devices.ndim == 2:
        return NamedSharding(g.mesh, P((ROW_AXIS, COL_AXIS)))
    return shard_spec(g.mesh)


def _put_state(state: dict, g) -> dict:
    """Host carry -> device carry with the graph's shardings (sharded
    vertex arrays on a Sharded(2D)Graph, plain device arrays otherwise)."""
    from bibfs_tpu.parallel.mesh import replicated_spec

    state = _refit(state, g.n_pad)
    dev = {}
    if hasattr(g, "mesh"):
        vspec = _vertex_sharding(g)
        sspec = replicated_spec(g.mesh)
        for key in _VERTEX_KEYS:
            dev[key] = jax.device_put(state[key], vspec)
        for key in _SCALAR_KEYS:
            dev[key] = jax.device_put(np.int32(state[key]), sspec)
    else:
        for key in _VERTEX_KEYS:
            dev[key] = jax.device_put(state[key])
        for key in _SCALAR_KEYS:
            dev[key] = jax.device_put(np.int32(state[key]))
    return dev


def _fetch_state(st: dict) -> dict:
    return {key: np.asarray(st[key]) for key in _STATE_KEYS}


# ----------------------------------------------------------- persistence


@dataclasses.dataclass
class CheckpointMeta:
    """Identity + progress of a snapshot. ``n``/``num_edges``/``src``/
    ``dst`` fingerprint the search (resuming against a different graph or
    query is refused); ``mode`` is the schedule it ran under (resume may
    override it — the level-synchronous carry is schedule-portable)."""

    n: int
    num_edges: int
    src: int
    dst: int
    mode: str
    levels: int
    elapsed_s: float = 0.0  # search seconds accumulated across resumes
    version: int = CKPT_VERSION

    def check(self, g, src: int, dst: int) -> None:
        if self.version != CKPT_VERSION:
            raise ValueError(
                f"checkpoint version {self.version} != {CKPT_VERSION}"
            )
        mine = (g.n, g.num_edges, src, dst)
        theirs = (self.n, self.num_edges, self.src, self.dst)
        if mine != theirs:
            raise ValueError(
                f"checkpoint fingerprint mismatch: file has (n, edges, src, "
                f"dst)={theirs}, caller has {mine}"
            )


def save_checkpoint(path: str, state: dict, meta: CheckpointMeta) -> None:
    """Atomic snapshot: write ``<path>.tmp``, fsync it, then ``os.replace``
    — a process crash mid-write leaves the previous checkpoint intact, and
    the fsync keeps a SYSTEM crash right after the rename from leaving a
    truncated npz behind the new name (rename-before-data reordering)."""
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            _meta=np.bytes_(json.dumps(dataclasses.asdict(meta))),
            **state,
        )
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_checkpoint(path: str) -> tuple[CheckpointMeta, dict]:
    """Load a snapshot. A file that is not a valid checkpoint (corrupt
    archive, missing arrays, malformed metadata) raises ValueError with
    the reason — never a raw zipfile/pickle/KeyError traceback."""
    try:
        with np.load(path) as z:
            meta = CheckpointMeta(
                **json.loads(bytes(z["_meta"].item()).decode())
            )
            state = {key: z[key] for key in _STATE_KEYS}
    except OSError:
        raise  # missing/unreadable file: the errno message is already clear
    except Exception as e:
        raise ValueError(
            f"{path} is not a valid checkpoint: {type(e).__name__}: {e}"
        ) from e
    return meta, state


def _deg_at(g, v: int) -> int:
    """Seed degree for the initial carry. A sharded array can't be indexed
    eagerly (gather output sharding is ambiguous) — ask for a replicated
    result explicitly."""
    if hasattr(g, "mesh"):
        from bibfs_tpu.parallel.mesh import replicated_spec

        try:
            return int(
                g.deg.at[jnp.int32(v)].get(
                    out_sharding=replicated_spec(g.mesh)
                )
            )
        except TypeError:
            # older jax: .at[].get has no out_sharding — pull the (one)
            # sharded vector to host for the scalar seed read instead
            return int(np.asarray(g.deg)[v])
    return int(jax.device_get(g.deg[v]))


# ---------------------------------------------------------------- driver


def _get_chunk_step(g, mode: str, chunk: int):
    """One-chunk advance function ``step(state) -> state`` for whichever
    execution substrate ``g`` is (dense chip / 1D mesh / 2D mesh)."""
    from bibfs_tpu.parallel.mesh import VERTEX_AXIS

    if hasattr(g, "bnbr"):  # Sharded2DGraph
        # remap BEFORE the lru_cache key so 'pallas'/'beamer' share the
        # base-schedule kernel instead of compiling identical duplicates
        kern = _sharded2d_chunk_kernel(
            g.mesh, g.R, g.C, DENSE_MODES[mode][0], g.tier_meta, chunk
        )
        return lambda st: kern(g.bnbr, g.bcnt, g.deg, g.aux, st)
    if hasattr(g, "mesh"):  # ShardedGraph
        # Mosaic-availability fallback resolved BEFORE the cache key; the
        # shard body itself degrades oversized graphs via pallas_fits
        from bibfs_tpu.solvers.dense import _resolve_pallas_mode
        from bibfs_tpu.solvers.sharded import _shard_geom

        if mode in ("fused", "fused_alt"):  # same rule as _compiled_sharded
            mode = {"fused": "pallas", "fused_alt": "pallas_alt"}[mode]
        mode = _resolve_pallas_mode(mode, _shard_geom(g))
        cap = kernel_cap(mode, g.n_pad)
        kern = _sharded_chunk_kernel(
            g.mesh, VERTEX_AXIS, mode, cap, g.tier_meta, chunk, _shard_geom(g)
        )
        return lambda st: kern(g.nbr, g.deg, g.aux, st)
    # DeviceGraph
    from bibfs_tpu.solvers.dense import _resolve_pallas_mode

    if mode in ("fused", "fused_alt"):
        # chunked execution snapshots the standard state dict; the fused
        # programs' dual-row carry has no snapshot form, so chunked/
        # resumed fused solves run the expansion-kernel modes instead
        mode = {"fused": "pallas", "fused_alt": "pallas_alt"}[mode]
    # Mosaic-unsupported -> base schedule (probe at the real geometry)
    mode = _resolve_pallas_mode(mode, (g.n_pad, g.n_pad, g.width))
    aux = g.aux
    if DENSE_MODES[mode][2]:
        from bibfs_tpu.ops.pallas_expand import pallas_fits

        if pallas_fits(g.n_pad, width=g.width):
            # build the kernel table ONCE per drive, device-resident, and
            # pair it with the original tier aux — each chunk dispatch
            # reuses it instead of re-transposing per chunk
            aux = (_prepare_tables_jit()(g.nbr, g.deg), g.aux)
        else:
            # too large for the kernel's static chunk loop: degrade to the
            # base schedule, same rule as the 1D/2D substrates
            mode = DENSE_MODES[mode][0]
    cap = kernel_cap(mode, g.n_pad)
    kern = _dense_chunk_kernel(mode, cap, g.tier_meta, chunk)
    return lambda st: kern(g.nbr, g.deg, aux, st)


def _drive(g, state_np, meta, *, mode, chunk, path, max_chunks):
    """The chunk loop: dispatch -> host-read the termination scalars ->
    snapshot -> repeat. Returns a BFSResult, or None when ``max_chunks``
    ran out first (state is durable in ``path`` if one was given)."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    step = _get_chunk_step(g, mode, chunk)
    st = _put_state(state_np, g)
    base_s = meta.elapsed_s  # search time accumulated by prior runs
    t0 = time.perf_counter()
    chunks = 0
    while True:
        st = step(st)
        # periodic host sync: three scalars decide termination (the same
        # predicate as the in-loop cond). Reading them also FORCES
        # execution of the queued chunk (solvers/timing.py laziness note).
        best = int(st["best"])
        running = (
            int(st["lvl_s"]) + int(st["lvl_t"]) < best
            and int(st["cnt_s"]) > 0
            and int(st["cnt_t"]) > 0
        )
        chunks += 1
        if path is not None:
            meta = dataclasses.replace(
                meta,
                levels=int(st["levels"]),
                elapsed_s=base_s + (time.perf_counter() - t0),
            )
            save_checkpoint(path, _fetch_state(st), meta)
        if not running:
            break
        if max_chunks is not None and chunks >= max_chunks:
            return None
    # cumulative across resumes, so levels/edges/time stay consistent and
    # the reported TEPS describes the WHOLE search
    elapsed = base_s + (time.perf_counter() - t0)
    out = (
        st["best"], st["meet"], st["par_s"], st["par_t"],
        st["levels"], st["edges"],
    )
    return _materialize(out, elapsed)


def solve_checkpointed(
    g,
    src: int,
    dst: int,
    *,
    mode: str = "sync",
    chunk: int = 8,
    path: str | None = None,
    max_chunks: int | None = None,
) -> BFSResult | None:
    """Chunked search on a :class:`~bibfs_tpu.solvers.dense.DeviceGraph`,
    :class:`~bibfs_tpu.solvers.sharded.ShardedGraph`, or
    :class:`~bibfs_tpu.solvers.sharded2d.Sharded2DGraph`: at most ``chunk``
    rounds per dispatch, snapshotting to ``path`` after every chunk.
    Returns the result, or ``None`` if ``max_chunks`` chunks ran out first
    (resume later with :func:`resume`). ``path=None`` gives pure chunked
    execution (periodic host sync, no disk)."""
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    deg_src = _deg_at(g, src)
    deg_dst = _deg_at(g, dst)
    state = _init_state_np(g.n_pad, src, dst, deg_src, deg_dst)
    meta = CheckpointMeta(
        n=g.n, num_edges=g.num_edges, src=src, dst=dst, mode=mode, levels=0
    )
    return _drive(
        g, state, meta, mode=mode, chunk=chunk, path=path,
        max_chunks=max_chunks,
    )


def resume(
    path: str,
    g,
    *,
    src: int,
    dst: int,
    mode: str | None = None,
    chunk: int = 8,
    max_chunks: int | None = None,
) -> BFSResult | None:
    """Continue a checkpointed search from its last completed chunk. ``g``
    may be a different backend or mesh size than the one that wrote the
    snapshot (state is re-padded/re-sharded); ``src``/``dst`` must match
    the file's fingerprint. ``mode=None`` keeps the snapshot's schedule.

    The resumed result's ``time_s`` and per-run counters (``levels``,
    ``edges_scanned``) are cumulative across the original run and the
    resume — the search continues, it does not restart."""
    meta, state = load_checkpoint(path)
    meta.check(g, src, dst)
    return _drive(
        g, state, meta, mode=mode or meta.mode, chunk=chunk, path=path,
        max_chunks=max_chunks,
    )
