"""Single-chip device-resident bidirectional BFS — the v3 replacement.

The reference v3 (v3/bibfs_cuda_only.cu:173-203) launches one CUDA kernel
per side per level, synchronizing and copying flag bytes back to the host
every iteration; v4 additionally round-trips the whole frontier+visited
arrays through host memory per level (v4/comp.cu:84-107, quirk Q5). Here the
ENTIRE search — both frontiers, visited sets, parents, distances, direction
choice, meet detection, and termination vote — is one ``jax.lax.while_loop``
inside one jitted XLA program: state never leaves HBM, and the host syncs
exactly once, at the end.

Algorithmic upgrades over the reference:
- smaller-frontier-first direction choice (v1/main-v1.cpp:51, v4
  mpi_bas.cpp:90-92 — absent in v3, which expands both sides every round)
- provably-correct termination: keep the best meet candidate and stop when
  ``level_s + level_t >= best`` (fixes quirks Q1/Q2)
- true hop counts and device-computed parent arrays for path reconstruction
  (v3 reports only found/not-found, v3/bibfs_cuda_only.cu:224; v2/v4
  re-run a serial BFS on the host, second_try.cpp:137-162)
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from bibfs_tpu.graph.csr import EllGraph, build_ell, build_tiered
from bibfs_tpu.ops.expand import (
    expand_pull_dual_tiered,
    expand_pull_tiered,
    expand_push_tiered,
    frontier_count,
    frontier_degree_sum,
)
from bibfs_tpu.solvers.api import BFSResult, register
from bibfs_tpu.solvers.serial import _reconstruct

# "infinite" distance sentinel; a plain int so importing this module never
# touches a JAX backend (device constants would initialize one eagerly)
INF32 = 1 << 30


@lru_cache(maxsize=4096)
def _device_scalar(v: int) -> jax.Array:
    """Device-resident int32 scalar, cached by value.

    Reusing the scalar buffer avoids a per-solve host->device transfer of
    the src/dst arguments. (calibration.json records the measured
    cached-vs-fresh dispatch cost per platform; on the tunneled backend
    the synchronous per-dispatch tax dwarfs both, but the cache stays —
    it is free and matters on backends with normal dispatch.)
    """
    return jnp.int32(v)


@dataclasses.dataclass
class DeviceGraph:
    """ELL (optionally tiered) adjacency resident in device HBM — the
    analog of v4's ``cudaInitGraph`` upload (v4/comp.cu:49-73), done once
    per graph. ``tiers`` (power-law graphs) holds one
    ``(nbr [count_pad, width], hub_ids [count_pad])`` array pair per hub
    tier; ``tier_meta`` carries the matching static ``(start, count,
    width)`` triples used as a jit-cache key."""

    n: int
    n_pad: int
    width: int
    num_edges: int
    nbr: jax.Array  # int32[n_pad, width]
    deg: jax.Array  # int32[n_pad] (TRUE degree when tiered)
    hub_rank: jax.Array | None = None  # int32[n_pad] when tiered
    tiers: tuple = ()  # ((nbr, hub_ids), ...)
    tier_meta: tuple = ()  # ((start, count, width), ...)

    @classmethod
    def from_ell(cls, g: EllGraph, device=None) -> "DeviceGraph":
        if g.overflow.shape[0]:
            raise NotImplementedError(
                "EllGraph has width_cap overflow edges; use build_tiered "
                "(tiered ELL) for skewed-degree graphs instead of width_cap"
            )
        put = partial(jax.device_put, device=device) if device else jax.device_put
        return cls(
            n=g.n,
            n_pad=g.n_pad,
            width=g.width,
            num_edges=g.num_edges,
            nbr=put(g.nbr),
            deg=put(g.deg),
        )

    @classmethod
    def from_tiered(cls, g, device=None) -> "DeviceGraph":
        """Upload a :class:`bibfs_tpu.graph.csr.TieredEllGraph`."""
        put = partial(jax.device_put, device=device) if device else jax.device_put
        tiers = []
        meta = []
        for t in g.tiers:
            count_pad = t.nbr.shape[0]
            tiers.append((put(t.nbr), put(g.hub_ids[:count_pad])))
            meta.append((t.start, t.count, t.nbr.shape[1]))
        return cls(
            n=g.n,
            n_pad=g.n_pad,
            width=g.width,
            num_edges=g.num_edges,
            nbr=put(g.nbr),
            deg=put(g.deg),
            hub_rank=put(g.hub_rank) if g.tiers else None,
            tiers=tuple(tiers),
            tier_meta=tuple(meta),
        )

    @property
    def aux(self):
        """The tier pytree passed through jit: () for plain ELL."""
        return (self.hub_rank, self.tiers) if self.tiers else ()

    @classmethod
    def build(
        cls,
        n: int,
        edges: np.ndarray | None = None,
        *,
        layout: str = "ell",
        device=None,
        pairs: np.ndarray | None = None,
    ) -> "DeviceGraph":
        """Build + upload in one step. ``layout="ell"`` = single-width table
        (uniform-degree graphs); ``layout="tiered"`` = base table +
        geometric hub tiers (power-law/RMAT degree distributions). ``pairs``
        is the precomputed :func:`~bibfs_tpu.graph.csr.canonical_pairs`
        result, letting callers canonicalize once across layouts."""
        if layout == "tiered":
            return cls.from_tiered(build_tiered(n, edges, pairs=pairs), device=device)
        if layout == "ell":
            return cls.from_ell(build_ell(n, edges, pairs=pairs), device=device)
        raise ValueError(f"unknown layout {layout!r} (expected 'ell' or 'tiered')")


@dataclasses.dataclass
class BlockedDeviceGraph:
    """The MXU-tile blocked adjacency resident in device HBM — the
    upload of :class:`bibfs_tpu.graph.blocked.BlockedGraph`, done once
    per graph like :meth:`DeviceGraph.from_ell`. ``tab`` stays int8 on
    device (the MXU's native input dtype; the CPU substrate's kernel
    casts to its resolved plane dtype at the dot)."""

    n: int
    n_pad: int
    tile: int
    nblocks: int
    bwidth: int
    num_edges: int
    tab: jax.Array  # int8 [nblocks, bwidth, tile, tile]
    bcol: jax.Array  # int32 [nblocks, bwidth], sentinel nblocks
    deg: jax.Array  # int32 [n_pad]

    @classmethod
    def from_host(cls, bg, device=None) -> "BlockedDeviceGraph":
        put = (
            partial(jax.device_put, device=device) if device
            else jax.device_put
        )
        return cls(
            n=bg.n, n_pad=bg.n_pad, tile=bg.tile, nblocks=bg.nblocks,
            bwidth=bg.bwidth, num_edges=bg.num_edges,
            tab=put(bg.tab), bcol=put(bg.bcol), deg=put(bg.deg),
        )


_BIGI = 2147483647  # int32 max: never wins a min


def _blocked_active(st):
    """Per-query live mask, the minor kernel's exact rule: both sides
    advance lock-step, so a query stops once ``2 * rnd >= best`` or
    either frontier empties."""
    return (
        (2 * st["rnd"] < st["best"])
        & (st["cnt_s"] > 0)
        & (st["cnt_t"] > 0)
    )


def _make_blocked_body(tab, bcol, deg, b: int, rc: int):
    """The blocked level body ``st -> st``: advance BOTH sides of all
    ``b`` queries one level as masked block matmuls
    (:func:`bibfs_tpu.ops.blocked_expand.expand_blocked_plane`). The
    dual-side plane ``fr [n_pad, 2b]`` (source columns ``0..b-1``,
    target columns ``b..2b-1``) rides ONE adjacency sweep per round —
    the whole flush amortizes the table, which is the route's point.
    Discovery masking, per-query freeze, the plane-wide meet vote and
    the ``lvl_s + lvl_t >= best`` stop are the batch-minor kernel's
    exact rules; parents are NOT tracked (a matmul has no argmin seam)
    — paths reconstruct from the dist planes on the host
    (:func:`_materialize_blocked_batch`), outside the timed region."""
    from bibfs_tpu.ops.blocked_expand import expand_blocked_plane

    def body(st):
        act = _blocked_active(st)
        actc = jnp.concatenate([act, act])
        acti = act.astype(jnp.int32)
        lvl = st["rnd"] + 1
        # edges scanned this round = the CURRENT frontiers' degree sums
        scanned = jnp.sum(
            jnp.where(st["fr"] > 0, deg[:, None], 0), axis=0
        )
        reach = expand_blocked_plane(st["fr"], tab, bcol, rc=rc)
        new = reach & (st["dist"] >= INF32) & actc[None, :]
        dist = jnp.where(new, lvl, st["dist"])
        ds, dtp = dist[:, :b], dist[:, b:]
        sums = jnp.where((ds < INF32) & (dtp < INF32), ds + dtp, INF32)
        mval = jnp.min(sums, axis=0)
        rowid = jax.lax.broadcasted_iota(jnp.int32, sums.shape, 0)
        midx = jnp.min(
            jnp.where(sums == mval[None, :], rowid, _BIGI), axis=0
        )
        take = mval < st["best"]
        return dict(
            fr=new.astype(st["fr"].dtype),
            dist=dist,
            best=jnp.minimum(st["best"], mval),
            meet=jnp.where(take, midx, st["meet"]),
            cnt_s=jnp.sum(new[:, :b], axis=0, dtype=jnp.int32),
            cnt_t=jnp.sum(new[:, b:], axis=0, dtype=jnp.int32),
            levels=st["levels"] + 2 * acti,
            edges=st["edges"] + (scanned[:b] + scanned[b:]) * acti,
            rnd=lvl,
        )

    return body


def _build_blocked_kernel(nblocks: int, bwidth: int, b: int, dt, rc: int,
                          tile: int = 128):
    """The jitted whole-batch blocked search for one (table, batch)
    geometry: ``(tab, bcol, deg, srcs, dsts) -> (best, meet,
    dist [n_pad, 2b], levels, edges)``. Like the minor kernel, a pure
    function of the PADDED geometry — the graph's true ``n`` never
    enters the program."""
    n_pad = nblocks * tile

    def blocked_kernel(tab, bcol, deg, srcs, dsts):
        qi = jnp.arange(b, dtype=jnp.int32)
        fr = (
            jnp.zeros((n_pad, 2 * b), dt)
            .at[srcs, qi].set(1).at[dsts, b + qi].set(1)
        )
        dist = (
            jnp.full((n_pad, 2 * b), INF32, jnp.int32)
            .at[srcs, qi].set(0).at[dsts, b + qi].set(0)
        )
        st = dict(
            fr=fr, dist=dist,
            best=jnp.where(srcs == dsts, 0, INF32).astype(jnp.int32),
            meet=jnp.where(srcs == dsts, srcs, -1).astype(jnp.int32),
            cnt_s=jnp.ones((b,), jnp.int32),
            cnt_t=jnp.ones((b,), jnp.int32),
            levels=jnp.zeros((b,), jnp.int32),
            edges=jnp.zeros((b,), jnp.int32),
            rnd=jnp.int32(0),
        )
        body = _make_blocked_body(tab, bcol, deg, b, rc)
        out = jax.lax.while_loop(
            lambda st: jnp.any(_blocked_active(st)), body, st
        )
        return (
            out["best"], out["meet"], out["dist"],
            out["levels"], out["edges"],
        )

    return blocked_kernel


@lru_cache(maxsize=None)
def _get_blocked_kernel(nblocks: int, bwidth: int, b: int, dt, rc: int,
                        tile: int = 128):
    return jax.jit(_build_blocked_kernel(nblocks, bwidth, b, dt, rc, tile))


def _walk_dist_plane(row_ptr, col_ind, dvec, v: int) -> list[int]:
    """Walk ``v`` back to its side's root along strictly-decreasing
    level stamps. Level-synchronous dists make this exact: every
    stamped vertex at level l > 0 has at least one neighbor stamped
    l - 1 (the one that discovered it)."""
    path = [v]
    lvl = int(dvec[v])
    while lvl > 0:
        for u in col_ind[row_ptr[v]: row_ptr[v + 1]]:
            if dvec[u] == lvl - 1:
                v = int(u)
                lvl -= 1
                path.append(v)
                break
        else:  # impossible for a level-synchronous stamping
            raise RuntimeError(
                f"blocked dist plane inconsistent at vertex {v}"
            )
    return path


def _materialize_blocked_batch(
    out, pairs, elapsed: float, row_ptr, col_ind
) -> list[BFSResult]:
    """The blocked route's untimed epilogue: one device->host transfer
    per output, then per-query path reconstruction from the dist
    planes over the host CSR — the walk costs ``hops * deg`` per found
    query, cheaper than shipping (or even computing) parent planes."""
    best, meet, dist, levels, edges = (np.asarray(o) for o in out)
    b_pad = dist.shape[1] // 2
    results = []
    for i, (src, dst) in enumerate(pairs):
        if best[i] >= INF32:
            results.append(BFSResult(
                False, None, None, None, elapsed,
                int(levels[i]), int(edges[i]),
            ))
            continue
        m = int(meet[i])
        left = _walk_dist_plane(row_ptr, col_ind, dist[:, i], m)
        right = _walk_dist_plane(row_ptr, col_ind, dist[:, b_pad + i], m)
        results.append(BFSResult(
            True, int(best[i]), left[::-1] + right[1:], m, elapsed,
            int(levels[i]), int(edges[i]),
        ))
    return results


def solve_blocked_batch(
    g: BlockedDeviceGraph, pairs, *, csr, dt=None
) -> list[BFSResult]:
    """Solve many (src, dst) queries through the blocked-matmul kernel
    (``solve_batch_graph`` contract: ``time_s`` is the whole-batch wall
    clock). ``csr`` is the host ``(row_ptr, col_ind)`` the path
    reconstruction walks."""
    from bibfs_tpu.solvers.batch_minor import blocked_batch_dispatch
    from bibfs_tpu.solvers.timing import force_scalar

    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    pairs, thunk = blocked_batch_dispatch(g, pairs, dt=dt)
    t0 = time.perf_counter()
    out = thunk()
    force_scalar(out)  # lazy runtimes execute at the value read
    elapsed = time.perf_counter() - t0
    return _materialize_blocked_batch(out, pairs, elapsed, *csr)


def solve_blocked_graph(
    g: BlockedDeviceGraph, src: int, dst: int, *, csr, dt=None
) -> BFSResult:
    """One query through the blocked kernel (a B=1 plane — the batched
    form is where the layout pays; this exists for parity tests and
    completeness)."""
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    return solve_blocked_batch(g, [(src, dst)], csr=csr, dt=dt)[0]


def _auto_push_cap(n_pad: int) -> int:
    """Frontier size below which push beats pull. Push costs ~K*width
    scattered elements (element-at-a-time scatter/gather), pull costs
    ~n_pad*width*4 bytes of sequential HBM reads.

    When ``calibration.json`` has an entry for this platform (produced by
    ``python bench.py --calibrate``, bibfs_tpu/utils/calibrate.py), the
    crossover is the MEASURED one: K = n_pad / push_cap_divisor rounded
    DOWN to a power of two (never exceeding what was measured faster), and
    a measured verdict of "push never beats pull" (push_cap 0) is honored
    as pull-only. Otherwise fall back to the uncalibrated default divisor
    256 (≈ the v5e-class crossover), rounded to a power of two and
    clamped."""
    from bibfs_tpu.utils.calibrate import load_calibration

    cal = load_calibration() or {}
    if "push_cap" in cal:
        if not cal["push_cap"]:
            return 0  # measured: pull wins at every tested K
        divisor = cal.get("push_cap_divisor")
        if isinstance(divisor, int) and divisor > 0:
            scaled = n_pad // divisor
            cap = 1 << max(7, scaled.bit_length() - 1)
            return int(min(4096, cap, max(128, n_pad)))
        # malformed entry (hand-edited/truncated): fall through to the
        # uncalibrated heuristic rather than crashing every solve
    cap = 1 << max(7, (n_pad // 256).bit_length())
    return int(min(2048, cap, max(128, n_pad)))


def _init_state(n_pad, k, src, dst, deg):
    zeros_b = jnp.zeros(n_pad, dtype=jnp.bool_)

    def side(v):
        fr = zeros_b.at[v].set(True)
        return dict(
            fr=fr,
            fi=jnp.full(k, -1, jnp.int32).at[0].set(v.astype(jnp.int32)),
            ok=jnp.bool_(True),
            cnt=jnp.int32(1),
            md=deg[v],  # max degree in the frontier (Beamer span routing)
            par=jnp.full(n_pad, -1, jnp.int32),
            dist=jnp.where(fr, 0, INF32).astype(jnp.int32),
            lvl=jnp.int32(0),
        )

    st = {f"{key}_s": val for key, val in side(src).items()}
    st.update({f"{key}_t": val for key, val in side(dst).items()})
    st.update(
        best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
        meet=jnp.where(src == dst, src, -1).astype(jnp.int32),
        levels=jnp.int32(0),
        edges=jnp.int32(0),
    )
    return st


def _meet_vote(st, delta):
    """Fused check_intersect (v3/bibfs_cuda_only.cu:45-62): best candidate
    distance + its meet vertex over the visited intersection. dist values of
    visited vertices are final in a level-synchronous BFS, so the min is
    exact. Visited sets are implicit: ``dist < INF32``."""
    both = (st["dist_s"] < INF32) & (st["dist_t"] < INF32)
    sums = jnp.where(both, st["dist_s"] + st["dist_t"], INF32)
    cur = jnp.min(sums)
    arg = jnp.argmin(sums).astype(jnp.int32)
    st["meet"] = jnp.where(cur < st["best"], arg, st["meet"])
    st["best"] = jnp.minimum(st["best"], cur)
    st["levels"] = st["levels"] + delta
    return st


def _outputs(out):
    return (
        out["best"],
        out["meet"],
        out["par_s"],
        out["par_t"],
        out["levels"],
        out["edges"],
    )


def _cond(st):
    # provably-correct stop: once lvl_s+lvl_t >= best no undiscovered vertex
    # can improve the meet (the midpoint of any shorter path would already
    # be visited by both sides) — fixes quirks Q1/Q2. Frontier-emptiness is
    # a scalar carry (v2 recomputed it with two Allreduce SUMs per level,
    # second_try.cpp:117-128).
    return (
        (st["lvl_s"] + st["lvl_t"] < st["best"])
        & (st["cnt_s"] > 0)
        & (st["cnt_t"] > 0)
    )


def _unrolled(body, unroll: int, cond=None):
    """Run ``unroll`` search rounds per ``while_loop`` iteration.

    The while cond is only evaluated once per block, so the fixed
    per-iteration cost (loop bookkeeping plus, on the tunneled runtime,
    whatever the backend charges per dynamic-trip iteration — the
    unexplained ~12 ms/level residual of VERDICT r4 weak #2) is
    amortized over ``unroll`` levels. Correctness is exact, not
    approximate: every in-block round after the first re-checks the SAME
    ``cond`` the while loop uses (default :func:`_cond`; the sharded
    solver passes its replicated-vote ``_shard_cond``) under
    ``lax.cond``, so a search that terminates mid-block skips the
    remaining rounds — nothing runs that the single-level program would
    not have run."""
    if cond is None:
        cond = _cond
    if unroll <= 1:
        return body

    def block(st):
        st = body(st)  # round 1: the while cond just approved it
        for _ in range(unroll - 1):
            st = jax.lax.cond(cond(st), body, lambda s: s, st)
        return st

    return block


def _full_tiers(aux, tier_meta) -> tuple:
    """Zip the static tier metadata with the device tier arrays into the
    ``(start, count, tier_nbr, hub_ids)`` tuples the expansion ops take —
    the ONE place the (meta, aux) pairing is interpreted."""
    tiers = aux[1] if aux else ()
    return tuple(
        (start, count, tnbr, tids)
        for (start, count, _w), (tnbr, tids) in zip(tier_meta, tiers)
    )


# a frontier whose max degree exceeds this stays on the pull path even
# when small: the push candidate width is static (base + allowed tiers),
# so hub tiers past this span never enter the push gather
PUSH_SPAN_TARGET = 256


def push_span(width: int, tier_meta) -> tuple[int, int]:
    """Static split of hub tiers into push-covered and pull-only. Returns
    ``(span, ncovered)``: the first ``ncovered`` tiers are inside the push
    span (cumulative width up to the first tier starting at or past
    :data:`PUSH_SPAN_TARGET`); a frontier whose max degree exceeds ``span``
    must take the pull path. Shared by the dense and sharded solvers so
    their Beamer gates cannot diverge."""
    span = width
    ncovered = 0
    for start, _count, twidth, *_rest in tier_meta:
        if start >= PUSH_SPAN_TARGET:
            break
        ncovered += 1
        span = start + twidth
    return span, ncovered


def _side_step(
    st, side: str, nbr, deg, aux, tier_meta, *, push_cap: int,
    use_pallas: bool = False,
):
    """Advance one side one level. ``push_cap > 0`` enables Beamer direction
    optimization: frontiers at most ``push_cap`` wide (and whose max degree
    fits the static push span) go through the sparse push path, larger ones
    through the dense pull path. ``push_cap == 0`` is pull-only (the
    v3-style dense schedule). ``use_pallas`` routes the base-table pull
    through the fused Pallas kernel (hub tiers stay as XLA ops)."""
    k = st[f"fi_{side}"].shape[0]
    # under pallas modes aux is (kernel tables, original tier aux): the
    # kernel owns the base table, hub tiers run as XLA ops around it
    if use_pallas:
        ptables, tier_aux = aux
        hub_rank = None  # pallas modes are pull-only; no push path
        full_tiers = _full_tiers(tier_aux, tier_meta)
    else:
        ptables = None
        hub_rank = aux[0] if aux else None
        full_tiers = _full_tiers(aux, tier_meta)
    span, ncov = push_span(nbr.shape[1], tier_meta)
    push_tiers = full_tiers[:ncov]
    carry = (
        st[f"fr_{side}"],
        st[f"fi_{side}"],
        st[f"ok_{side}"],
        st[f"par_{side}"],
        st[f"dist_{side}"],
        st[f"lvl_{side}"],
    )

    def pull(c):
        fr, fi, _ok, par, dist, lvl = c
        scanned = frontier_degree_sum(fr, deg)
        if use_pallas:
            from bibfs_tpu.ops.pallas_expand import pallas_pull_level

            # ptables is the prepared transposed table (built once per
            # solve, outside the while_loop — see _build_kernel)
            nf, par, dist, md = pallas_pull_level(
                fr, par, dist, ptables, deg, full_tiers, lvl + 1, inf=INF32
            )
        else:
            nf, par, dist, md = expand_pull_tiered(
                fr, par, dist, nbr, deg, full_tiers, lvl + 1, inf=INF32
            )
        # the compact index list is now stale; push recomputes it on entry
        return (
            nf, fi, jnp.bool_(False), par, dist, lvl + 1,
            frontier_count(nf), md, scanned,
        )

    def push(c):
        fr, fi, ok, par, dist, lvl = c
        fi = jax.lax.cond(
            ok,
            lambda: fi,
            lambda: jnp.flatnonzero(fr, size=k, fill_value=-1).astype(jnp.int32),
        )
        nf, nfi, cnt, par, dist, scanned, md = expand_push_tiered(
            fi, par, dist, nbr, deg, hub_rank, push_tiers, lvl + 1, inf=INF32
        )
        return nf, nfi, cnt <= k, par, dist, lvl + 1, cnt, md, scanned

    if push_cap > 0:
        use_push = (st[f"cnt_{side}"] <= push_cap) & (st[f"md_{side}"] <= span)
        out = jax.lax.cond(use_push, push, pull, carry)
    else:
        out = pull(carry)
    nf, fi, ok, par, dist, lvl, cnt, md, scanned = out
    return {
        **st,
        f"fr_{side}": nf,
        f"fi_{side}": fi,
        f"ok_{side}": ok,
        f"par_{side}": par,
        f"dist_{side}": dist,
        f"lvl_{side}": lvl,
        f"cnt_{side}": cnt,
        f"md_{side}": md,
        "edges": st["edges"] + scanned,
    }


# mode -> (schedule, hybrid expansion?, pallas pull?). Schedules: "sync"
# expands BOTH sides every round (the v2/v3 schedule, second_try.cpp:68-105
# / bibfs_cuda_only.cu:173-193 — half the sequential rounds, best when
# latency-bound); "alt" expands the smaller frontier only
# (v1/main-v1.cpp:51, v4 mpi_bas.cpp:90-92 — fewest edge scans). "beamer"
# variants add push/pull direction optimization per expansion (Beamer-style
# top-down/bottom-up switching — BASELINE.json config scope, never in the
# reference). "pallas" variants run the base-table pull as the fused Pallas
# kernel (ops/pallas_expand.py — the v3 expand_frontier analog the north
# star names) with hub tiers as XLA ops; interpret-mode off-TPU; the
# v2 rebuild (XLA gather + reduction/key-min kernel) compiles on TPU at
# every audited geometry (AOT_AUDIT.json). "fused" runs the ENTIRE
# lock-step level as one XLA dual gather + ONE whole-level kernel
# (ops/pallas_fused.py): the per-level op-group count, which the
# tunneled backend charges ~2 ms each for (PERF_NOTES §2), drops to
# gather + kernel + one scalar fixup. Plain ELL only; tiered or
# key/VMEM-unfit graphs degrade at trace time.
DENSE_MODES = {
    "sync": ("sync", False, False),
    "alt": ("alt", False, False),
    "beamer": ("sync", True, False),
    "beamer_alt": ("alt", True, False),
    "pallas": ("sync", False, True),
    "pallas_alt": ("alt", False, True),
    "fused": ("sync", False, "fused"),
    "fused_alt": ("alt", False, "fused"),
    # A/B control for the round-3 dual fusion claims (VERDICT r3 item 4):
    # the same lock-step schedule with the PRE-fusion structure — two
    # single-side expansions per round (two table reads; under the 1D
    # mesh, two single-side frontier collectives). Exists to measure the
    # fusion, not to run in production.
    "sync_unfused": ("sync", False, False),
}


def kernel_cap(mode: str, n_pad: int) -> int:
    """The push-cap cache key for (mode, graph): the auto cap for hybrid
    (Beamer) modes, 0 for pull-only modes — so sync/alt/pallas share one
    compiled kernel per shape instead of one per distinct auto cap."""
    return _auto_push_cap(n_pad) if DENSE_MODES[mode][1] else 0


def _make_body(mode: str, cap: int, tier_meta, nbr, deg, aux):
    """The while_loop body ``st -> st`` for (mode, cap, tier layout) over
    the given device graph arrays — shared by the one-shot kernel below and
    the chunked/checkpointed kernel (:mod:`bibfs_tpu.solvers.checkpoint`),
    so the two execution strategies cannot diverge algorithmically."""
    schedule, hybrid, use_pallas = DENSE_MODES[mode]

    def step(st, side):
        return _side_step(
            st, side, nbr, deg, aux, tier_meta,
            push_cap=cap, use_pallas=use_pallas,
        )

    if schedule == "sync" and use_pallas:
        # lock-step pallas: the dual kernel streams the transposed table
        # ONCE per round for both sides (mirrors the XLA dual branch below)
        from bibfs_tpu.ops.pallas_expand import pallas_pull_level_dual

        ptables, tier_aux = aux
        pallas_tiers = _full_tiers(tier_aux, tier_meta)

        def body(st):
            scanned = frontier_degree_sum(
                st["fr_s"], deg
            ) + frontier_degree_sum(st["fr_t"], deg)
            nf_s, par_s, dist_s, md_s, nf_t, par_t, dist_t, md_t = (
                pallas_pull_level_dual(
                    st["fr_s"], st["fr_t"],
                    st["par_s"], st["dist_s"], st["par_t"], st["dist_t"],
                    ptables, deg, pallas_tiers,
                    st["lvl_s"] + 1, st["lvl_t"] + 1, inf=INF32,
                )
            )
            st = {
                **st,
                "fr_s": nf_s, "par_s": par_s, "dist_s": dist_s,
                "md_s": md_s, "cnt_s": frontier_count(nf_s),
                "lvl_s": st["lvl_s"] + 1, "ok_s": jnp.bool_(False),
                "fr_t": nf_t, "par_t": par_t, "dist_t": dist_t,
                "md_t": md_t, "cnt_t": frontier_count(nf_t),
                "lvl_t": st["lvl_t"] + 1, "ok_t": jnp.bool_(False),
                "edges": st["edges"] + scanned,
            }
            return _meet_vote(st, 2)

    elif (schedule == "sync" and not hybrid and not use_pallas
          and mode != "sync_unfused"):
        # pull-only lock-step: fuse both sides' expansions so every
        # neighbor table (base + hub tiers) is gathered ONCE per round
        # for both searches — half the HBM traffic of two sequential
        # pulls, the dominant cost of a pull round
        full_tiers = _full_tiers(aux, tier_meta)

        def body(st):
            scanned = frontier_degree_sum(
                st["fr_s"], deg
            ) + frontier_degree_sum(st["fr_t"], deg)
            nf_s, par_s, dist_s, md_s, nf_t, par_t, dist_t, md_t = (
                expand_pull_dual_tiered(
                    st["fr_s"], st["fr_t"],
                    st["par_s"], st["dist_s"], st["par_t"], st["dist_t"],
                    nbr, deg, full_tiers,
                    st["lvl_s"] + 1, st["lvl_t"] + 1, inf=INF32,
                )
            )
            st = {
                **st,
                "fr_s": nf_s, "par_s": par_s, "dist_s": dist_s,
                "md_s": md_s, "cnt_s": frontier_count(nf_s),
                "lvl_s": st["lvl_s"] + 1, "ok_s": jnp.bool_(False),
                "fr_t": nf_t, "par_t": par_t, "dist_t": dist_t,
                "md_t": md_t, "cnt_t": frontier_count(nf_t),
                "lvl_t": st["lvl_t"] + 1, "ok_t": jnp.bool_(False),
                "edges": st["edges"] + scanned,
            }
            return _meet_vote(st, 2)

    elif schedule == "sync":

        def body(st):
            return _meet_vote(step(step(st, "s"), "t"), 2)

    else:

        def body(st):
            st = jax.lax.cond(
                st["cnt_s"] <= st["cnt_t"],
                lambda st: step(st, "s"),
                lambda st: step(st, "t"),
                st,
            )
            return _meet_vote(st, 1)

    return body


def _build_fused_kernel(tier_meta: tuple = (), unroll: int = 1):
    """The whole-level-kernel search program (mode "fused"): every round
    is one XLA dual gather + one
    :func:`bibfs_tpu.ops.pallas_fused.fused_dual_level` kernel + a scalar
    fixup — state (the dual-coded frontier row, dist/par rows) never
    leaves the kernel layout between levels. ``unroll`` runs that many
    rounds per while iteration (see :func:`_unrolled`). Tiered layouts
    and geometries past the key/VMEM bounds degrade to the round-3
    "pallas" program at trace time (same contract surface:
    ``fn(nbr, deg, aux, src, dst)``)."""
    from bibfs_tpu.ops.pallas_fused import (
        INF32 as FINF,
        dual_seed,
        fused_dual_level,
        fused_fits,
        key_stride,
        prepare_fused_tables,
    )

    assert FINF == INF32

    def dense_fused_kernel(nbr, deg, aux, src, dst):
        n_pad = nbr.shape[0]
        if tier_meta or not fused_fits(n_pad, width=nbr.shape[1]):
            # degrade to the round-3 kernel path (which may itself degrade
            # further); resolved at trace time from static shape/layout
            return _build_kernel("pallas", 0, tier_meta, unroll)(
                nbr, deg, aux, src, dst)
        nbr_t, deg2 = prepare_fused_tables(nbr, deg)
        n_rows_p = nbr_t.shape[1]
        ks = key_stride(n_pad)
        src32 = src.astype(jnp.int32)
        dst32 = dst.astype(jnp.int32)

        def side(v):
            return dict(
                dist=jnp.full((1, n_rows_p), INF32, jnp.int32)
                .at[0, v].set(0),
                par=jnp.full((1, n_rows_p), -1, jnp.int32),
                cnt=jnp.int32(1),
                md=deg[v],
                ds=deg[v],  # degree sum = this frontier's edge-scan count
                lvl=jnp.int32(0),
            )

        st = {f"{k}_s": v for k, v in side(src).items()}
        st.update({f"{k}_t": v for k, v in side(dst).items()})
        st.update(
            dual=dual_seed(src, dst, n_rows_p),
            best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
            meet=jnp.where(src == dst, src32, -1).astype(jnp.int32),
            levels=jnp.int32(0),
            edges=jnp.int32(0),
        )

        def body(st):
            (dual, dist_s, dist_t, par_s, par_t,
             cnt_s, cnt_t, md_s, md_t, ds_s, ds_t, mval, midx) = (
                fused_dual_level(
                    st["dual"], nbr_t, deg2,
                    st["dist_s"], st["dist_t"], st["par_s"], st["par_t"],
                    st["lvl_s"] + 1, st["lvl_t"] + 1, ks=ks,
                )
            )
            take = mval < st["best"]
            return {
                "dual": dual,
                "dist_s": dist_s, "dist_t": dist_t,
                "par_s": par_s, "par_t": par_t,
                "cnt_s": cnt_s, "cnt_t": cnt_t,
                "md_s": md_s, "md_t": md_t,
                "ds_s": ds_s, "ds_t": ds_t,
                "lvl_s": st["lvl_s"] + 1, "lvl_t": st["lvl_t"] + 1,
                "best": jnp.minimum(st["best"], mval),
                "meet": jnp.where(take, midx, st["meet"]),
                "levels": st["levels"] + 2,
                # this round scanned the CURRENT frontiers, whose degree
                # sums were produced by the previous round (or init)
                "edges": st["edges"] + st["ds_s"] + st["ds_t"],
            }

        out = jax.lax.while_loop(_cond, _unrolled(body, unroll), st)
        return (
            out["best"],
            out["meet"],
            out["par_s"][0, :n_pad],
            out["par_t"][0, :n_pad],
            out["levels"],
            out["edges"],
        )

    return dense_fused_kernel


def _build_fused_alt_kernel(tier_meta: tuple = (), unroll: int = 1):
    """The alt-schedule whole-level-kernel program (mode "fused_alt"):
    each round advances only the SMALLER frontier (v1's direction
    choice) through ONE single-side kernel; the shared dual gather runs
    inside the chosen branch. Degrades like mode "fused"."""
    from bibfs_tpu.ops.pallas_fused import (
        dual_seed,
        fused_fits,
        fused_single_level,
        key_stride,
        prepare_fused_tables,
    )

    def dense_fused_alt_kernel(nbr, deg, aux, src, dst):
        n_pad = nbr.shape[0]
        if tier_meta or not fused_fits(n_pad, width=nbr.shape[1]):
            return _build_kernel("pallas_alt", 0, tier_meta, unroll)(
                nbr, deg, aux, src, dst
            )
        nbr_t, deg2 = prepare_fused_tables(nbr, deg)
        n_rows_p = nbr_t.shape[1]
        ks = key_stride(n_pad)
        src32 = src.astype(jnp.int32)

        def side(v):
            return dict(
                dist=jnp.full((1, n_rows_p), INF32, jnp.int32)
                .at[0, v].set(0),
                par=jnp.full((1, n_rows_p), -1, jnp.int32),
                cnt=jnp.int32(1),
                md=deg[v],
                ds=deg[v],
                lvl=jnp.int32(0),
            )

        st = {f"{k}_s": v for k, v in side(src).items()}
        st.update({f"{k}_t": v for k, v in side(dst).items()})
        st.update(
            dual=dual_seed(src, dst, n_rows_p),
            best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
            meet=jnp.where(src == dst, src32, -1).astype(jnp.int32),
            levels=jnp.int32(0),
            edges=jnp.int32(0),
        )

        def round_side(st, side_key, bit):
            other = "t" if side_key == "s" else "s"
            (dual, dist_a, par_a, cnt, md, ds, mval, midx) = (
                fused_single_level(
                    st["dual"], nbr_t, deg2,
                    st[f"dist_{side_key}"], st[f"dist_{other}"],
                    st[f"par_{side_key}"], st[f"lvl_{side_key}"] + 1,
                    bit=bit, ks=ks,
                )
            )
            take = mval < st["best"]
            return {
                **st,
                "dual": dual,
                f"dist_{side_key}": dist_a,
                f"par_{side_key}": par_a,
                f"cnt_{side_key}": cnt,
                f"md_{side_key}": md,
                f"ds_{side_key}": ds,
                f"lvl_{side_key}": st[f"lvl_{side_key}"] + 1,
                "best": jnp.minimum(st["best"], mval),
                "meet": jnp.where(take, midx, st["meet"]),
                "levels": st["levels"] + 1,
                # this round scanned the expanded side's CURRENT frontier
                "edges": st["edges"] + st[f"ds_{side_key}"],
            }

        def body(st):
            return jax.lax.cond(
                st["cnt_s"] <= st["cnt_t"],
                lambda st: round_side(st, "s", 0),
                lambda st: round_side(st, "t", 1),
                st,
            )

        out = jax.lax.while_loop(_cond, _unrolled(body, unroll), st)
        return (
            out["best"],
            out["meet"],
            out["par_s"][0, :n_pad],
            out["par_t"][0, :n_pad],
            out["levels"],
            out["edges"],
        )

    return dense_fused_alt_kernel


def _build_kernel(mode: str, push_cap: int, tier_meta: tuple = (),
                  unroll: int = 1):
    """Build the (unjitted) search kernel for (mode, push_cap, tier layout):
    ``fn(nbr, deg, aux, src, dst) -> (best, meet, parent_s, parent_t,
    levels, edges_scanned)``; ``best >= INF32`` means no path. ``aux`` is
    ``(hub_rank, tiers)`` for tiered graphs, ``()`` otherwise. The whole
    search is one ``lax.while_loop`` in one XLA program — state never
    leaves HBM and the host syncs exactly once at the end (versus per-level
    host round-trips, quirk Q5). ``unroll`` > 1 runs that many rounds per
    while iteration (:func:`_unrolled`) to amortize the fixed
    per-iteration cost; exact for every mode and schedule."""
    if unroll < 1:
        raise ValueError(f"unroll must be >= 1, got {unroll}")
    if mode == "fused":
        return _build_fused_kernel(tier_meta, unroll)
    if mode == "fused_alt":
        return _build_fused_alt_kernel(tier_meta, unroll)
    cap = push_cap if DENSE_MODES[mode][1] else 0
    k = max(cap, 1)

    def dense_kernel(nbr, deg, aux, src, dst):
        n_pad = nbr.shape[0]
        kmode = mode
        if DENSE_MODES[mode][2]:
            from bibfs_tpu.ops.pallas_expand import (
                pallas_fits,
                prepare_pallas_tables,
            )

            if pallas_fits(n_pad, width=nbr.shape[1]):
                # pallas pull: aux becomes (kernel tables, original tier
                # aux). The transposed sentinel-padded table is built HERE
                # — outside the while_loop — so the transpose runs once
                # per solve, not once per level; hub tiers stay as XLA ops
                aux = (prepare_pallas_tables(nbr, deg), aux)
            else:
                # graph too large for the static chunk loop: degrade to the
                # XLA pull path (same documented fallback as an unsupported
                # Mosaic), resolved at trace time from the static shape
                kmode = DENSE_MODES[mode][0]
        init = _init_state(n_pad, k, src, dst, deg)
        body = _make_body(kmode, cap, tier_meta, nbr, deg, aux)
        return _outputs(
            jax.lax.while_loop(_cond, _unrolled(body, unroll), init))

    return dense_kernel


@lru_cache(maxsize=None)
def _resolve_pallas_mode(mode: str, geom: tuple | None = None) -> str:
    """Fall back to the XLA pull path when the compiled Pallas kernel is
    unavailable on this backend (Mosaic vector-gather support varies by
    jaxlib). ``geom = (n_rows, id_space, width)`` makes the probe compile
    the REAL padded geometry the solve will use — Mosaic failures are
    frequently shape-dependent, so the toy-shape probe alone (``geom is
    None``, kept for geometry-less callers) does not prove the target
    shape compiles (VERDICT r3 weak #1). Off-TPU the kernels run
    interpreted and are always available."""
    if not DENSE_MODES[mode][2] or jax.default_backend() != "tpu":
        return mode
    import sys

    if mode in ("fused", "fused_alt"):
        from bibfs_tpu.ops.pallas_fused import fused_available

        single = mode == "fused_alt"  # probe only the kernel THIS mode runs
        ok = (
            fused_available(geom[0], geom[2], id_space=geom[1], single=single)
            if geom else fused_available(single=single)
        )
        if ok:
            return mode
        print(
            "warning: fused level kernel does not compile on this backend "
            f"(geometry {geom}); mode {mode!r} falling back to the "
            "expansion-kernel path",
            file=sys.stderr,
        )
        return _resolve_pallas_mode(
            {"fused": "pallas", "fused_alt": "pallas_alt"}[mode], geom
        )
    from bibfs_tpu.ops.pallas_expand import (
        pallas_available,
        pallas_available_at,
    )

    ok = pallas_available_at(*geom) if geom else pallas_available()
    if ok:
        return mode
    print(
        f"warning: Pallas pull kernel does not compile on this backend "
        f"(geometry {geom}); mode {mode!r} falling back to the XLA pull "
        "path",
        file=sys.stderr,
    )
    return {"pallas": "sync", "pallas_alt": "alt"}[mode]


def _geom_of(g: "DeviceGraph") -> tuple:
    """The (n_rows, id_space, width) probe geometry of a device graph."""
    return (g.n_pad, g.n_pad, g.width)


def _get_kernel(mode: str, push_cap: int, tier_meta: tuple = (),
                geom: tuple | None = None, unroll: int = 1):
    # resolve the pallas fallback BEFORE the cache key so a fallen-back
    # 'pallas' shares the already-compiled 'sync' kernel instead of paying
    # a redundant XLA compile of an identical program
    if mode in ("fused", "fused_alt") and (
        tier_meta or (geom is not None and not _fused_fits_geom(geom))
    ):
        # a fused solve that will degrade at trace time must degrade HERE
        # first, so the probe chain gates the kernel it will actually run
        # (probing only the fused kernel and then tracing the pallas one
        # would bypass the Mosaic availability check)
        mode = {"fused": "pallas", "fused_alt": "pallas_alt"}[mode]
    return _get_kernel_resolved(
        _resolve_pallas_mode(mode, geom), push_cap, tier_meta, unroll
    )


def _fused_fits_geom(geom: tuple) -> bool:
    from bibfs_tpu.ops.pallas_fused import fused_fits

    return fused_fits(geom[0], id_space=geom[1], width=geom[2])


@lru_cache(maxsize=None)
def _get_kernel_resolved(mode: str, push_cap: int, tier_meta: tuple = (),
                         unroll: int = 1):
    return jax.jit(_build_kernel(mode, push_cap, tier_meta, unroll))


def _get_batch_kernel(mode: str, push_cap: int, tier_meta: tuple = (),
                      geom: tuple | None = None):
    # same pre-cache pallas resolution as _get_kernel. The fused kernel's
    # cross-grid (1,1) accumulators assume grid axis 0 is the vertex tile
    # walk; vmap would prepend a batch grid dim and break that, so batch
    # queries route to the expansion-kernel modes instead
    if mode in ("fused", "fused_alt"):
        mode = {"fused": "pallas", "fused_alt": "pallas_alt"}[mode]
    return _get_batch_kernel_resolved(
        _resolve_pallas_mode(mode, geom), push_cap, tier_meta
    )


@lru_cache(maxsize=None)
def _get_batch_kernel_resolved(mode: str, push_cap: int, tier_meta: tuple = ()):
    """vmap of the full search over (src, dst) pairs: B independent
    bidirectional searches advance lock-step inside ONE compiled while_loop
    (finished searches freeze via select until the last one stops) — the
    amortized-throughput mode the reference cannot express (one process
    launch per query, benchmark_test.sh:44-59)."""
    return jax.jit(
        jax.vmap(
            _build_kernel(mode, push_cap, tier_meta),
            in_axes=(None, None, None, 0, 0),
        )
    )


@lru_cache(maxsize=None)
def _get_traced_side_step(mode: str, cap: int, tier_meta: tuple, side: str):
    """One jitted single-side expansion round for the telemetry-traced
    driver (:func:`_solve_dense_traced`) — exactly the ``_side_step``
    the compiled while_loop bodies run, jitted per (mode, cap, layout,
    side) so a traced solve pays one compile per side, then per-level
    dispatches."""

    def traced_side_step(nbr, deg, aux, st):
        return _side_step(st, side, nbr, deg, aux, tier_meta,
                          push_cap=cap, use_pallas=False)

    return jax.jit(traced_side_step)


@lru_cache(maxsize=None)
def _get_traced_vote(delta: int):
    def traced_meet_vote(st):
        return _meet_vote(st, delta)

    return jax.jit(traced_meet_vote)


def _solve_dense_traced(
    g: DeviceGraph, src: int, dst: int, mode: str, telemetry
) -> BFSResult:
    """The per-level telemetry drive of the dense search: the SAME
    state dict, side steps, meet vote and termination rule as the
    compiled one-shot program, but stepped level-by-level from the host
    so each round's frontier size, edges scanned and push/pull choice
    can be read off and recorded. Pallas/fused modes trace through
    their XLA-schedule equivalent (the kernels fuse work per level, not
    across levels, so the per-level numbers are the same); the "sync"
    schedule steps its sides sequentially — the documented
    ``sync_unfused`` control body, identical state evolution to the
    fused dual expansion it A/Bs.

    This is the diagnostic path: every level pays a host sync to read
    the counters. The ``telemetry=None`` default in
    :func:`solve_dense_graph` never comes near it."""
    from bibfs_tpu.obs.telemetry import coerce

    tel = coerce(telemetry)
    if tel.n != 0:
        # re-stamp per solve (see solve_serial_csr; n=0 opts out)
        tel.n = g.n
    schedule, hybrid, _pl = DENSE_MODES[mode]
    base_mode = {
        "pallas": "sync", "pallas_alt": "alt",
        "fused": "sync", "fused_alt": "alt",
    }.get(mode, mode)
    cap = kernel_cap(base_mode, g.n_pad)
    k = max(cap, 1)
    span, _ncov = push_span(g.width, g.tier_meta)
    steps = {
        s: _get_traced_side_step(base_mode, cap, g.tier_meta, s)
        for s in ("s", "t")
    }
    vote = _get_traced_vote(2 if schedule == "sync" else 1)
    t0 = time.perf_counter()
    st = _init_state(g.n_pad, k, _device_scalar(src), _device_scalar(dst),
                     g.deg)

    def advance(side):
        """Expand one side; record its pre-step routing and post-step
        frontier/edge telemetry."""
        nonlocal st
        cnt_pre = int(st[f"cnt_{side}"])
        md_pre = int(st[f"md_{side}"])
        edges_pre = int(st["edges"])
        st = steps[side](g.nbr, g.deg, g.aux, st)
        pushed = hybrid and cap > 0 and cnt_pre <= cap and md_pre <= span
        tel.record_level(
            int(st["lvl_s"]) + int(st["lvl_t"]), side,
            "push" if pushed else "pull",
            int(st[f"cnt_{side}"]), int(st["edges"]) - edges_pre,
        )

    while (
        int(st["lvl_s"]) + int(st["lvl_t"]) < int(st["best"])
        and int(st["cnt_s"]) > 0
        and int(st["cnt_t"]) > 0
    ):
        best_pre = int(st["best"])
        if schedule == "sync":
            advance("s")
            advance("t")
        else:  # alt: smaller-frontier-first, the lax.cond's exact rule
            advance("s" if int(st["cnt_s"]) <= int(st["cnt_t"]) else "t")
        st = vote(st)
        if int(st["best"]) < best_pre:
            tel.note_meet(int(st["levels"]), int(st["meet"]))
    elapsed = time.perf_counter() - t0
    res = _materialize(_outputs(st), elapsed)
    res.level_stats = tel.as_dict()
    return res


def bibfs_dense(nbr, deg, src, dst):
    """Pull-only lock-step search (both sides per round). Kept as the plain
    jittable entry (`__graft_entry__.entry`); see :data:`DENSE_MODES` for
    the full schedule × expansion matrix."""
    return _get_kernel("sync", 0)(nbr, deg, (), src, dst)


def bibfs_dense_alt(nbr, deg, src, dst):
    """Pull-only alternating smaller-frontier-first search."""
    return _get_kernel("alt", 0)(nbr, deg, (), src, dst)


def solve_dense_graph(
    g: DeviceGraph, src: int, dst: int, *, mode: str = "sync",
    unroll: int = 1, telemetry=None
) -> BFSResult:
    """Run the jitted search on an already-device-resident graph; timing
    covers the search only (reference parity: each version times only the
    hot loop, SURVEY.md §5 tracing). ``unroll`` runs that many rounds per
    while iteration (:func:`_unrolled`) — exact, any mode. ``telemetry``
    (opt-in) swaps in the level-stepped traced drive
    (:func:`_solve_dense_traced`), recording per-level frontier sizes,
    edges scanned and the push/pull routing onto the result's
    ``level_stats``; the default None runs the one-shot compiled program
    untouched."""
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    if telemetry:  # any falsy value (None/False/0) = fully off
        return _solve_dense_traced(g, src, dst, mode, telemetry)
    from bibfs_tpu.solvers.timing import force_scalar

    kern = _get_kernel(mode, kernel_cap(mode, g.n_pad), g.tier_meta,
                       _geom_of(g), unroll)
    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    t0 = time.perf_counter()
    out = kern(g.nbr, g.deg, g.aux, src_a, dst_a)
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    elapsed = time.perf_counter() - t0
    return _materialize(out, elapsed)


def _materialize(out, elapsed: float) -> BFSResult:
    best, meet, par_s, par_t, levels, edges = out
    best = int(best)
    if best >= int(INF32):
        return BFSResult(False, None, None, None, elapsed, int(levels), int(edges))
    path = _reconstruct(
        np.asarray(par_s, dtype=np.int64), np.asarray(par_t, dtype=np.int64), int(meet)
    )
    return BFSResult(True, best, path, int(meet), elapsed, int(levels), int(edges))


def time_search(
    g: DeviceGraph, src: int, dst: int, *, repeats: int = 30,
    mode: str = "sync", unroll: int = 1
) -> tuple[list[float], BFSResult]:
    """Forced-execution timing loop + one materializing solve (protocol and
    the tunneled-runtime laziness rationale in
    :mod:`bibfs_tpu.solvers.timing`). Returns ``(times_s, result)`` with
    ``result.time_s`` = median."""
    return _timed(g, src, dst, repeats, mode,
                  lambda: solve_dense_graph(g, src, dst, mode=mode,
                                            unroll=unroll),
                  unroll)


def time_search_only(
    g: DeviceGraph, src: int, dst: int, *, repeats: int = 30,
    mode: str = "sync", unroll: int = 1
) -> list[float]:
    """:func:`time_search` without the final result materialization —
    per-repeat execution is still FORCED via a one-scalar read (see
    :mod:`bibfs_tpu.solvers.timing`: on the tunneled backend,
    ``block_until_ready`` does not actually wait, so un-forced loops
    measure enqueue rate, not solves)."""
    times, _ = _timed(g, src, dst, repeats, mode, None, unroll)
    return times


def _timed(g, src, dst, repeats, mode, materialize, unroll: int = 1):
    from bibfs_tpu.solvers.timing import force_scalar, timed_repeats

    kern = _get_kernel(mode, kernel_cap(mode, g.n_pad), g.tier_meta,
                       _geom_of(g), unroll)
    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    return timed_repeats(
        lambda: kern(g.nbr, g.deg, g.aux, src_a, dst_a),
        materialize,
        repeats,
        force=force_scalar,
    )


def _batch_dispatch(g: DeviceGraph, pairs, mode: str):
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    if mode == "auto":
        # best eligible batch layout (minor8 > minor > vmapped sync) —
        # the measured preference order, solvers/batch_minor.py
        from bibfs_tpu.solvers.batch_minor import auto_batch_mode

        mode = auto_batch_mode(g, len(pairs))
    if mode in ("minor", "minor8"):
        # batch-MINOR layout ([n_pad, B] planes, contiguous-row expansion
        # gather — solvers/batch_minor.py; tiered layouts run per-tier
        # slab passes). "minor8" additionally drops ALL loop planes to
        # int8 (slot-coded parents, host-decoded in ``finish``; depth-
        # capped queries re-solved via the int32 kernel there too) and
        # stays plain-ELL
        from bibfs_tpu.solvers.batch_minor import batch_dispatch

        return batch_dispatch(g, pairs, dt8=(mode == "minor8"))
    kern = _get_batch_kernel(mode, kernel_cap(mode, g.n_pad), g.tier_meta,
                             _geom_of(g))
    srcs = jnp.asarray(pairs[:, 0], dtype=jnp.int32)
    dsts = jnp.asarray(pairs[:, 1], dtype=jnp.int32)
    dispatch = lambda: jax.block_until_ready(  # noqa: E731
        kern(g.nbr, g.deg, g.aux, srcs, dsts)
    )
    # third element: the untimed finish hook (identity for the vmapped
    # path; the minor8 path decodes slot-parents + refills there)
    return pairs, dispatch, lambda out: out


def _materialize_batch(out, num: int, elapsed: float) -> list[BFSResult]:
    # one device->host transfer per OUTPUT, not per (output, query) pair —
    # np.asarray inside the query loop would re-copy the whole [B, n_pad]
    # parent arrays B times
    outs = [np.asarray(o) for o in out]
    return [_materialize(tuple(o[i] for o in outs), elapsed) for i in range(num)]


def solve_batch_graph(
    g: DeviceGraph, pairs, *, mode: str = "sync"
) -> list[BFSResult]:
    """Solve many (src, dst) queries in ONE device program (vmapped search).

    Wall-clock is amortized: the batch runs as long as its hardest query,
    with every level's gathers/scatters batched across queries. Returns one
    :class:`BFSResult` per pair; each result's ``time_s`` is the WHOLE
    batch wall-clock (divide by ``len(pairs)`` for per-query throughput).
    """
    from bibfs_tpu.solvers.timing import force_scalar

    pairs, dispatch, finish = _batch_dispatch(g, pairs, mode)
    t0 = time.perf_counter()
    out = dispatch()
    force_scalar(out)  # execution is lazy until a value read; see timing.py
    elapsed = time.perf_counter() - t0
    return _materialize_batch(finish(out), pairs.shape[0], elapsed)


def time_batch_graph(
    g: DeviceGraph, pairs, *, repeats: int = 5, mode: str = "sync"
) -> tuple[list[float], list[BFSResult]]:
    """Batch solve under the shared timing protocol (warm-up excluded,
    forced execution per repeat, median stamped into every result's
    ``time_s``; see :mod:`bibfs_tpu.solvers.timing`). The LAST timed
    output is materialized directly — an extra whole-batch solve just to
    fetch a result would cost real seconds through the tunnel."""
    from bibfs_tpu.solvers.timing import timed_batch_repeats

    pairs, dispatch, finish = _batch_dispatch(g, pairs, mode)
    times, out = timed_batch_repeats(dispatch, repeats)
    return times, _materialize_batch(
        finish(out), pairs.shape[0], float(np.median(times))
    )


def time_batch_only(
    g: DeviceGraph, pairs, *, repeats: int = 10, mode: str = "sync"
) -> list[float]:
    """Forced-execution batch timing without result materialization.
    Returns per-repeat wall times for solving ALL pairs in one vmapped
    device program."""
    from bibfs_tpu.solvers.timing import force_scalar, timed_repeats

    _pairs, dispatch, _finish = _batch_dispatch(g, pairs, mode)
    return timed_repeats(dispatch, None, repeats, force=force_scalar)[0]


def solve_dense(
    n: int,
    edges: np.ndarray,
    src: int,
    dst: int,
    *,
    mode: str = "sync",
    layout: str = "ell",
    unroll: int = 1,
    telemetry=None,
) -> BFSResult:
    return solve_dense_graph(
        DeviceGraph.build(n, edges, layout=layout), src, dst, mode=mode,
        unroll=unroll, telemetry=telemetry,
    )


@register("dense")
def _dense_backend(n, edges, src, dst, mode="sync", layout="ell",
                   unroll=1, telemetry=None, **_):
    return solve_dense(n, edges, src, dst, mode=mode, layout=layout,
                       unroll=unroll, telemetry=telemetry)
