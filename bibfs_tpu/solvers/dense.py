"""Single-chip device-resident bidirectional BFS — the v3 replacement.

The reference v3 (v3/bibfs_cuda_only.cu:173-203) launches one CUDA kernel
per side per level, synchronizing and copying flag bytes back to the host
every iteration; v4 additionally round-trips the whole frontier+visited
arrays through host memory per level (v4/comp.cu:84-107, quirk Q5). Here the
ENTIRE search — both frontiers, visited sets, parents, distances, direction
choice, meet detection, and termination vote — is one ``jax.lax.while_loop``
inside one jitted XLA program: state never leaves HBM, and the host syncs
exactly once, at the end.

Algorithmic upgrades over the reference:
- smaller-frontier-first direction choice (v1/main-v1.cpp:51, v4
  mpi_bas.cpp:90-92 — absent in v3, which expands both sides every round)
- provably-correct termination: keep the best meet candidate and stop when
  ``level_s + level_t >= best`` (fixes quirks Q1/Q2)
- true hop counts and device-computed parent arrays for path reconstruction
  (v3 reports only found/not-found, v3/bibfs_cuda_only.cu:224; v2/v4
  re-run a serial BFS on the host, second_try.cpp:137-162)
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from bibfs_tpu.graph.csr import EllGraph, build_ell
from bibfs_tpu.ops.expand import expand_pull, frontier_count, frontier_degree_sum
from bibfs_tpu.solvers.api import BFSResult, register
from bibfs_tpu.solvers.serial import _reconstruct

# "infinite" distance sentinel; a plain int so importing this module never
# touches a JAX backend (device constants would initialize one eagerly)
INF32 = 1 << 30


@lru_cache(maxsize=4096)
def _device_scalar(v: int) -> jax.Array:
    """Device-resident int32 scalar, cached by value.

    Passing a *freshly* eager-created device scalar as a jit argument stalls
    the dispatch path on tunneled-TPU runtimes (measured ~100ms per fresh
    arg vs ~20us when the scalar buffer is reused), so solver entry points
    must route src/dst through this cache rather than calling
    ``jnp.int32(...)`` per solve.
    """
    return jnp.int32(v)


@dataclasses.dataclass
class DeviceGraph:
    """ELL adjacency resident in device HBM — the analog of v4's
    ``cudaInitGraph`` upload (v4/comp.cu:49-73), done once per graph."""

    n: int
    n_pad: int
    width: int
    num_edges: int
    nbr: jax.Array  # int32[n_pad, width]
    deg: jax.Array  # int32[n_pad]

    @classmethod
    def from_ell(cls, g: EllGraph, device=None) -> "DeviceGraph":
        if g.overflow.shape[0]:
            raise NotImplementedError(
                "EllGraph has width_cap overflow edges; the device solvers "
                "do not handle the hybrid ELL+COO layout yet — build the "
                "ELL without width_cap"
            )
        put = partial(jax.device_put, device=device) if device else jax.device_put
        return cls(
            n=g.n,
            n_pad=g.n_pad,
            width=g.width,
            num_edges=g.num_edges,
            nbr=put(g.nbr),
            deg=put(g.deg),
        )


def _init_state(n_pad, src, dst):
    zeros_b = jnp.zeros(n_pad, dtype=jnp.bool_)
    fs = zeros_b.at[src].set(True)
    ft = zeros_b.at[dst].set(True)
    return dict(
        vis_s=fs,
        fr_s=fs,
        par_s=jnp.full(n_pad, -1, jnp.int32),
        dist_s=jnp.where(fs, 0, INF32).astype(jnp.int32),
        vis_t=ft,
        fr_t=ft,
        par_t=jnp.full(n_pad, -1, jnp.int32),
        dist_t=jnp.where(ft, 0, INF32).astype(jnp.int32),
        lvl_s=jnp.int32(0),
        lvl_t=jnp.int32(0),
        best=jnp.where(src == dst, 0, INF32).astype(jnp.int32),
        meet=jnp.where(src == dst, src, -1).astype(jnp.int32),
        levels=jnp.int32(0),
        edges=jnp.int32(0),
    )


def _meet_vote(st):
    """Fused check_intersect (v3/bibfs_cuda_only.cu:45-62): best candidate
    distance + its meet vertex over the visited intersection. dist values of
    visited vertices are final in a level-synchronous BFS, so the min is
    exact."""
    sums = jnp.where(st["vis_s"] & st["vis_t"], st["dist_s"] + st["dist_t"], INF32)
    cur = jnp.min(sums)
    arg = jnp.argmin(sums).astype(jnp.int32)
    st["meet"] = jnp.where(cur < st["best"], arg, st["meet"])
    st["best"] = jnp.minimum(st["best"], cur)
    return st


def _outputs(out):
    return (
        out["best"],
        out["meet"],
        out["par_s"],
        out["par_t"],
        out["levels"],
        out["edges"],
    )


def _cond(st):
    # provably-correct stop: once lvl_s+lvl_t >= best no undiscovered vertex
    # can improve the meet (the midpoint of any shorter path would already
    # be visited by both sides) — fixes quirks Q1/Q2
    return (
        (st["lvl_s"] + st["lvl_t"] < st["best"])
        & jnp.any(st["fr_s"])
        & jnp.any(st["fr_t"])
    )


@jax.jit
def bibfs_dense(nbr, deg, src, dst):
    """Jittable bidirectional-BFS search, lock-step variant: BOTH sides
    expand every round (the v2/v3 schedule, second_try.cpp:68-105 /
    bibfs_cuda_only.cu:173-193 — but with the correct termination rule).

    Half the sequential rounds of the alternating variant for the same
    total work — on TPU the search is latency-bound (a round is one
    while_loop iteration), so this is the headline path.

    Returns ``(best, meet, parent_s, parent_t, levels, edges_scanned)`` —
    ``best >= INF32`` means no path.
    """
    n_pad = nbr.shape[0]
    init = _init_state(n_pad, src, dst)

    def body(st):
        scanned = frontier_degree_sum(st["fr_s"], deg) + frontier_degree_sum(
            st["fr_t"], deg
        )
        nf_s, pcand_s = expand_pull(st["fr_s"], st["vis_s"], nbr, deg)
        nf_t, pcand_t = expand_pull(st["fr_t"], st["vis_t"], nbr, deg)
        st = {
            **st,
            "fr_s": nf_s,
            "vis_s": st["vis_s"] | nf_s,
            "par_s": jnp.where(nf_s, pcand_s, st["par_s"]),
            "dist_s": jnp.where(nf_s, st["lvl_s"] + 1, st["dist_s"]),
            "fr_t": nf_t,
            "vis_t": st["vis_t"] | nf_t,
            "par_t": jnp.where(nf_t, pcand_t, st["par_t"]),
            "dist_t": jnp.where(nf_t, st["lvl_t"] + 1, st["dist_t"]),
            "lvl_s": st["lvl_s"] + 1,
            "lvl_t": st["lvl_t"] + 1,
            "edges": st["edges"] + scanned,
            "levels": st["levels"] + 2,
        }
        return _meet_vote(st)

    return _outputs(jax.lax.while_loop(_cond, body, init))


@jax.jit
def bibfs_dense_alt(nbr, deg, src, dst):
    """Alternating smaller-frontier-first variant (v1/main-v1.cpp:51, v4
    mpi_bas.cpp:90-92): one side per round, always the cheaper one — fewer
    total edge scans than lock-step at twice the sequential rounds. Prefer
    for work-bound (large-graph) searches; same return contract as
    :func:`bibfs_dense`.
    """
    n_pad = nbr.shape[0]
    init = _init_state(n_pad, src, dst)

    def body(st):
        cs = frontier_count(st["fr_s"])
        ct = frontier_count(st["fr_t"])

        def one_side(fr, vis, par, dist, lvl):
            nf, pcand = expand_pull(fr, vis, nbr, deg)
            par = jnp.where(nf, pcand, par)
            dist = jnp.where(nf, lvl + 1, dist)
            return nf, vis | nf, par, dist, lvl + 1

        def s_branch(st):
            scanned = frontier_degree_sum(st["fr_s"], deg)
            nf, vis, par, dist, lvl = one_side(
                st["fr_s"], st["vis_s"], st["par_s"], st["dist_s"], st["lvl_s"]
            )
            return {
                **st,
                "fr_s": nf,
                "vis_s": vis,
                "par_s": par,
                "dist_s": dist,
                "lvl_s": lvl,
                "edges": st["edges"] + scanned,
            }

        def t_branch(st):
            scanned = frontier_degree_sum(st["fr_t"], deg)
            nf, vis, par, dist, lvl = one_side(
                st["fr_t"], st["vis_t"], st["par_t"], st["dist_t"], st["lvl_t"]
            )
            return {
                **st,
                "fr_t": nf,
                "vis_t": vis,
                "par_t": par,
                "dist_t": dist,
                "lvl_t": lvl,
                "edges": st["edges"] + scanned,
            }

        st = jax.lax.cond(cs <= ct, s_branch, t_branch, st)
        st["levels"] = st["levels"] + 1
        return _meet_vote(st)

    return _outputs(jax.lax.while_loop(_cond, body, init))


_DENSE_KERNELS = {"sync": bibfs_dense, "alt": bibfs_dense_alt}


def solve_dense_graph(
    g: DeviceGraph, src: int, dst: int, *, mode: str = "sync"
) -> BFSResult:
    """Run the jitted search on an already-device-resident graph; timing
    covers the search only (reference parity: each version times only the
    hot loop, SURVEY.md §5 tracing)."""
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    kern = _DENSE_KERNELS[mode]
    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    t0 = time.perf_counter()
    out = jax.block_until_ready(kern(g.nbr, g.deg, src_a, dst_a))
    elapsed = time.perf_counter() - t0
    return _materialize(out, elapsed)


def _materialize(out, elapsed: float) -> BFSResult:
    best, meet, par_s, par_t, levels, edges = out
    best = int(best)
    if best >= int(INF32):
        return BFSResult(False, None, None, None, elapsed, int(levels), int(edges))
    path = _reconstruct(
        np.asarray(par_s, dtype=np.int64), np.asarray(par_t, dtype=np.int64), int(meet)
    )
    return BFSResult(True, best, path, int(meet), elapsed, int(levels), int(edges))


def time_search(
    g: DeviceGraph, src: int, dst: int, *, repeats: int = 30, mode: str = "sync"
) -> tuple[list[float], BFSResult]:
    """Zero-D2H timing loop + one materializing solve (protocol and
    rationale in :mod:`bibfs_tpu.solvers.timing`). Returns ``(times_s,
    result)`` with ``result.time_s`` = median."""
    from bibfs_tpu.solvers.timing import timed_repeats

    kern = _DENSE_KERNELS[mode]
    src_a = _device_scalar(src)
    dst_a = _device_scalar(dst)
    return timed_repeats(
        lambda: jax.block_until_ready(kern(g.nbr, g.deg, src_a, dst_a)),
        lambda: solve_dense_graph(g, src, dst, mode=mode),
        repeats,
    )


def solve_dense(
    n: int, edges: np.ndarray, src: int, dst: int, *, mode: str = "sync"
) -> BFSResult:
    g = DeviceGraph.from_ell(build_ell(n, edges))
    return solve_dense_graph(g, src, dst, mode=mode)


@register("dense")
def _dense_backend(n, edges, src, dst, mode="sync", **_):
    return solve_dense(n, edges, src, dst, mode=mode)
