"""ctypes bindings for the native C++ runtime (bibfs_native.cpp).

The ``native`` backend is the framework's v1-parity wall-clock baseline —
the reference's serial C++ solver (v1/main-v1.cpp) re-done as a library
call instead of a standalone main, with the corrected termination rule.
Also exposes the native graph loader/CSR builder used for 10M-node-scale
preprocessing.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os

import numpy as np

from bibfs_tpu.native.build import ensure_built
from bibfs_tpu.solvers.api import BFSResult, register

_ERR = {
    -1: "cannot open file",
    -2: "truncated or malformed file",
    -3: "edge endpoint out of range",
    -4: "bad argument",
    -5: "buffer too small",
    -6: "allocation failure",
}


def _lib() -> ctypes.CDLL:
    global _CACHED
    try:
        return _CACHED
    except NameError:
        pass
    lib = ctypes.CDLL(ensure_built())
    i8, i32, i64, u32, f64 = (
        ctypes.c_int8,
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_uint32,
        ctypes.c_double,
    )
    p = ctypes.POINTER
    lib.bibfs_read_header.argtypes = [ctypes.c_char_p, p(u32), p(u32)]
    lib.bibfs_read_edges.argtypes = [ctypes.c_char_p, u32, u32, p(u32)]
    lib.bibfs_build_csr.argtypes = [u32, ctypes.c_uint64, p(u32), p(i64), p(i32), p(i64)]
    lib.bibfs_solve.argtypes = [
        u32, p(i64), p(i32), u32, u32,
        p(i32), p(i32), i32, p(i32), p(f64), p(i64), p(i32),
    ]
    lib.bibfs_solve_s.argtypes = [
        u32, p(i64), p(i32), ctypes.c_void_p, u32, u32,
        p(i32), p(i32), i32, p(i32), p(f64), p(i64), p(i32),
    ]
    lib.bibfs_solve_batch.argtypes = [
        u32, p(i64), p(i32), i32, p(u32), p(u32), i32,
        p(i32), p(i32), i32, p(i32), p(f64), p(i64), p(i32),
    ]
    lib.bibfs_solve_levels.argtypes = [
        u32, p(i64), p(i32), ctypes.c_void_p, u32, u32,
        p(i32), p(i32), i32, p(i32), p(f64), p(i64), p(i32),
        i32, p(ctypes.c_uint8), p(i32), p(i64), p(i32),
    ]
    lib.bibfs_scratch_create.argtypes = [u32]
    lib.bibfs_scratch_create.restype = ctypes.c_void_p
    lib.bibfs_scratch_free.argtypes = [ctypes.c_void_p]
    lib.bibfs_scratch_free.restype = None
    for fn in (lib.bibfs_read_header, lib.bibfs_read_edges,
               lib.bibfs_build_csr, lib.bibfs_solve, lib.bibfs_solve_s,
               lib.bibfs_solve_batch, lib.bibfs_solve_levels):
        fn.restype = i32
    _CACHED = lib
    return lib


def _check(rc: int, what: str):
    if rc != 0:
        raise RuntimeError(f"{what}: {_ERR.get(rc, f'error {rc}')}")


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def read_graph_native(path: str) -> tuple[int, np.ndarray]:
    """Native binary loader — same contract as graph.io.read_graph_bin."""
    lib = _lib()
    n = ctypes.c_uint32()
    m = ctypes.c_uint32()
    _check(lib.bibfs_read_header(path.encode(), ctypes.byref(n), ctypes.byref(m)),
           path)
    # validate the untrusted header against the actual file size before
    # allocating m*8 bytes (a corrupt m=0xFFFFFFFF would try ~32 GB)
    need = 8 + 8 * int(m.value)
    have = os.path.getsize(path)
    if have < need:
        raise RuntimeError(f"{path}: {_ERR[-2]} (m={m.value} needs {need} B, file is {have} B)")
    edges = np.empty((m.value, 2), dtype=np.uint32)
    _check(
        lib.bibfs_read_edges(path.encode(), n.value, m.value,
                             _ptr(edges, ctypes.c_uint32)),
        path,
    )
    return int(n.value), edges.astype(np.int64)


@dataclasses.dataclass
class NativeGraph:
    n: int
    row_ptr: np.ndarray  # int64[n+1]
    col_ind: np.ndarray  # int32[nnz]

    def __post_init__(self):
        # epoch-stamped solve scratch: repeated solves over this graph pay
        # O(vertices touched) setup instead of refilling four n-sized
        # arrays (the dominant cost of short searches on big graphs).
        # Owned by this object; freed by the GC finalizer. NOT thread-safe:
        # one in-flight solve per NativeGraph.
        import weakref

        lib = _lib()
        self._scratch = lib.bibfs_scratch_create(self.n)
        if not self._scratch:
            raise MemoryError(f"scratch allocation failed for n={self.n}")
        self._path_buf = np.empty(self.n + 1, dtype=np.int32)
        weakref.finalize(self, lib.bibfs_scratch_free, self._scratch)

    @classmethod
    def build(cls, n: int, edges: np.ndarray) -> "NativeGraph":
        lib = _lib()
        edges_u = np.ascontiguousarray(
            np.asarray(edges).reshape(-1, 2), dtype=np.uint32
        )
        m = edges_u.shape[0]
        row_ptr = np.zeros(n + 1, dtype=np.int64)
        col_ind = np.empty(max(2 * m, 1), dtype=np.int32)
        nnz = ctypes.c_int64()
        _check(
            lib.bibfs_build_csr(
                n, m, _ptr(edges_u, ctypes.c_uint32),
                _ptr(row_ptr, ctypes.c_int64), _ptr(col_ind, ctypes.c_int32),
                ctypes.byref(nnz),
            ),
            "build_csr",
        )
        return cls(n=n, row_ptr=row_ptr, col_ind=col_ind[: nnz.value].copy())


def solve_native_graph(
    g: NativeGraph, src: int, dst: int, *, telemetry=None
) -> BFSResult:
    """Solve on a prebuilt :class:`NativeGraph`, reusing its epoch-stamped
    scratch (per-solve setup is O(vertices touched), not O(n)).

    ``telemetry`` (opt-in; a
    :class:`bibfs_tpu.obs.telemetry.LevelTelemetry` or True) routes the
    solve through the ``bibfs_solve_levels`` export, which additionally
    fills per-level side/frontier/edge arrays and the meet level — the
    search itself is the same ``solve_impl`` either way, so hops/paths
    are identical. Default None takes the exact pre-telemetry ABI call.

    NOT thread-safe: the scratch and path buffer belong to ``g``, so run
    at most one solve per NativeGraph at a time (concurrent threads must
    use separate NativeGraph instances or the stateless
    :func:`solve_native`)."""
    if not (0 <= src < g.n and 0 <= dst < g.n):
        raise ValueError(f"src/dst out of range for n={g.n}")
    lib = _lib()
    hops = ctypes.c_int32()
    path_buf = g._path_buf
    path_len = ctypes.c_int32()
    secs = ctypes.c_double()
    scanned = ctypes.c_int64()
    levels = ctypes.c_int32()
    common = (
        g.n, _ptr(g.row_ptr, ctypes.c_int64), _ptr(g.col_ind, ctypes.c_int32),
        g._scratch,
        src, dst, ctypes.byref(hops), _ptr(path_buf, ctypes.c_int32),
        path_buf.size, ctypes.byref(path_len), ctypes.byref(secs),
        ctypes.byref(scanned), ctypes.byref(levels),
    )
    tel = None
    if telemetry:  # any falsy value (None/False/0) = fully off
        from bibfs_tpu.obs.telemetry import coerce

        tel = coerce(telemetry)
        if tel is not None and tel.n != 0:
            # re-stamp per solve (see solve_serial_csr; n=0 opts out)
            tel.n = int(g.n)
    if tel is None:
        _check(lib.bibfs_solve_s(*common), "solve")
    else:
        # a bidirectional search runs at most best+1 <= n rounds, so
        # n + 1 level slots can never truncate
        cap = g.n + 1
        lvl_side = np.zeros(cap, dtype=np.uint8)
        lvl_frontier = np.zeros(cap, dtype=np.int32)
        lvl_edges = np.zeros(cap, dtype=np.int64)
        meet_level = ctypes.c_int32()
        _check(
            lib.bibfs_solve_levels(
                *common, cap, _ptr(lvl_side, ctypes.c_uint8),
                _ptr(lvl_frontier, ctypes.c_int32),
                _ptr(lvl_edges, ctypes.c_int64), ctypes.byref(meet_level),
            ),
            "solve_levels",
        )
        for i in range(min(levels.value, cap)):
            tel.record_level(
                i + 1, "s" if lvl_side[i] == 0 else "t", "push",
                int(lvl_frontier[i]), int(lvl_edges[i]),
            )
        if meet_level.value >= 0:
            tel.note_meet(meet_level.value)
    if hops.value < 0:
        res = BFSResult(
            False, None, None, None, secs.value, levels.value, int(scanned.value)
        )
    else:
        path = path_buf[: path_len.value].tolist() if path_len.value else None
        meet = None  # meet vertex not exposed over the ABI; path carries it
        res = BFSResult(
            True, hops.value, path, meet, secs.value, levels.value,
            int(scanned.value),
        )
    if tel is not None:
        res.level_stats = tel.as_dict()
    return res


def solve_native(
    n: int, edges: np.ndarray, src: int, dst: int, *, telemetry=None
) -> BFSResult:
    return solve_native_graph(NativeGraph.build(n, edges), src, dst,
                              telemetry=telemetry)


# default per-query path capacity in the threaded batch, bounded by the
# graph size (a path can never exceed n+1 vertices, so small graphs get
# FULL paths, matching the single solve). High-diameter graphs past the
# default cap report hops-only unless the caller raises ``path_cap``.
_BATCH_PATH_CAP = 512


def _batch_path_cap(g: NativeGraph, path_cap: int | None) -> int:
    if path_cap is None:
        return min(g.n + 1, _BATCH_PATH_CAP)
    if path_cap < 1:
        raise ValueError(f"path_cap must be >= 1, got {path_cap}")
    return min(g.n + 1, path_cap)


def solve_batch_native_graph(
    g: NativeGraph, pairs, *, threads: int | None = None,
    path_cap: int | None = None,
) -> list[BFSResult]:
    """Solve many (src, dst) queries on one graph via the THREADED native
    batch (`bibfs_solve_batch`): queries stripe over worker threads, each
    with its own epoch-stamped scratch, sharing the read-only CSR — the
    host analog of the dense backend's vmapped batch. Each returned
    result's ``time_s`` is the WHOLE batch wall-clock, matching
    :func:`bibfs_tpu.solvers.dense.solve_batch_graph`'s contract.
    ``path_cap`` raises the per-query path buffer for high-diameter
    graphs (default ``min(n+1, 512)``); deeper paths report hops-only."""
    return time_batch_native(
        g, pairs, repeats=1, threads=threads, path_cap=path_cap
    )[1]


def _run_batch_native(
    g: NativeGraph, pairs: np.ndarray, threads: int, path_cap: int
):
    lib = _lib()
    b = pairs.shape[0]
    srcs = np.ascontiguousarray(pairs[:, 0], dtype=np.uint32)
    dsts = np.ascontiguousarray(pairs[:, 1], dtype=np.uint32)
    hops = np.full(b, -1, dtype=np.int32)
    path_buf = np.empty((b, path_cap), dtype=np.int32)
    path_len = np.zeros(b, dtype=np.int32)
    secs = ctypes.c_double()
    edges = np.zeros(b, dtype=np.int64)
    levels = np.zeros(b, dtype=np.int32)
    _check(
        lib.bibfs_solve_batch(
            g.n, _ptr(g.row_ptr, ctypes.c_int64),
            _ptr(g.col_ind, ctypes.c_int32), b,
            _ptr(srcs, ctypes.c_uint32), _ptr(dsts, ctypes.c_uint32),
            threads, _ptr(hops, ctypes.c_int32),
            _ptr(path_buf, ctypes.c_int32), path_cap,
            _ptr(path_len, ctypes.c_int32), ctypes.byref(secs),
            _ptr(edges, ctypes.c_int64), _ptr(levels, ctypes.c_int32),
        ),
        "solve_batch",
    )
    results = []
    for i in range(b):
        if hops[i] < 0:
            results.append(BFSResult(
                False, None, None, None, secs.value, int(levels[i]),
                int(edges[i]),
            ))
        else:
            path = path_buf[i, : path_len[i]].tolist() if path_len[i] else None
            results.append(BFSResult(
                True, int(hops[i]), path, None, secs.value, int(levels[i]),
                int(edges[i]),
            ))
    return float(secs.value), results


def time_batch_native(
    g: NativeGraph, pairs, *, repeats: int = 5, threads: int | None = None,
    path_cap: int | None = None,
) -> tuple[list[float], list[BFSResult]]:
    """Batch timing protocol for the native backend: ``repeats`` whole-
    batch passes through the threaded C batch, median stamped into every
    result's ``time_s``. ``threads`` defaults to the host's core count
    (capped at 16); ``path_cap`` as in :func:`solve_batch_native_graph`."""
    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    if threads is None:
        threads = min(os.cpu_count() or 1, 16)
    if threads < 1:
        raise ValueError(f"threads must be >= 1, got {threads}")
    cap = _batch_path_cap(g, path_cap)
    pairs = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
    if pairs.size and not ((0 <= pairs).all() and (pairs < g.n).all()):
        raise ValueError(f"src/dst out of range for n={g.n}")
    times = []
    results: list[BFSResult] = []
    for _ in range(repeats):
        wall, results = _run_batch_native(g, pairs, threads, cap)
        times.append(wall)
    med = float(np.median(times))
    return times, [dataclasses.replace(r, time_s=med) for r in results]


# Load (building if needed) at import time so a missing C++ toolchain
# surfaces as an OSError HERE — where solve()'s lazy-import catch turns it
# into "backend 'native' unavailable" — instead of escaping from the first
# solve call as a raw traceback.
_lib()


@register("native")
def _native_backend(n, edges, src, dst, telemetry=None, **_):
    return solve_native(n, edges, src, dst, telemetry=telemetry)
