"""Serial host bidirectional BFS — the correctness oracle and wall-clock bar.

Re-design of the reference v1 solver (v1/main-v1.cpp:50-81): level-synchronous
bidirectional BFS with smaller-frontier-first direction choice (main-v1.cpp:51),
per-side parent arrays (42) and full path reconstruction (86-97). Two changes
versus the reference:

1. The inner loop is NumPy-vectorized over the whole frontier (CSR row
   gather) instead of a per-vertex C++ loop — this is the "serial" baseline
   done idiomatically for an array machine, and it is what the benchmark's
   v1 row compares against on this hardware.
2. Termination uses the provably-correct rule — keep the best meet candidate
   and stop once ``level_s + level_t >= best`` — instead of stopping at the
   first meet (quirk Q2: the article linked at v1/main-v1.cpp:2 is exactly
   about naive first-meet stopping being wrong in general).
"""

from __future__ import annotations

import time

import numpy as np

from bibfs_tpu.graph.csr import build_csr
from bibfs_tpu.solvers.api import BFSResult, register

_INF = np.iinfo(np.int64).max // 4


def _expand(
    frontier: np.ndarray,
    row_ptr: np.ndarray,
    col_ind: np.ndarray,
    dist_self: np.ndarray,
    parent_self: np.ndarray,
    level_next: int,
) -> tuple[np.ndarray, int]:
    """One BFS level: visit all unvisited neighbors of ``frontier``.

    Returns (new frontier, directed edges scanned). Parent choice is
    deterministic: the first (lowest CSR position) discovering edge wins —
    where CUDA used first-atomic-wins nondeterminism (v3/bibfs_cuda_only.cu:36).
    """
    starts = row_ptr[frontier]
    counts = row_ptr[frontier + 1] - starts
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64), 0
    offs = np.cumsum(counts) - counts
    flat = np.arange(total, dtype=np.int64)
    src_pos = np.repeat(np.arange(frontier.size), counts)
    gather_idx = flat - offs[src_pos] + starts[src_pos]
    neigh = col_ind[gather_idx]
    par = frontier[src_pos]
    new_mask = dist_self[neigh] == _INF
    neigh, par = neigh[new_mask], par[new_mask]
    uniq, first = np.unique(neigh, return_index=True)
    dist_self[uniq] = level_next
    parent_self[uniq] = par[first]
    return uniq, total


def solve_serial(
    n: int, edges: np.ndarray, src: int, dst: int, *, telemetry=None
) -> BFSResult:
    row_ptr, col_ind = build_csr(n, edges)
    return solve_serial_csr(n, row_ptr, col_ind, src, dst,
                            telemetry=telemetry)


def solve_serial_csr(
    n: int, row_ptr: np.ndarray, col_ind: np.ndarray, src: int, dst: int,
    *, telemetry=None, cutoff: int | None = None,
) -> BFSResult:
    """``telemetry`` (opt-in, default None = exact pre-telemetry code
    path): a :class:`bibfs_tpu.obs.telemetry.LevelTelemetry` (or True)
    recording per-level frontier/edge stats onto the result's
    ``level_stats`` — serial expansion is frontier-driven, so every
    recorded direction is "push".

    ``cutoff`` is a KNOWN upper bound on the true distance (the
    distance-oracle's UB): it seeds the meet bound at ``cutoff + 1``,
    so the provably-correct termination rule (``level_s + level_t >=
    best``) stops expanding past it instead of exploring to the
    frontier's natural death. Exact by the same invariant the unseeded
    rule rests on — any path of length ``d <= cutoff`` has a vertex
    within ``level_s`` of the source and ``level_t`` of the target once
    ``level_s + level_t >= d``, so the true distance is recorded as a
    meet candidate before the seeded bound can trigger. A WRONG (too
    small) cutoff would make a reachable pair report unreachable;
    callers must only pass a proven bound."""
    if not (0 <= src < n and 0 <= dst < n):
        raise ValueError(f"src/dst out of range for n={n}")
    if telemetry is not None:
        from bibfs_tpu.obs.telemetry import coerce

        telemetry = coerce(telemetry)
        if telemetry is not None and telemetry.n != 0:
            # re-stamp per solve: a collector reused across graphs
            # must record THIS graph's fractions (n=0 opts out)
            telemetry.n = int(n)
    t0 = time.perf_counter()
    if src == dst:
        res = BFSResult(True, 0, [src], src, time.perf_counter() - t0, 0, 0)
        if telemetry is not None:
            res.level_stats = telemetry.as_dict()
        return res

    dist_s = np.full(n, _INF, dtype=np.int64)
    dist_t = np.full(n, _INF, dtype=np.int64)
    parent_s = np.full(n, -1, dtype=np.int64)
    parent_t = np.full(n, -1, dtype=np.int64)
    dist_s[src] = 0
    dist_t[dst] = 0
    frontier_s = np.array([src], dtype=np.int64)
    frontier_t = np.array([dst], dtype=np.int64)
    level_s = level_t = 0
    best = _INF if cutoff is None else min(_INF, int(cutoff) + 1)
    meet = -1
    levels = 0
    edges_scanned = 0

    while frontier_s.size and frontier_t.size and level_s + level_t < best:
        if frontier_s.size <= frontier_t.size:  # smaller-frontier-first
            level_s += 1
            frontier_s, scanned = _expand(
                frontier_s, row_ptr, col_ind, dist_s, parent_s, level_s
            )
            newly = frontier_s
        else:
            level_t += 1
            frontier_t, scanned = _expand(
                frontier_t, row_ptr, col_ind, dist_t, parent_t, level_t
            )
            newly = frontier_t
        levels += 1
        edges_scanned += scanned
        if telemetry is not None:
            telemetry.record_level(
                levels, "s" if newly is frontier_s else "t", "push",
                newly.size, scanned,
            )
        if newly.size:
            other = dist_t if newly is frontier_s else dist_s
            mine = dist_s if newly is frontier_s else dist_t
            hit = newly[other[newly] != _INF]
            if hit.size:
                sums = mine[hit] + other[hit]
                k = int(np.argmin(sums))
                if int(sums[k]) < best:
                    best = int(sums[k])
                    meet = int(hit[k])
                    if telemetry is not None:
                        telemetry.note_meet(levels, meet)
    elapsed = time.perf_counter() - t0

    if meet < 0:  # no meet recorded (best may hold the cutoff seed)
        res = BFSResult(False, None, None, None, elapsed, levels, edges_scanned)
    else:
        path = _reconstruct(parent_s, parent_t, meet)
        res = BFSResult(True, best, path, meet, elapsed, levels, edges_scanned)
    if telemetry is not None:
        res.level_stats = telemetry.as_dict()
    return res


def _reconstruct(
    parent_s: np.ndarray, parent_t: np.ndarray, meet: int
) -> list[int]:
    """Walk parents from the meet vertex to both endpoints (v1/main-v1.cpp:86-97)."""
    left = [meet]
    while parent_s[left[-1]] != -1:
        left.append(int(parent_s[left[-1]]))
    right = []
    v = meet
    while parent_t[v] != -1:
        v = int(parent_t[v])
        right.append(v)
    return list(reversed(left)) + right


@register("serial")
def _serial_backend(n, edges, src, dst, telemetry=None, **_):
    return solve_serial(n, edges, src, dst, telemetry=telemetry)
