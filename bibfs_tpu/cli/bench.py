"""Benchmark harness — reference ``benchmark_test.sh`` parity, plus fixes.

The reference harness (benchmark_test.sh:30-76) builds each of the four
solver binaries, runs them on the four suite graphs, awk-scrapes a time
line into ``benchmark_results.csv`` and renders a boxed
``benchmark_table.txt``. This harness runs the framework's backends as
functions (no scraping), with the reference's known defects fixed:

- consistent units — always seconds (quirk Q3: the v3 rows in the
  reference CSV are milliseconds mislabeled as seconds);
- a TEPS column (BASELINE.json metric; the reference never reports TEPS);
- hop counts cross-checked against the ground-truth JSON per run (the
  reference relied on eyeballing, and v2's printed lengths were wrong, Q1);
- search-only timing with jit warm-up excluded, matching how every
  reference version brackets only its hot loop (SURVEY.md §5 tracing).

CSV schema: ``version,graph,time_sec,teps,hops,ok``.
"""

from __future__ import annotations

import argparse
import csv
import os
import sys
import time

import numpy as np

from bibfs_tpu.graph.io import ground_truth_path, read_graph_bin, read_ground_truth


def _run_backend(
    backend: str, n, edges, src, dst, repeats: int, num_devices=None,
    mode="sync", layout="ell",
):
    """Returns (median_time_s, result) via the shared timing protocol
    (graph build + warm-up excluded, zero-D2H repeat loop; see
    bibfs_tpu.solvers.timing). ``result.time_s`` equals the returned time."""
    from bibfs_tpu.solvers.timing import time_backend

    _times, res = time_backend(
        backend, n, edges, src, dst,
        repeats=repeats, num_devices=num_devices, mode=mode, layout=layout,
    )
    return res.time_s, res


def available_backends() -> list[str]:
    out = ["serial"]
    try:
        import bibfs_tpu.solvers.native  # noqa: F401

        out.append("native")
    except (ImportError, OSError):
        pass
    try:
        import jax  # noqa: F401

        out += ["dense", "sharded", "sharded2d"]
    except ModuleNotFoundError:
        pass
    return out


def _batch_oracle(n, edges, pairs_file):
    """Load the pairs file and solve every pair with the serial oracle
    ONCE — shared across however many backends get a batch row (the
    oracle pass dominates on big graphs and must not repeat per backend)."""
    from bibfs_tpu.solvers.serial import solve_serial

    pairs = np.loadtxt(pairs_file, dtype=np.int64, ndmin=2)
    if pairs.shape[1] != 2:
        raise ValueError(f"{pairs_file} must have two columns (src dst)")
    wants = [solve_serial(n, edges, int(s), int(d)) for s, d in pairs]
    return pairs, wants


def _batch_row(
    label, n, edges, pairs, wants, repeats, mode, layout, backend="dense",
    num_devices=None,
):
    """One amortized-throughput row: all (src, dst) pairs solved as ONE
    vmapped device program (dense/sharded backends) or a scratch-reusing
    host loop (native backend), validated per pair against the precomputed
    oracle results. time_sec is the PER-QUERY amortized wall-clock."""
    if backend == "native":
        from bibfs_tpu.solvers.native import NativeGraph, time_batch_native

        ng = NativeGraph.build(n, edges)
        times, results = time_batch_native(ng, pairs, repeats=repeats)
    elif backend == "sharded":
        from bibfs_tpu.parallel.mesh import make_1d_mesh
        from bibfs_tpu.solvers.sharded import ShardedGraph, time_batch_sharded

        sg = ShardedGraph.build(
            n, edges, make_1d_mesh(num_devices), layout=layout
        )
        times, results = time_batch_sharded(
            sg, pairs, repeats=repeats, mode=mode
        )
    elif backend == "sharded2d":
        from bibfs_tpu.solvers.sharded2d import (
            Sharded2DGraph,
            time_batch_sharded2d,
        )

        g2 = Sharded2DGraph.build(n, edges, num_devices=num_devices)
        times, results = time_batch_sharded2d(
            g2, pairs, repeats=repeats, mode=mode
        )
    else:
        from bibfs_tpu.solvers.dense import DeviceGraph, time_batch_graph

        g = DeviceGraph.build(n, edges, layout=layout)
        times, results = time_batch_graph(g, pairs, repeats=repeats, mode=mode)
    batch_s = float(np.median(times))
    ok = True
    hops_total = 0
    edges_scanned = 0
    for want, res in zip(wants, results):
        ok = ok and (res.found == want.found) and (res.hops == want.hops)
        hops_total += res.hops or 0
        edges_scanned += res.edges_scanned
    per_query = batch_s / max(len(results), 1)
    return dict(
        version=f"{backend}-batch{len(results)}",
        graph=label,
        time_sec=per_query,
        teps=edges_scanned / batch_s if batch_s > 0 else 0.0,
        hops=hops_total,
        ok=ok,
    )


def _serve_row(label, n, edges, pairs, wants, repeats, pipelined=False):
    """One serving-engine throughput row: all pairs served through a
    fresh :class:`bibfs_tpu.serve.QueryEngine` (or, with ``pipelined``,
    a :class:`bibfs_tpu.serve.PipelinedQueryEngine` — background
    deadline flusher, dispatch/finish overlap) per repeat (so every
    repeat's distance cache starts cold and the row measures solving,
    not memoization; compiled executables persist process-wide, and the
    first, discarded run carries compile/warm-up as usual). time_sec is
    the per-query amortized wall-clock of the median repeat."""
    from bibfs_tpu.serve import PipelinedQueryEngine, QueryEngine

    times = []
    results = stats = None
    for _ in range(max(repeats, 1) + 1):
        eng = (
            PipelinedQueryEngine(n, edges) if pipelined
            else QueryEngine(n, edges)
        )
        if not eng._use_device():
            # host route: the solver build (native CSR / oracle CSR) is
            # per-engine setup, not serving — keep it outside the timed
            # window like every other row's graph build
            eng._get_host_solver()
        t0 = time.time()
        results = eng.query_many(pairs)
        times.append(time.time() - t0)
        stats = eng.stats()
        eng.close()
    times = times[1:]  # warm-up run (device compile) excluded
    batch_s = float(np.median(times))
    ok = True
    hops_total = 0
    edges_scanned = 0
    for want, res in zip(wants, results):
        ok = ok and (res.found == want.found) and (res.hops == want.hops)
        hops_total += res.hops or 0
        edges_scanned += res.edges_scanned
    per_query = batch_s / max(len(results), 1)
    route = "device" if stats["device_batches_enabled"] else (
        stats["host_backend"] or "host"
    )
    name = "serve-pipe" if pipelined else "serve"
    return dict(
        version=f"{name}-batch{len(results)}",
        graph=label,
        time_sec=per_query,
        teps=edges_scanned / batch_s if batch_s > 0 else 0.0,
        hops=hops_total,
        ok=ok,
        config=f"{name}/{route}",
    )


def _row_provenance(backend: str, mode: str, layout: str) -> tuple[str, str]:
    """(platform, config) stamps for one row: a reader must be able to
    tell a CPU-substrate row from a real device row — and which schedule
    produced it — without opening any JSON (VERDICT r4 weak #6)."""
    if backend in ("serial", "native") or backend.startswith("native"):
        return "host", "-"
    try:
        import jax

        return jax.default_backend(), f"{mode}/{layout}"
    except Exception:
        return "?", f"{mode}/{layout}"


def run_bench(
    graphs: list[str],
    backends: list[str],
    *,
    repeats: int = 5,
    csv_path: str = "benchmark_results.csv",
    table_path: str = "benchmark_table.txt",
    num_devices=None,
    mode: str = "sync",
    layout: str = "ell",
    pairs_file: str | None = None,
    serve: bool = False,
) -> list[dict]:
    rows = []
    for gpath in graphs:
        n, edges = read_graph_bin(gpath)
        src, dst = 0, n - 1
        expected = None
        gt_path = ground_truth_path(gpath)
        if os.path.exists(gt_path):
            try:
                gt = read_ground_truth(gt_path)
                src, dst = int(gt["source"]), int(gt["target"])
                expected = gt["hop_count"]
            except (ValueError, KeyError, TypeError) as e:
                # a corrupt sidecar must not take down the whole sweep;
                # fall back to the src=0/dst=n-1 convention, ungated
                print(
                    f"  warning: ignoring malformed ground truth "
                    f"{gt_path}: {e}",
                    file=sys.stderr,
                )
                src, dst, expected = 0, n - 1, None
        label = os.path.splitext(os.path.basename(gpath))[0]
        for backend in backends:
            t0 = time.time()
            try:
                secs, res = _run_backend(
                    backend, n, edges, src, dst, repeats, num_devices,
                    mode, layout,
                )
            except Exception as e:  # keep the sweep alive, record the failure
                print(f"  {backend} on {label}: FAILED ({e})", file=sys.stderr)
                plat, cfg = _row_provenance(backend, mode, layout)
                rows.append(
                    dict(version=backend, graph=label, time_sec=None,
                         teps=None, hops=None, ok=False,
                         platform=plat, config=cfg)
                )
                continue
            ok = expected is None or res.hops == expected
            plat, cfg = _row_provenance(backend, mode, layout)
            rows.append(
                dict(
                    version=backend,
                    graph=label,
                    time_sec=secs,
                    teps=res.edges_scanned / secs if secs > 0 else 0.0,
                    hops=res.hops,
                    ok=ok,
                    platform=plat,
                    config=cfg,
                )
            )
            print(
                f"  {backend:8s} {label:6s} {secs:.6e}s  "
                f"teps={rows[-1]['teps']:.3e} hops={res.hops} "
                f"{'OK' if ok else 'MISMATCH vs gt=' + str(expected)} "
                f"(total {time.time() - t0:.1f}s)"
            )
        batch_oracle = None
        for batch_backend in ("dense", "native", "sharded", "sharded2d"):
            if pairs_file is None or batch_backend not in backends:
                continue
            if batch_backend == "sharded" and mode.startswith("pallas"):
                continue  # no pallas path under shard_map
            if batch_backend == "sharded2d" and mode not in ("sync", "alt"):
                continue  # the 2D partition is pull-only sync/alt
            try:
                if batch_oracle is None:
                    batch_oracle = _batch_oracle(n, edges, pairs_file)
                row = _batch_row(
                    label, n, edges, *batch_oracle, repeats, mode,
                    layout, backend=batch_backend, num_devices=num_devices,
                )
                plat, cfg = _row_provenance(batch_backend, mode, layout)
                row.setdefault("platform", plat)
                row.setdefault("config", cfg)
                rows.append(row)
                print(
                    f"  {row['version']:8s} {label:6s} {row['time_sec']:.6e}"
                    f"s/query  teps={row['teps']:.3e} "
                    f"{'OK' if row['ok'] else 'MISMATCH vs oracle'}"
                )
            except Exception as e:
                print(
                    f"  {batch_backend} batch on {label}: FAILED ({e})",
                    file=sys.stderr,
                )
                plat, cfg = _row_provenance(batch_backend, mode, layout)
                rows.append(
                    dict(version=f"{batch_backend}-batch", graph=label,
                         time_sec=None, teps=None, hops=None, ok=False,
                         platform=plat, config=cfg)
                )
        if pairs_file is not None and serve:
            # amortized serving-engine throughput (adaptive micro-batch
            # + caches; bibfs_tpu/serve) against the same oracle —
            # one row per engine flavor: synchronous and pipelined
            for pipelined in (False, True):
                name = "serve-pipe" if pipelined else "serve"
                try:
                    if batch_oracle is None:
                        batch_oracle = _batch_oracle(n, edges, pairs_file)
                    row = _serve_row(label, n, edges, *batch_oracle,
                                     repeats, pipelined=pipelined)
                    plat, _cfg = _row_provenance("dense", name, "ell")
                    row.setdefault("platform", plat)
                    rows.append(row)
                    print(
                        f"  {row['version']:8s} {label:6s} "
                        f"{row['time_sec']:.6e}s/query  "
                        f"teps={row['teps']:.3e} "
                        f"{'OK' if row['ok'] else 'MISMATCH vs oracle'}"
                    )
                except Exception as e:
                    print(f"  {name} engine on {label}: FAILED ({e})",
                          file=sys.stderr)
                    rows.append(
                        dict(version=f"{name}-batch", graph=label,
                             time_sec=None, teps=None, hops=None, ok=False,
                             platform="?", config=name)
                    )
    _write_csv(rows, csv_path)
    _write_table(rows, table_path)
    return rows


def _write_csv(rows, path):
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(
            f, fieldnames=["version", "graph", "time_sec", "teps", "hops",
                           "ok", "platform", "config"]
        )
        w.writeheader()
        for r in rows:
            w.writerow(r)


def _write_table(rows, path):
    """Boxed summary table (the reference's benchmark_table.txt:1-21 look)."""
    headers = ["version", "graph", "time_sec", "TEPS", "hops", "ok",
               "platform", "config"]
    table = [
        [
            r["version"],
            r["graph"],
            "-" if r["time_sec"] is None else f"{r['time_sec']:.6e}",
            "-" if not r["teps"] else f"{r['teps']:.3e}",
            str(r["hops"]),
            "yes" if r["ok"] else "NO",
            str(r.get("platform", "?")),
            str(r.get("config", "-")),
        ]
        for r in rows
    ]
    widths = [
        max(len(h), *(len(row[i]) for row in table)) if table else len(h)
        for i, h in enumerate(headers)
    ]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = [sep, "|" + "|".join(f" {h:<{w}} " for h, w in zip(headers, widths)) + "|", sep]
    for row in table:
        lines.append("|" + "|".join(f" {c:<{w}} " for c, w in zip(row, widths)) + "|")
    lines.append(sep)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


def main(argv=None):
    ap = argparse.ArgumentParser(description="Run the benchmark sweep")
    ap.add_argument("graphs", nargs="+", help=".bin graph files")
    ap.add_argument(
        "--backends",
        default=None,
        help="comma list (default: all available)",
    )
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--devices", type=int, default=None)
    ap.add_argument(
        "--mode",
        default="sync",
        choices=["sync", "alt", "beamer", "beamer_alt", "pallas",
                 "pallas_alt", "fused", "fused_alt", "sync_unfused"],
        help="device-kernel schedule: sync = both sides per round (fewest "
        "rounds), alt = smaller-frontier-first alternation (fewest edge "
        "scans); beamer variants add push/pull direction optimization; "
        "pallas variants use the fused Pallas pull kernel for the base "
        "table, hub tiers as XLA ops (dense backend); fused runs the whole "
        "lock-step level as one kernel (dense backend, plain ELL)",
    )
    ap.add_argument(
        "--layout",
        default="ell",
        choices=["ell", "tiered"],
        help="adjacency layout for the device backends (see bibfs-solve)",
    )
    ap.add_argument(
        "--pairs",
        default=None,
        metavar="FILE",
        help='also bench batched multi-query throughput: file of "src dst" '
        "lines solved as one vmapped device program (dense single-chip, "
        "sharded multi-chip) and/or a scratch-reusing host loop (native), "
        "one per-query amortized row per benched backend",
    )
    ap.add_argument(
        "--serve",
        action="store_true",
        help="with --pairs: add a serving-engine throughput row per "
        "graph (adaptive micro-batching + distance/executable caches, "
        "bibfs_tpu/serve) validated against the same oracle",
    )
    ap.add_argument("--csv", default="benchmark_results.csv")
    ap.add_argument("--table", default="benchmark_table.txt")
    args = ap.parse_args(argv)
    from bibfs_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    backends = (
        args.backends.split(",") if args.backends else available_backends()
    )
    if args.mode.startswith("pallas") and any(
        b not in ("dense", "serial", "native") for b in backends
    ):
        ap.error("--mode pallas/pallas_alt requires --backends dense (the "
                 "sharded backends have no pallas path)")
    if args.mode in ("fused", "fused_alt") and any(
        b not in ("dense", "sharded", "serial", "native") for b in backends
    ):
        ap.error("--mode fused/fused_alt requires --backends dense/sharded "
                 "(the whole-level kernel has no 2D form)")
    if args.mode not in ("sync", "alt") and "sharded2d" in backends:
        ap.error("--backends sharded2d supports --mode sync/alt only")
    if args.layout != "ell" and "sharded2d" in backends:
        ap.error("--backends sharded2d has its own block layout; drop "
                 "--layout or bench it separately")
    if args.pairs is not None and not {
        "dense", "native", "sharded", "sharded2d"
    } & set(backends):
        ap.error("--pairs requires the dense, native, sharded and/or "
                 "sharded2d backend in --backends")
    if args.serve and args.pairs is None:
        ap.error("--serve needs --pairs FILE (the served query list)")
    rows = run_bench(
        args.graphs,
        backends,
        repeats=args.repeats,
        csv_path=args.csv,
        table_path=args.table,
        num_devices=args.devices,
        mode=args.mode,
        layout=args.layout,
        pairs_file=args.pairs,
        serve=args.serve,
    )
    return 0 if all(r["ok"] for r in rows) else 1


if __name__ == "__main__":
    raise SystemExit(main())
