"""CLI solver — the reference's common binary contract, one binary for all
backends.

Reference contract: ``<exe> <graph.bin> <src> <dst>`` (v1/main-v1.cpp:15,
v2/second_try.cpp:23, v3/bibfs_cuda_only.cu:66, v4/mpi_bas.cpp:19), printing
a scrapeable time line, a "Shortest path length = N" line and a "Path: ..."
line (v1/main-v1.cpp:93-101). We keep those exact output shapes so the
reference's awk harness patterns (benchmark_test.sh:61-69) scrape this
solver unmodified, and add ``--backend`` to select the engine.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Bidirectional BFS (TPU-native framework)"
    )
    ap.add_argument("graph", help="binary graph file (uint32 N,M + edge pairs)")
    ap.add_argument("src", type=int, nargs="?", default=None)
    ap.add_argument("dst", type=int, nargs="?", default=None)
    ap.add_argument(
        "--pairs",
        default=None,
        metavar="FILE",
        help='batch mode (dense/sharded/sharded2d/native backends): file '
        'of "src dst" lines solved as ONE vmapped device program (dense '
        "single-chip, sharded/sharded2d multi-chip) or a scratch-reusing "
        "host loop (native); replaces the positional src/dst",
    )
    ap.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="write a jax.profiler trace of the solve to DIR (inspect with "
        "TensorBoard / xprof)",
    )
    ap.add_argument(
        "--backend",
        default="serial",
        help="serial | native | dense | sharded | sharded2d "
        "(default: serial)",
    )
    ap.add_argument(
        "--grid",
        default=None,
        metavar="RxC",
        help="mesh shape for --backend sharded2d (e.g. 2x4): adjacency is "
        "blocked over an R x C grid so per-level frontier traffic scales "
        "as O(n/C + n/R) instead of the 1D solver's O(n) (default: the "
        "squarest factorization of the visible device count)",
    )
    ap.add_argument(
        "--devices",
        type=int,
        default=None,
        help="mesh size for the sharded/sharded2d backends (default: all "
        "visible devices; sharded2d factorizes it into the squarest grid "
        "unless --grid is given)",
    )
    ap.add_argument("--no-path", action="store_true", help="skip path printing")
    ap.add_argument(
        "--sources",
        default=None,
        metavar="S1,S2,...",
        help="multi-source query (bibfs_tpu/query): hop distance from "
        "EVERY listed source to dst, answered by one bitmask-packed "
        "msBFS sweep per 64 sources (replaces the positional src — "
        "put the dst positional BEFORE this flag: "
        "`bibfs-solve g.bin DST --sources S1,S2`; host tier)",
    )
    ap.add_argument(
        "--kshortest",
        type=int,
        default=None,
        metavar="K",
        help="the K shortest loopless src->dst paths (Yen's over the "
        "restricted-BFS machinery; host tier), non-decreasing in length",
    )
    ap.add_argument(
        "--weighted",
        action="store_true",
        help="weighted shortest path via delta-stepping, edge weights "
        "derived from the seeded symmetric hash (--weight-seed); "
        "host tier",
    )
    ap.add_argument(
        "--weight-seed",
        type=int,
        default=0,
        help="weight-derivation seed for --weighted (same seed = same "
        "weights on every replica; default 0)",
    )
    ap.add_argument(
        "--level-stats",
        action="store_true",
        help="record per-level telemetry (frontier sizes, edges scanned, "
        "push/pull direction, meet level) during the solve and print it "
        "after the answer — supported by the serial/native/dense "
        "backends (bibfs_tpu/obs/telemetry); single-query only",
    )
    ap.add_argument(
        "--repeat",
        type=int,
        default=1,
        help="time the median of K repeats after a warm-up run (K>1 excludes "
        "JIT compile from the reported time, like the benchmark harness)",
    )
    ap.add_argument(
        "--mode",
        default=None,
        choices=["sync", "alt", "beamer", "beamer_alt", "pallas",
                 "pallas_alt", "fused", "fused_alt", "sync_unfused",
                 "minor", "minor8", "auto"],
        help="device-kernel schedule for the device backends (default "
        "sync): sync = both sides per round, alt = smaller-frontier-first "
        "alternation; beamer/beamer_alt add push/pull direction "
        "optimization (sparse frontiers go through a scatter push path "
        "instead of the full-table pull gather); fused runs the whole "
        "lock-step level as ONE kernel (dense backend, plain ELL); "
        "pallas/pallas_alt run the "
        "base-table pull as the fused Pallas TPU kernel, hub tiers as XLA "
        "ops (dense backend; interpreted off-TPU); minor/minor8 are "
        "BATCH-only layouts (--pairs, dense backend): per-query "
        "state on the lane axis so the expansion gathers contiguous rows, "
        "minor8 with all-int8 planes (plain ELL); auto (batch only) picks "
        "the best eligible batch layout. With --resume, omitting "
        "--mode keeps the snapshot's recorded schedule",
    )
    ap.add_argument(
        "--unroll",
        type=int,
        default=1,
        metavar="K",
        help="dense/sharded backends: run K search rounds per while-loop "
        "iteration (exact — each in-block round re-checks the same "
        "termination vote), amortizing the backend's fixed per-iteration "
        "cost; 1 = the plain per-level loop",
    )
    ap.add_argument(
        "--checkpoint",
        default=None,
        metavar="FILE",
        help="device backends (dense/sharded/sharded2d): run the search "
        "in chunks and snapshot "
        "the device state to FILE after every chunk (atomic .npz); with "
        "--resume, continue a previous search from FILE instead of "
        "restarting (the snapshot is backend/mesh-portable)",
    )
    ap.add_argument(
        "--chunk",
        type=int,
        default=None,
        metavar="K",
        help="levels per dispatch for the checkpointed path (default 8); "
        "implies chunked execution even without --checkpoint",
    )
    ap.add_argument(
        "--resume",
        action="store_true",
        help="resume the search from --checkpoint FILE (src/dst must match "
        "the snapshot's fingerprint)",
    )
    ap.add_argument(
        "--layout",
        default="ell",
        choices=["ell", "tiered"],
        help="adjacency layout for the dense/sharded backends: ell = "
        "single-width table (uniform-degree graphs), tiered = base table + "
        "geometric hub tiers (power-law/RMAT degree distributions)",
    )
    args = ap.parse_args(argv)
    # None = unspecified: acts as "sync" everywhere except --resume, where
    # it means "keep the schedule recorded in the snapshot"
    mode = args.mode or "sync"

    from bibfs_tpu.graph.io import read_graph_bin
    from bibfs_tpu.solvers.api import solve
    from bibfs_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    try:
        n, edges = read_graph_bin(args.graph)
    except (OSError, ValueError) as e:
        print(f"Error reading graph: {e}", file=sys.stderr)
        return 2

    taxonomy = (
        args.sources is not None or args.kshortest is not None
        or args.weighted
    )
    if taxonomy:
        if sum((args.sources is not None, args.kshortest is not None,
                args.weighted)) > 1:
            ap.error("--sources / --kshortest / --weighted are mutually "
                     "exclusive query kinds")
        if args.pairs is not None or args.repeat > 1 or args.level_stats:
            ap.error("taxonomy queries are single-query (no --pairs / "
                     "--repeat / --level-stats)")
        return _taxonomy_main(ap, args, n, edges)

    if args.layout == "tiered" and args.backend not in ("dense", "sharded"):
        ap.error("--layout tiered is only supported by the dense/sharded backends")
    rows = cols = None
    if args.grid is not None:
        if args.backend != "sharded2d":
            ap.error("--grid only applies to --backend sharded2d")
        try:
            rows, cols = (int(x) for x in args.grid.lower().split("x"))
            if rows < 1 or cols < 1:
                raise ValueError
        except ValueError:
            ap.error(f"--grid must look like 2x4, got {args.grid!r}")
    if args.backend == "sharded2d":
        if mode not in ("sync", "alt"):
            ap.error("--backend sharded2d supports --mode sync/alt only "
                     "(pull-only 2D partition)")
        if args.layout != "ell":
            ap.error("--backend sharded2d has its own block layout; "
                     "--layout does not apply")

    if mode.startswith("pallas") and args.backend not in ("dense", "sharded"):
        ap.error("--mode pallas/pallas_alt is only supported by the dense "
                 "and sharded backends")
    if mode in ("fused", "fused_alt") and args.backend not in (
        "dense", "sharded"
    ):
        ap.error("--mode fused/fused_alt (whole-level kernel) is only "
                 "supported by the dense and sharded backends")
    if mode in ("minor", "minor8", "auto"):
        if args.pairs is None or args.backend != "dense":
            ap.error("--mode minor/minor8/auto are batch-only: use "
                     "--pairs FILE with --backend dense")
        if args.layout == "tiered" and mode == "minor8":
            ap.error("--mode minor8 is plain-ELL only (slot-coded "
                     "parents); tiered graphs batch through --mode "
                     "minor or sync")
    if args.pairs is not None:
        if args.backend not in ("dense", "native", "sharded", "sharded2d"):
            ap.error("--pairs batch mode is supported by --backend dense/"
                     "sharded/sharded2d (one vmapped device program) and "
                     "native (scratch-reusing host loop)")
        if args.devices is not None and args.backend not in (
            "sharded", "sharded2d"
        ):
            ap.error("--devices only applies to the sharded backends in "
                     "--pairs batch mode (dense/native are single-device)")
        if args.src is not None or args.dst is not None:
            ap.error("--pairs replaces the positional src/dst arguments")
    elif args.src is None or args.dst is None:
        ap.error("src and dst are required (or use --pairs FILE)")
    checkpointed = (
        args.checkpoint is not None or args.chunk is not None or args.resume
    )
    if checkpointed:
        if args.backend not in ("dense", "sharded", "sharded2d"):
            ap.error("--checkpoint/--chunk/--resume need a device backend "
                     "(dense/sharded/sharded2d); host backends finish in "
                     "one shot")
        if args.pairs is not None or args.repeat > 1:
            ap.error("--checkpoint/--chunk are single-query (no --pairs / "
                     "--repeat)")
        if args.resume and args.checkpoint is None:
            ap.error("--resume needs --checkpoint FILE to resume from")
        if args.chunk is not None and args.chunk < 1:
            ap.error("--chunk must be >= 1")
    if args.unroll < 1:
        ap.error("--unroll must be >= 1")
    if args.unroll > 1 and args.backend not in ("dense", "sharded"):
        ap.error("--unroll applies to the dense/sharded backends only")
    if args.unroll > 1 and (args.pairs is not None or checkpointed):
        # reject rather than silently run un-unrolled: the batch and
        # chunked kernels do not thread the unroll parameter (yet)
        ap.error("--unroll is single-query only (no --pairs / "
                 "--checkpoint / --chunk / --resume)")
    if args.level_stats:
        if args.backend not in ("serial", "native", "dense"):
            ap.error("--level-stats is supported by the serial/native/"
                     "dense backends")
        if args.pairs is not None or checkpointed or args.repeat > 1:
            ap.error("--level-stats is single-query only (no --pairs / "
                     "--checkpoint / --repeat)")
    kwargs = {}
    if args.level_stats:
        kwargs["telemetry"] = True
    if args.devices is not None:
        kwargs["num_devices"] = args.devices
    if args.backend in ("dense", "sharded"):
        kwargs["mode"] = mode
        kwargs["layout"] = args.layout
        kwargs["unroll"] = args.unroll
    elif args.backend == "sharded2d":
        kwargs["mode"] = mode
        kwargs["rows"] = rows
        kwargs["cols"] = cols
    import contextlib

    def tracer():
        if not args.profile:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.trace(args.profile)

    try:
        if args.pairs is not None:
            return _batch_main(args, n, edges, tracer, mode, rows, cols)
        if checkpointed:
            return _checkpoint_main(args, n, edges, tracer, mode, rows, cols)
        with tracer():
            if args.repeat > 1:
                # shared protocol: graph/JIT warm-up excluded, zero-D2H
                # repeat loop, median reported (bibfs_tpu.solvers.timing)
                from bibfs_tpu.solvers.timing import time_backend

                _times, res = time_backend(
                    args.backend, n, edges, args.src, args.dst,
                    repeats=args.repeat,
                    num_devices=args.devices,
                    mode=mode,
                    layout=args.layout,
                    rows=rows,
                    cols=cols,
                    unroll=args.unroll,
                )
            else:
                res = solve(args.backend, n, edges, args.src, args.dst, **kwargs)
    except KeyError as e:
        print(f"Error: {e.args[0]}", file=sys.stderr)
        return 2
    except (ValueError, RuntimeError, ImportError, OSError) as e:
        # RuntimeError covers device-backend init failures (e.g. a
        # configured-but-unreachable TPU platform); ImportError/OSError a
        # missing JAX stack or native toolchain on the --repeat path
        print(f"Error: {e}", file=sys.stderr)
        return 2

    if res.found:
        print(f"Shortest path length = {res.hops}")
        if res.path and not args.no_path:
            print("Path: " + " -> ".join(str(v) for v in res.path))
    else:
        print("No path found.")
    # scrapeable time line (same shape as v1/main-v1.cpp:101)
    print(f"[Time] {args.backend} bidirectional BFS took {res.time_s:.9f} seconds")
    print(f"[TEPS] {res.teps:.3e} traversed edges/second ({res.edges_scanned} edges)")
    if args.level_stats and res.level_stats is not None:
        for lv in res.level_stats["levels"]:
            print(
                "[Level] {level:>3} side={side} dir={dir:<4} "
                "frontier={frontier:>8} edges={edges}".format(**lv)
            )
        print(f"[Level] meet_level={res.level_stats['meet_level']}")
    return 0


def _checkpoint_main(args, n, edges, tracer, mode, rows=None, cols=None):
    from bibfs_tpu.solvers.checkpoint import resume, solve_checkpointed

    if args.backend == "sharded2d":
        from bibfs_tpu.solvers.sharded2d import Sharded2DGraph

        g = Sharded2DGraph.build(
            n, edges, rows=rows, cols=cols, num_devices=args.devices
        )
    elif args.backend == "sharded":
        from bibfs_tpu.parallel.mesh import make_1d_mesh
        from bibfs_tpu.solvers.sharded import ShardedGraph

        g = ShardedGraph.build(
            n, edges, make_1d_mesh(args.devices), layout=args.layout
        )
    else:
        from bibfs_tpu.solvers.dense import DeviceGraph

        g = DeviceGraph.build(n, edges, layout=args.layout)
    chunk = args.chunk if args.chunk is not None else 8
    with tracer():
        if args.resume:
            res = resume(
                args.checkpoint, g, src=args.src, dst=args.dst,
                mode=args.mode, chunk=chunk,
            )
        else:
            res = solve_checkpointed(
                g, args.src, args.dst, mode=mode, chunk=chunk,
                path=args.checkpoint,
            )
    if res.found:
        print(f"Shortest path length = {res.hops}")
        if res.path and not args.no_path:
            print("Path: " + " -> ".join(str(v) for v in res.path))
    else:
        print("No path found.")
    print(
        f"[Time] {args.backend} bidirectional BFS took {res.time_s:.9f} seconds"
    )
    print(
        f"[TEPS] {res.teps:.3e} traversed edges/second "
        f"({res.edges_scanned} edges)"
    )
    if args.checkpoint:
        print(f"[Checkpoint] {args.checkpoint} (chunk={chunk} levels)")
    return 0


def _batch_main(args, n, edges, tracer, mode, rows=None, cols=None):
    import numpy as np

    pairs = np.loadtxt(args.pairs, dtype=np.int64, ndmin=2)
    if pairs.shape[1] != 2:
        print(f"Error: {args.pairs} must have two columns (src dst)", file=sys.stderr)
        return 2
    if args.backend == "native":
        from bibfs_tpu.solvers.native import (
            NativeGraph,
            solve_batch_native_graph,
            time_batch_native,
        )

        g = NativeGraph.build(n, edges)
        with tracer():
            if args.repeat > 1:
                _times, results = time_batch_native(
                    g, pairs, repeats=args.repeat
                )
            else:
                results = solve_batch_native_graph(g, pairs)
    elif args.backend == "sharded":
        from bibfs_tpu.parallel.mesh import make_1d_mesh
        from bibfs_tpu.solvers.sharded import (
            ShardedGraph,
            solve_batch_sharded_graph,
            time_batch_sharded,
        )

        g = ShardedGraph.build(
            n, edges, make_1d_mesh(args.devices), layout=args.layout
        )
        with tracer():
            if args.repeat > 1:
                _times, results = time_batch_sharded(
                    g, pairs, repeats=args.repeat, mode=mode
                )
            else:
                results = solve_batch_sharded_graph(g, pairs, mode=mode)
    elif args.backend == "sharded2d":
        from bibfs_tpu.solvers.sharded2d import (
            Sharded2DGraph,
            solve_batch_sharded2d_graph,
            time_batch_sharded2d,
        )

        g = Sharded2DGraph.build(
            n, edges, rows=rows, cols=cols, num_devices=args.devices
        )
        with tracer():
            if args.repeat > 1:
                _times, results = time_batch_sharded2d(
                    g, pairs, repeats=args.repeat, mode=mode
                )
            else:
                results = solve_batch_sharded2d_graph(g, pairs, mode=mode)
    else:
        from bibfs_tpu.solvers.dense import (
            DeviceGraph,
            solve_batch_graph,
            time_batch_graph,
        )

        g = DeviceGraph.build(n, edges, layout=args.layout)
        with tracer():
            if args.repeat > 1:
                _times, results = time_batch_graph(
                    g, pairs, repeats=args.repeat, mode=mode
                )
            else:
                results = solve_batch_graph(g, pairs, mode=mode)
    for (src, dst), res in zip(pairs, results):
        if res.found:
            line = f"{src} -> {dst}: length = {res.hops}"
            if res.path and not args.no_path:
                line += "  path: " + " -> ".join(str(v) for v in res.path)
        else:
            line = f"{src} -> {dst}: no path"
        print(line)
    batch_s = results[0].time_s if results else 0.0
    print(
        f"[Time] {args.backend} batch of {len(results)} searches took "
        f"{batch_s:.9f} seconds ({batch_s / max(len(results), 1):.9f} s/query)"
    )
    return 0


def _taxonomy_main(ap, args, n, edges):
    """``--sources`` / ``--kshortest`` / ``--weighted``: the typed
    query kinds (bibfs_tpu/query) through :func:`api.solve_query`,
    host tier, with the reference's scrapeable output shapes kept
    where they apply."""
    from bibfs_tpu.query import KShortest, MultiSource, Weighted
    from bibfs_tpu.solvers.api import solve_query

    if args.dst is None:
        # --sources replaces src only; every kind still needs a dst
        # (with --sources the one positional argument IS the dst)
        if args.sources is not None and args.src is not None:
            args.dst, args.src = args.src, None
        else:
            ap.error("taxonomy queries need a destination vertex")
    if args.sources is not None:
        if args.src is not None:
            ap.error("--sources replaces the positional src")
        try:
            sources = tuple(
                int(x) for x in args.sources.split(",") if x.strip()
            )
        except ValueError:
            ap.error(f"--sources must be a comma list of ints, got "
                     f"{args.sources!r}")
        q = MultiSource(sources, args.dst)
    elif args.kshortest is not None:
        if args.src is None:
            ap.error("--kshortest needs positional src and dst")
        q = KShortest(args.src, args.dst, k=args.kshortest)
    else:
        if args.src is None:
            ap.error("--weighted needs positional src and dst")
        q = Weighted(args.src, args.dst, weight_seed=args.weight_seed)
    try:
        res = solve_query(n, edges, q)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2
    if isinstance(q, MultiSource):
        for s, hops in zip(q.sources, res.per_source):
            print(f"{s} -> {q.dst}: "
                  + (f"length = {hops}" if hops is not None else "no path"))
        if res.found and res.path and not args.no_path:
            print(f"Best ({q.sources[res.best]}): Path: "
                  + " -> ".join(str(v) for v in res.path))
        print(f"[Time] msbfs {res.sweeps} sweep(s) over {len(q.sources)} "
              f"sources took {res.time_s:.9f} seconds")
    elif isinstance(q, KShortest):
        if not res.found:
            print("No path found.")
        for i, (p, hops) in enumerate(zip(res.paths, res.hops), 1):
            line = f"[{i}] length = {hops}"
            if not args.no_path:
                line += "  path: " + " -> ".join(str(v) for v in p)
            print(line)
        print(f"[Time] kshortest k={q.k} took {res.time_s:.9f} seconds")
    else:
        if res.found:
            print(f"Weighted distance = {res.dist:g} ({res.hops} edges)")
            if res.path and not args.no_path:
                print("Path: " + " -> ".join(str(v) for v in res.path))
        else:
            print("No path found.")
        print(f"[Time] weighted delta-stepping took {res.time_s:.9f} "
              f"seconds ({res.buckets} buckets, "
              f"{res.relaxations} relaxations)")
    return 0


def _main():
    try:
        return main()
    except BrokenPipeError:  # e.g. piped into `head`
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    raise SystemExit(_main())
