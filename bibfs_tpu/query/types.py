"""The typed query/result taxonomy — what replaced the raw ``(s, t)``.

Every layer of the serving stack used to thread one query shape — an
unweighted point-to-point ``(src, dst)`` hop count plus path — through
``solvers/api.py``, both engines, and the route ladder, while the
plumbing around it (batching, routes, resilience, oracle, WAL) was
already general. This module is the forcing-function refactor ROADMAP
item 3 asked for: queries are TYPED values, each kind carrying exactly
the fields its solvers need, and the engines dispatch on ``kind``
instead of assuming the tuple:

- :class:`PointToPoint` — the original shape; resolves to a
  :class:`~bibfs_tpu.solvers.api.BFSResult` through the unchanged
  ladder (oracle/cache/mesh/blocked/device/host).
- :class:`MultiSource` — K sources against one destination, answered
  by ONE bitmask-packed msBFS sweep per 64 sources
  (:mod:`bibfs_tpu.query.msbfs` — the ``oracle/trees.py`` build
  primitive promoted to a first-class serving route; seed idea from
  the reference MPI version's bitset frontiers, v2/second_try.cpp).
- :class:`Weighted` — weighted shortest path via delta-stepping over
  bucketed frontiers (:mod:`bibfs_tpu.query.weighted`), validated
  against a NumPy Dijkstra oracle. Weights are derived per edge from
  a seeded symmetric hash (``weight_seed``) so a weighted query is
  self-describing against any snapshot — no per-query weight arrays
  on the wire.
- :class:`KShortest` — Yen's algorithm over the repaired-path
  machinery (:mod:`bibfs_tpu.query.kshortest`), a host-tier kind.
- :class:`AsOf` — the time-travel wrapper: any non-AsOf query answered
  against the graph AS OF a historical store version, reconstructed
  from the WAL + versioned manifests (:mod:`bibfs_tpu.store.history`).

``coerce_query`` keeps the old call sites working: a bare ``(s, d)``
pair IS a :class:`PointToPoint`. ``QUERY_KINDS`` is the taxonomy the
``bibfs_query_total{kind,route}`` metric family and the loadgen mix
spec share.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

#: the query-kind taxonomy (``bibfs_query_total{kind=}`` label values);
#: ``asof`` wraps one of the others but is counted as its own kind —
#: the operational question "how much time-travel traffic" is about
#: the replay machinery, not the inner shape
QUERY_KINDS = ("pt", "msbfs", "weighted", "kshortest", "asof",
               # whole-graph analytics kinds (bibfs_tpu/analytics):
               # vectors/scalars over the full graph, same dispatch
               "sssp", "pagerank", "components", "triangles")

#: sources one bitmask-packed msBFS sweep answers (one uint64 word of
#: reachability bits per vertex per sweep — oracle/trees.py)
MSBFS_WORD = 64


class Query:
    """Base of the taxonomy: ``kind`` is the metric/dispatch label,
    ``validate(n)`` raises ``ValueError`` on malformed client input
    (the submit-time seam that may tag ``kind='invalid'``), and
    ``cache_key()`` is the per-snapshot result-cache identity."""

    kind: str = "pt"

    def validate(self, n: int) -> None:
        raise NotImplementedError

    def cache_key(self) -> tuple:
        raise NotImplementedError


def _check_node(v, n: int, what: str) -> int:
    v = int(v)
    if not 0 <= v < n:
        raise ValueError(f"{what}={v} out of range for n={n}")
    return v


@dataclasses.dataclass(frozen=True)
class PointToPoint(Query):
    """The original query shape: unweighted s-t hops + path."""

    src: int
    dst: int
    kind = "pt"

    def validate(self, n: int) -> None:
        _check_node(self.src, n, "src")
        _check_node(self.dst, n, "dst")

    def cache_key(self) -> tuple:
        return ("pt", int(self.src), int(self.dst))


@dataclasses.dataclass(frozen=True)
class MultiSource(Query):
    """K sources against one destination: ``dist(s_i, dst)`` for every
    source, one packed sweep per 64 distinct sources. ``sources`` is a
    tuple (hashable — the cache key needs it); order is preserved in
    the result's ``per_source``."""

    sources: tuple
    dst: int
    kind = "msbfs"

    def __post_init__(self):
        object.__setattr__(
            self, "sources", tuple(int(s) for s in self.sources)
        )

    def validate(self, n: int) -> None:
        if not self.sources:
            raise ValueError("MultiSource needs at least one source")
        for s in self.sources:
            _check_node(s, n, "source")
        _check_node(self.dst, n, "dst")

    def cache_key(self) -> tuple:
        return ("msbfs", self.sources, int(self.dst))


@dataclasses.dataclass(frozen=True)
class Weighted(Query):
    """Weighted shortest path under the seeded symmetric edge-weight
    hash (:func:`bibfs_tpu.query.weighted.synthetic_weights` — the
    same ``weight_seed`` always derives the same weights from the same
    snapshot, so results cache per (snapshot, seed, s, t))."""

    src: int
    dst: int
    weight_seed: int = 0
    kind = "weighted"

    def validate(self, n: int) -> None:
        _check_node(self.src, n, "src")
        _check_node(self.dst, n, "dst")

    def cache_key(self) -> tuple:
        return ("weighted", int(self.src), int(self.dst),
                int(self.weight_seed))


@dataclasses.dataclass(frozen=True)
class KShortest(Query):
    """The K shortest loopless s-t paths (Yen's), non-decreasing in
    length; ``k`` is a request cap, the result may hold fewer."""

    src: int
    dst: int
    k: int = 3
    kind = "kshortest"

    def validate(self, n: int) -> None:
        _check_node(self.src, n, "src")
        _check_node(self.dst, n, "dst")
        if int(self.k) < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")

    def cache_key(self) -> tuple:
        return ("kshortest", int(self.src), int(self.dst), int(self.k))


@dataclasses.dataclass(frozen=True)
class AsOf(Query):
    """Time-travel wrapper: answer ``inner`` against the graph as of
    store ``version`` (reconstructed from the WAL + versioned
    manifests — :mod:`bibfs_tpu.store.history`). ``inner`` may be any
    non-AsOf query; nesting wrappers would mean nothing."""

    inner: Query
    version: int
    kind = "asof"

    def __post_init__(self):
        if isinstance(self.inner, AsOf):
            raise ValueError("AsOf cannot wrap another AsOf query")
        if not isinstance(self.inner, Query):
            object.__setattr__(self, "inner", coerce_query(self.inner))
            if isinstance(self.inner, AsOf):
                raise ValueError("AsOf cannot wrap another AsOf query")

    def validate(self, n: int) -> None:
        if int(self.version) < 1:
            raise ValueError(
                f"as_of version must be >= 1, got {self.version}"
            )
        self.inner.validate(n)

    def cache_key(self) -> tuple:
        return ("asof", int(self.version)) + self.inner.cache_key()


def coerce_query(q) -> Query:
    """A :class:`Query` from whatever a call site passed: a Query
    passes through, a 2-sequence is a :class:`PointToPoint` (the old
    ``(s, d)`` contract). Anything else is a ``ValueError`` — the
    submit-time seam tags it ``kind='invalid'``."""
    if isinstance(q, Query):
        return q
    try:
        s, d = q
        return PointToPoint(int(s), int(d))
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"not a query: {q!r} (expected a Query or an (src, dst) pair)"
        ) from e


# ---- results ---------------------------------------------------------
@dataclasses.dataclass
class MultiSourceResult:
    """One :class:`MultiSource` answer. ``per_source[i]`` is the hop
    count from ``sources[i]`` to ``dst`` (None = unreachable);
    ``best`` indexes the nearest reachable source; ``path`` is a real
    shortest path from that source (validated edge-by-edge in tests)."""

    found: bool                      # any source reaches dst
    per_source: tuple                # hops per source, None = unreachable
    best: Optional[int]              # index of the nearest source
    hops: Optional[int]              # per_source[best]
    path: Optional[list]             # [sources[best], ..., dst]
    time_s: float
    sweeps: int = 1                  # packed sweeps this answer rode


@dataclasses.dataclass
class WeightedResult:
    """One :class:`Weighted` answer: exact weighted distance + path
    (``hops`` is the path's edge count — distinct from ``dist``, the
    weight sum the Dijkstra oracle pins)."""

    found: bool
    dist: Optional[float]
    hops: Optional[int]
    path: Optional[list]
    time_s: float
    relaxations: int = 0
    buckets: int = 0                 # delta-stepping buckets processed


@dataclasses.dataclass
class KShortestResult:
    """One :class:`KShortest` answer: up to k loopless paths, hops
    strictly non-decreasing; ``found`` iff at least one path exists."""

    found: bool
    paths: list                      # list[list[int]], each [src..dst]
    hops: list                       # len(paths), edge counts
    time_s: float


def result_found(res) -> bool:
    """Uniform "did the query connect" read across the result
    taxonomy (every result type carries ``found``)."""
    return bool(getattr(res, "found", False))
