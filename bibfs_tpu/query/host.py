"""One host-tier solve for any (non-AsOf) taxonomy query over a CSR.

The shared dispatch the serving AsOf route (historical snapshots have
no serving tier — time-travel is a read path) and the
``solvers/api.py`` convenience entry both use: given the
``(row_ptr, col_ind)`` truth, route the query to its kind's host
implementation and return its typed result.
"""

from __future__ import annotations

from bibfs_tpu.query.types import (
    AsOf,
    KShortest,
    MultiSource,
    PointToPoint,
    Weighted,
)


def solve_query_csr(n: int, row_ptr, col_ind, q):
    """Solve one typed query on the host tier. ``AsOf`` is rejected —
    resolving a version needs a store (the serving route / the api
    entry unwrap it first)."""
    if isinstance(q, PointToPoint):
        from bibfs_tpu.solvers.serial import solve_serial_csr

        return solve_serial_csr(n, row_ptr, col_ind, q.src, q.dst)
    if isinstance(q, MultiSource):
        from bibfs_tpu.query.msbfs import solve_multi_source

        return solve_multi_source(n, row_ptr, col_ind, [q])[0]
    if isinstance(q, Weighted):
        from bibfs_tpu.query.weighted import delta_stepping, synthetic_weights

        w = synthetic_weights(row_ptr, col_ind, int(q.weight_seed))
        return delta_stepping(n, row_ptr, col_ind, w, q.src, q.dst)
    if isinstance(q, KShortest):
        from bibfs_tpu.query.kshortest import yen_k_shortest

        return yen_k_shortest(n, row_ptr, col_ind, q.src, q.dst, q.k)
    if isinstance(q, AsOf):
        raise ValueError(
            "AsOf resolves through a store (serve.routes.taxonomy / "
            "api.solve_query with store=); solve_query_csr takes the "
            "inner query against the reconstructed CSR"
        )
    raise ValueError(f"unknown query type {type(q).__name__}")
