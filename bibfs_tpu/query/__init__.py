"""The query taxonomy subsystem (``bibfs_tpu/query``).

Typed queries (:mod:`bibfs_tpu.query.types`) and the host-tier solver
implementations behind the non-point-to-point kinds: bitmask-packed
multi-source answering (:mod:`bibfs_tpu.query.msbfs`), delta-stepping
weighted shortest paths with a Dijkstra validation oracle
(:mod:`bibfs_tpu.query.weighted`), and Yen's k-shortest
(:mod:`bibfs_tpu.query.kshortest`). The serving integration — routes,
breakers, chaos seams, metrics — lives in
:mod:`bibfs_tpu.serve.routes.taxonomy`; the time-travel reconstruction
behind :class:`AsOf` lives in :mod:`bibfs_tpu.store.history`.

Import-light by design: importing the taxonomy pulls neither JAX nor
the serving stack, so ``solvers/api.py`` and the CLIs can type their
signatures against it for free.
"""

from bibfs_tpu.query.types import (
    MSBFS_WORD,
    QUERY_KINDS,
    AsOf,
    KShortest,
    KShortestResult,
    MultiSource,
    MultiSourceResult,
    PointToPoint,
    Query,
    Weighted,
    WeightedResult,
    coerce_query,
    result_found,
)

__all__ = [
    "MSBFS_WORD",
    "QUERY_KINDS",
    "AsOf",
    "KShortest",
    "KShortestResult",
    "MultiSource",
    "MultiSourceResult",
    "PointToPoint",
    "Query",
    "Weighted",
    "WeightedResult",
    "coerce_query",
    "result_found",
]
