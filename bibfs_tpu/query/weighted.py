"""Weighted shortest paths: delta-stepping over bucketed frontiers.

The unweighted engines advance ONE frontier per level; delta-stepping
(Meyer & Sanders) is the same frontier machinery with the frontier
split into distance buckets of width ``delta``: bucket ``i`` holds
vertices with tentative distance in ``[i*delta, (i+1)*delta)``, light
edges (weight <= delta) are relaxed iteratively until the bucket
settles, heavy edges once per settled bucket. With unit weights and
``delta=1`` this degenerates to exactly the level-synchronous BFS the
rest of the repo runs — which is why it is the right weighted
generalization of this codebase rather than a bolted-on Dijkstra.

Weights are not stored in the graph (snapshots are edge-set content —
their digest must not depend on a query-time concern): they are
DERIVED per query from a seeded symmetric hash of the edge endpoints
(:func:`synthetic_weights`), so the same ``weight_seed`` always
reproduces the same weights from the same snapshot on every replica,
and a weighted result caches per ``(snapshot, seed, s, t)``.

:func:`dijkstra_numpy` is the validation oracle — a plain binary-heap
Dijkstra with none of the bucket machinery, the independent
implementation the property tests (and the ``--serve-queries`` bench
gate) pin delta-stepping against, query by query.
"""

from __future__ import annotations

import heapq
import time

import numpy as np

_INF = np.float64(np.inf)


def edge_weight_hash(src: np.ndarray, dst: np.ndarray, seed: int = 0,
                     *, max_w: int = 9) -> np.ndarray:
    """The ONE weight derivation: positive integer weights in
    ``[1, max_w]`` for arbitrary (src, dst) endpoint arrays, SYMMETRIC
    (hashing the canonical (min, max) pair) and deterministic in
    ``seed``. Shared by the CSR derivation below and the device rung's
    ELL-aligned table (:func:`ell_weights`) — the two layouts MUST
    weigh every edge identically or the device answers drift."""
    a = np.minimum(src, dst).astype(np.uint64)
    b = np.maximum(src, dst).astype(np.uint64)
    # splitmix-style avalanche over the canonical (min, max, seed)
    # triple — uint64 wraparound is the point, silence the warnings
    with np.errstate(over="ignore"):
        seed_mix = np.uint64(
            ((int(seed) & 0xFFFFFFFF) * 0x94D049BB133111EB)
            & 0xFFFFFFFFFFFFFFFF
        )
        h = (a * np.uint64(0x9E3779B97F4A7C15)
             ^ b * np.uint64(0xBF58476D1CE4E5B9)
             ^ seed_mix)
        h ^= h >> np.uint64(31)
        h *= np.uint64(0xD6E8FEB86659FD93)
        h ^= h >> np.uint64(27)
    return (1 + (h % np.uint64(int(max_w)))).astype(np.float64)


def synthetic_weights(row_ptr: np.ndarray, col_ind: np.ndarray,
                      seed: int = 0, *, max_w: int = 9) -> np.ndarray:
    """Per-CSR-entry weights via :func:`edge_weight_hash` — one
    vectorized mixing pass over the CSR, no Python per-edge loop."""
    n = row_ptr.shape[0] - 1
    src = np.repeat(
        np.arange(n, dtype=np.int64), np.diff(row_ptr).astype(np.int64)
    )
    return edge_weight_hash(src, col_ind.astype(np.int64), seed,
                            max_w=max_w)


def ell_weights(nbr: np.ndarray, deg: np.ndarray, seed: int = 0, *,
                max_w: int = 9) -> np.ndarray:
    """The same derived weights aligned with an ELL table: ``float32
    [n_pad, width]``, ``+inf`` at dead/pad slots (a dead slot's
    relaxation candidate must never win a scatter-min). The live
    entries hash identically to :func:`synthetic_weights` over the
    same graph — the device delta-stepping rung's exactness leans on
    it."""
    n_pad, width = nbr.shape
    rows = np.repeat(np.arange(n_pad, dtype=np.int64), width)
    w = edge_weight_hash(
        rows, nbr.astype(np.int64).ravel(), seed, max_w=max_w
    ).reshape(n_pad, width).astype(np.float32)
    alive = np.arange(width, dtype=np.int64)[None, :] < deg[:, None]
    return np.where(alive, w, np.float32(np.inf))


def delta_stepping(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                   weights: np.ndarray, src: int, dst: int, *,
                   delta: float | None = None):
    """Exact single-source shortest path to ``dst`` by delta-stepping.

    Returns a :class:`~bibfs_tpu.query.types.WeightedResult`. ``delta``
    defaults to the mean edge weight (the standard heuristic; any
    positive value is exact, only the bucket count changes). Stops
    early once every remaining bucket's lower bound exceeds the best
    distance to ``dst`` — the s-t pruning the serving path wants."""
    from bibfs_tpu.query.types import WeightedResult

    t0 = time.perf_counter()
    src, dst = int(src), int(dst)
    if weights.shape[0] != col_ind.shape[0]:
        raise ValueError(
            f"weights misaligned: {weights.shape[0]} entries for "
            f"{col_ind.shape[0]} CSR slots"
        )
    if delta is None:
        delta = float(weights.mean()) if weights.size else 1.0
    delta = float(delta)
    if delta <= 0:
        raise ValueError(f"delta must be > 0, got {delta}")
    dist = np.full(n, _INF, dtype=np.float64)
    parent = np.full(n, -1, dtype=np.int64)
    dist[src] = 0.0
    light = weights <= delta
    buckets: dict[int, set] = {0: {src}}
    relaxations = 0
    processed = 0
    bi = 0
    while buckets:
        while bi not in buckets:
            bi += 1
            if bi > max(buckets):
                break
        if bi not in buckets:
            break
        if dist[dst] < bi * delta:
            break  # every remaining vertex is provably farther than dst
        settled: set = set()
        # light-edge phase: reinsertions within the bucket re-relax
        while buckets.get(bi):
            frontier = np.array(sorted(buckets.pop(bi)), dtype=np.int64)
            settled.update(int(v) for v in frontier)
            relaxations += _relax(
                frontier, row_ptr, col_ind, weights, light, dist,
                parent, buckets, delta, heavy=False,
            )
        # heavy-edge phase: once, from everything the bucket settled
        if settled:
            frontier = np.array(sorted(settled), dtype=np.int64)
            relaxations += _relax(
                frontier, row_ptr, col_ind, weights, light, dist,
                parent, buckets, delta, heavy=True,
            )
        processed += 1
        bi += 1
    found = bool(np.isfinite(dist[dst]))
    path = None
    if found:
        path = [dst]
        while path[-1] != src:
            path.append(int(parent[path[-1]]))
        path.reverse()
    return WeightedResult(
        found=found,
        dist=float(dist[dst]) if found else None,
        hops=len(path) - 1 if found else None,
        path=path,
        time_s=time.perf_counter() - t0,
        relaxations=relaxations,
        buckets=processed,
    )


def _relax(frontier, row_ptr, col_ind, weights, light, dist, parent,
           buckets, delta, *, heavy: bool) -> int:
    """Relax the light (or heavy) edges out of ``frontier``, moving
    improved vertices into their new buckets. Returns edges relaxed."""
    starts = row_ptr[frontier]
    counts = row_ptr[frontier + 1] - starts
    total = int(counts.sum())
    if total == 0:
        return 0
    offs = np.cumsum(counts) - counts
    src_pos = np.repeat(np.arange(frontier.size), counts)
    gather = (np.arange(total, dtype=np.int64) - offs[src_pos]
              + starts[src_pos])
    sel = ~light[gather] if heavy else light[gather]
    gather = gather[sel]
    if gather.size == 0:
        return 0
    src_pos = src_pos[sel]
    neigh = col_ind[gather]
    cand = dist[frontier[src_pos]] + weights[gather]
    better = cand < dist[neigh]
    neigh, cand = neigh[better], cand[better]
    par = frontier[src_pos[better]]
    # duplicate targets in one relax round: keep the minimum candidate
    # (np.minimum.at scatters all, then one pass recovers the winners)
    order = np.argsort(cand, kind="stable")
    neigh, cand, par = neigh[order], cand[order], par[order]
    uniq, first = np.unique(neigh, return_index=True)
    cand_u, par_u = cand[first], par[first]
    improve = cand_u < dist[uniq]
    uniq, cand_u, par_u = uniq[improve], cand_u[improve], par_u[improve]
    dist[uniq] = cand_u
    parent[uniq] = par_u
    for v, d in zip(uniq, cand_u):
        buckets.setdefault(int(d / delta), set()).add(int(v))
    return int(gather.size)


def dijkstra_numpy(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                   weights: np.ndarray, src: int,
                   dst: int | None = None):
    """The validation oracle: binary-heap Dijkstra, independent of the
    bucket machinery. Returns ``(dist, parent)`` float64/int64 arrays;
    with ``dst`` it stops once ``dst`` settles (exact — Dijkstra
    settles in distance order)."""
    dist = np.full(n, _INF, dtype=np.float64)
    parent = np.full(n, -1, dtype=np.int64)
    dist[int(src)] = 0.0
    heap = [(0.0, int(src))]
    while heap:
        d, u = heapq.heappop(heap)
        if d > dist[u]:
            continue  # stale heap entry
        if dst is not None and u == int(dst):
            break
        lo, hi = int(row_ptr[u]), int(row_ptr[u + 1])
        for i in range(lo, hi):
            v = int(col_ind[i])
            nd = d + float(weights[i])
            if nd < dist[v]:
                dist[v] = nd
                parent[v] = u
                heapq.heappush(heap, (nd, v))
    return dist, parent


def path_weight(row_ptr, col_ind, weights, path) -> float:
    """Sum of the path's edge weights (validation aid): each edge is
    located by binary search in its source's ascending CSR row."""
    total = 0.0
    for a, b in zip(path[:-1], path[1:]):
        lo, hi = int(row_ptr[a]), int(row_ptr[a + 1])
        row = col_ind[lo:hi]
        i = int(np.searchsorted(row, b))
        if i >= row.size or row[i] != b:
            raise ValueError(f"path edge ({a}, {b}) not in graph")
        total += float(weights[lo + i])
    return total
