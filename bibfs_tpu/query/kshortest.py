"""K shortest loopless paths — Yen's algorithm over the repaired-path
machinery.

Yen's is a host-tier query kind by nature: each candidate spur is one
restricted shortest-path solve (the base BFS with banned nodes and
banned spur edges), and the restriction set changes per spur — there
is no batch shape for a device program to amortize. The subroutine
here is the same deque-over-CSR level BFS the serial oracle runs, with
two masks threaded through: ``banned_nodes`` (the root prefix, so
candidates stay loopless) and ``banned_edges`` (the spur edges of
every accepted path sharing the root, so candidates are new). Results
are guaranteed loopless, distinct, and non-decreasing in hop count —
the properties the taxonomy tests pin edge-by-edge.
"""

from __future__ import annotations

import heapq
import time
from collections import deque

import numpy as np


def bfs_restricted(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                   src: int, dst: int, *,
                   banned_nodes=None, banned_edges=None):
    """Shortest path avoiding ``banned_nodes`` (bool[n] or set) and
    directed ``banned_edges`` (set of (u, v)); None = unrestricted.
    Returns the path ``[src..dst]`` or None. Deterministic: lowest CSR
    position wins, matching the serial solver's parent choice."""
    src, dst = int(src), int(dst)
    if banned_nodes is not None and not isinstance(banned_nodes, np.ndarray):
        mask = np.zeros(n, dtype=bool)
        for v in banned_nodes:
            mask[int(v)] = True
        banned_nodes = mask
    if banned_nodes is not None and (banned_nodes[src] or banned_nodes[dst]):
        return None
    if src == dst:
        return [src]
    parent = np.full(n, -1, dtype=np.int64)
    seen = np.zeros(n, dtype=bool)
    seen[src] = True
    if banned_nodes is not None:
        seen |= banned_nodes  # banned = never enqueue
        seen[src] = True
    q = deque([src])
    while q:
        u = q.popleft()
        row = col_ind[row_ptr[u]: row_ptr[u + 1]]
        for v in row:
            v = int(v)
            if seen[v]:
                continue
            if banned_edges is not None and (u, v) in banned_edges:
                continue
            parent[v] = u
            if v == dst:
                path = [dst]
                while path[-1] != src:
                    path.append(int(parent[path[-1]]))
                path.reverse()
                return path
            seen[v] = True
            q.append(v)
    return None


def yen_k_shortest(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                   src: int, dst: int, k: int):
    """Up to ``k`` shortest loopless ``src``->``dst`` paths, hop counts
    non-decreasing. Returns a
    :class:`~bibfs_tpu.query.types.KShortestResult`."""
    from bibfs_tpu.query.types import KShortestResult

    t0 = time.perf_counter()
    src, dst, k = int(src), int(dst), int(k)
    first = bfs_restricted(n, row_ptr, col_ind, src, dst)
    if first is None:
        return KShortestResult(
            found=False, paths=[], hops=[],
            time_s=time.perf_counter() - t0,
        )
    accepted = [first]
    seen_paths = {tuple(first)}
    candidates: list = []  # heap of (hops, tiebreak path, path)
    while len(accepted) < k:
        prev = accepted[-1]
        for i in range(len(prev) - 1):
            spur = prev[i]
            root = prev[: i + 1]
            banned_edges = set()
            for p in accepted:
                if len(p) > i and p[: i + 1] == root:
                    banned_edges.add((p[i], p[i + 1]))
            banned_nodes = set(root[:-1])  # root prefix minus the spur
            tail = bfs_restricted(
                n, row_ptr, col_ind, spur, dst,
                banned_nodes=banned_nodes, banned_edges=banned_edges,
            )
            if tail is None:
                continue
            cand = root[:-1] + tail
            key = tuple(cand)
            if key not in seen_paths:
                seen_paths.add(key)
                heapq.heappush(candidates, (len(cand) - 1, cand))
        if not candidates:
            break
        _hops, best = heapq.heappop(candidates)
        accepted.append(best)
    return KShortestResult(
        found=True,
        paths=accepted,
        hops=[len(p) - 1 for p in accepted],
        time_s=time.perf_counter() - t0,
    )
