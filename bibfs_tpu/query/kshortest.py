"""K shortest loopless paths — Yen's algorithm over restricted BFS
solves.

Yen's spur step is a RESTRICTED shortest-path solve: the base BFS with
banned nodes (the root prefix, so candidates stay loopless) and banned
spur edges (the spur edges of every accepted path sharing the root, so
candidates are new). The solve is split into two halves precisely so
the device tier can carry the expensive one:

- :func:`restricted_dists` — the restricted BFS *distance vector*
  (level-synchronous, completes the level that reaches ``dst`` and
  stops). This is the half the batched device kernel
  (:func:`bibfs_tpu.solvers.query_device.restricted_batch_dists`)
  replaces: one ``[n_pad, B]`` plane solves every spur candidate of a
  Yen iteration at once, each column under its own node mask.
- :func:`descend_min_id` — the CANONICAL path off a distance vector:
  from ``dst``, step to the lowest-id neighbor one level closer. Both
  tiers descend with this one rule on host, so the host rung and the
  batched device rung produce IDENTICAL paths — the identity the
  serve-layer parity gate pins, not just equal lengths.

``yen_k_shortest(..., spur_batch=)`` is the batching seam: the default
solves each candidate serially through :func:`bfs_restricted`; the
device rung passes a batch solver and every candidate of one iteration
rides one dispatch. Results are loopless, distinct, and non-decreasing
in hop count — the properties the taxonomy tests pin edge-by-edge.
"""

from __future__ import annotations

import heapq
import time

import numpy as np


def _banned_mask(n: int, banned_nodes) -> np.ndarray | None:
    if banned_nodes is None:
        return None
    if isinstance(banned_nodes, np.ndarray):
        return banned_nodes
    mask = np.zeros(n, dtype=bool)
    for v in banned_nodes:
        mask[int(v)] = True
    return mask


def first_hops(row_ptr: np.ndarray, col_ind: np.ndarray, src: int, *,
               banned_mask=None, banned_edges=None) -> np.ndarray:
    """The allowed level-1 frontier out of ``src``: its CSR row minus
    banned targets and banned ``(src, v)`` edges. Shared with the
    device kernel's host-side seeding — banned spur edges all leave
    the spur vertex, so filtering the first hop IS the whole edge
    restriction once the node mask holds elsewhere."""
    row = col_ind[row_ptr[src]: row_ptr[src + 1]]
    if banned_edges:
        row = np.asarray(
            [v for v in row if (src, int(v)) not in banned_edges],
            dtype=col_ind.dtype,
        )
    if banned_mask is not None and row.size:
        row = row[~banned_mask[row]]
    return row


def restricted_dists(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                     src: int, dst: int, *, banned_mask=None,
                     banned_edges=None) -> np.ndarray:
    """The restricted BFS distance vector (``int32 [n]``, -1 =
    unreached): level-synchronous sweep that COMPLETES the level which
    reaches ``dst`` and stops — every distance ``<= dist[dst]`` is
    final, which is all :func:`descend_min_id` reads. Banned edges not
    leaving ``src`` are honored too (general contract; Yen only bans
    spur-outgoing ones)."""
    src, dst = int(src), int(dst)
    dist = np.full(n, -1, dtype=np.int32)
    dist[src] = 0
    if src == dst:
        return dist
    general_bans = None
    if banned_edges:
        general_bans = {e for e in banned_edges if int(e[0]) != src}
    frontier = first_hops(
        row_ptr, col_ind, src,
        banned_mask=banned_mask, banned_edges=banned_edges,
    )
    frontier = frontier[dist[frontier] < 0]
    dist[frontier] = 1
    level = 1
    while frontier.size and dist[dst] < 0:
        level += 1
        starts = row_ptr[frontier]
        counts = row_ptr[frontier + 1] - starts
        total = int(counts.sum())
        if total == 0:
            break
        offs = np.cumsum(counts) - counts
        src_pos = np.repeat(np.arange(frontier.size), counts)
        gather = (np.arange(total, dtype=np.int64) - offs[src_pos]
                  + starts[src_pos])
        neigh = col_ind[gather]
        if general_bans:
            u_of = frontier[src_pos]
            keep = np.asarray([
                (int(u), int(v)) not in general_bans
                for u, v in zip(u_of, neigh)
            ])
            neigh = neigh[keep]
        cand = np.unique(neigh)
        cand = cand[dist[cand] < 0]
        if banned_mask is not None and cand.size:
            cand = cand[~banned_mask[cand]]
        dist[cand] = level
        frontier = cand
    return dist


def descend_min_id(row_ptr: np.ndarray, col_ind: np.ndarray,
                   dist: np.ndarray, src: int, dst: int, *,
                   banned_edges=None):
    """THE canonical path off a restricted distance vector: walk from
    ``dst`` down the gradient, picking the LOWEST-ID neighbor one
    level closer at every step (CSR rows are id-ascending, so the
    first hit wins). Deterministic and tier-independent — the host
    rung and the device rung descend identically, so equal distance
    vectors mean equal paths. ``banned_edges`` must be the restriction
    the vector was computed under: a banned ``(u, cur)`` step is
    skipped (the vector guarantees an allowed alternative exists —
    ``cur`` was only ever relaxed through allowed edges). Returns
    ``[src..dst]`` or None."""
    src, dst = int(src), int(dst)
    d = int(dist[dst])
    if d < 0:
        return None
    path = [dst]
    cur = dst
    for step in range(d, 0, -1):
        row = col_ind[row_ptr[cur]: row_ptr[cur + 1]]
        down = row[dist[row] == step - 1]
        if banned_edges:
            down = [
                u for u in down if (int(u), cur) not in banned_edges
            ]
        if len(down) == 0:  # cannot happen on a consistent vector
            return None
        cur = int(down[0])
        path.append(cur)
    path.reverse()
    return path


def bfs_restricted(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                   src: int, dst: int, *,
                   banned_nodes=None, banned_edges=None):
    """Shortest path avoiding ``banned_nodes`` (bool[n] or set) and
    directed ``banned_edges`` (set of (u, v)); None = unrestricted.
    Returns the path ``[src..dst]`` or None — the CANONICAL one
    (:func:`descend_min_id` over :func:`restricted_dists`), so every
    tier solving the same restriction reports the same path."""
    src, dst = int(src), int(dst)
    mask = _banned_mask(n, banned_nodes)
    if mask is not None and (mask[src] or mask[dst]):
        return None
    if src == dst:
        return [src]
    dist = restricted_dists(
        n, row_ptr, col_ind, src, dst,
        banned_mask=mask, banned_edges=banned_edges,
    )
    return descend_min_id(row_ptr, col_ind, dist, src, dst,
                          banned_edges=banned_edges)


def _spur_batch_host(n, row_ptr, col_ind, dst, cands):
    """The default (host) spur-candidate solver: one restricted BFS
    per candidate. ``cands`` is a list of ``(spur, banned_nodes set,
    banned_edges set)``; returns one tail-path-or-None per candidate."""
    return [
        bfs_restricted(
            n, row_ptr, col_ind, spur, dst,
            banned_nodes=banned_nodes, banned_edges=banned_edges,
        )
        for spur, banned_nodes, banned_edges in cands
    ]


def yen_k_shortest(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                   src: int, dst: int, k: int, *, spur_batch=None):
    """Up to ``k`` shortest loopless ``src``->``dst`` paths, hop counts
    non-decreasing. Returns a
    :class:`~bibfs_tpu.query.types.KShortestResult`.

    ``spur_batch(cands) -> [tail|None, ...]`` overrides how one Yen
    iteration's spur candidates solve (the device rung batches them
    through one restricted-BFS dispatch); answers must match the host
    solver's canonical paths, which the shared descent rule
    guarantees — so the ladder's rungs return IDENTICAL results."""
    from bibfs_tpu.query.types import KShortestResult

    t0 = time.perf_counter()
    src, dst, k = int(src), int(dst), int(k)
    if spur_batch is None:
        def spur_batch(cands):
            return _spur_batch_host(n, row_ptr, col_ind, dst, cands)
    first = bfs_restricted(n, row_ptr, col_ind, src, dst)
    if first is None:
        return KShortestResult(
            found=False, paths=[], hops=[],
            time_s=time.perf_counter() - t0,
        )
    accepted = [first]
    seen_paths = {tuple(first)}
    candidates: list = []  # heap of (hops, path)
    while len(accepted) < k:
        prev = accepted[-1]
        # collect the iteration's spur restrictions, then solve them
        # as ONE batch — the seam the device rung rides
        cands = []
        roots = []
        for i in range(len(prev) - 1):
            spur = prev[i]
            root = prev[: i + 1]
            banned_edges = set()
            for p in accepted:
                if len(p) > i and p[: i + 1] == root:
                    banned_edges.add((p[i], p[i + 1]))
            banned_nodes = set(root[:-1])  # root prefix minus the spur
            cands.append((spur, banned_nodes, banned_edges))
            roots.append(root)
        tails = spur_batch(cands)
        for root, tail in zip(roots, tails):
            if tail is None:
                continue
            cand = root[:-1] + tail
            key = tuple(cand)
            if key not in seen_paths:
                seen_paths.add(key)
                heapq.heappush(candidates, (len(cand) - 1, cand))
        if not candidates:
            break
        _hops, best = heapq.heappop(candidates)
        accepted.append(best)
    return KShortestResult(
        found=True,
        paths=accepted,
        hops=[len(p) - 1 for p in accepted],
        time_s=time.perf_counter() - t0,
    )
