"""Multi-source query answering over the bitmask-packed msBFS sweep.

:func:`bibfs_tpu.oracle.trees.multi_source_bfs` has carried the
oracle tier since PR 6 as an INDEX BUILDER — K landmark BFS trees in
one level-synchronous pass, one ``uint64`` reachability word per
vertex, the reference MPI version's bitset-frontier idea
(v2/second_try.cpp) word-packed and vectorized. This module promotes
it to a first-class ANSWERING primitive for the ``msbfs`` query kind:
one packed sweep computes all 64 sources' full distance vectors, so a
flush holding any number of :class:`~bibfs_tpu.query.types.MultiSource`
queries costs ``ceil(distinct_sources / 64)`` sweeps total — against
one full bidirectional solve per (source, dst) pair on the
point-to-point route. The per-query read afterwards is two array
lookups per source, and a shortest PATH for the best source falls out
of its distance vector by greedy descent (every vertex at distance d
has a neighbor at d-1, by BFS construction).
"""

from __future__ import annotations

import time

import numpy as np

from bibfs_tpu.query.types import MSBFS_WORD, MultiSourceResult


def path_from_dist(row_ptr: np.ndarray, col_ind: np.ndarray,
                   dist_col: np.ndarray, src: int, dst: int):
    """A shortest ``src``->``dst`` path recovered from the full
    distance vector ``dist_col`` (distances FROM ``src``; -1 =
    unreachable): walk from ``dst`` down the distance gradient. Cost
    O(hops * deg) — no parent array needed, which is exactly why the
    packed sweep (which stores none) can still answer with paths."""
    d = int(dist_col[dst])
    if d < 0:
        return None
    path = [int(dst)]
    cur = int(dst)
    for step in range(d, 0, -1):
        row = col_ind[row_ptr[cur]: row_ptr[cur + 1]]
        down = row[dist_col[row] == step - 1]
        if down.size == 0:  # cannot happen on a consistent vector
            return None
        cur = int(down[0])
        path.append(cur)
    path.reverse()
    return path


def solve_multi_source(n: int, row_ptr: np.ndarray, col_ind: np.ndarray,
                       queries, *, with_paths: bool = True,
                       dist_fn=None):
    """Answer a batch of :class:`MultiSource` queries with ONE packed
    sweep: the DISTINCT sources across the whole batch ride a single
    multi-word sweep (``ceil(distinct / 64)`` mask words per vertex —
    the K > 64 case is one wider pass, not a loop of 64-wide ones),
    then every query reads its ``(source, dst)`` cells from the shared
    distance plane — one contiguous ``plane[dst]`` row read per query,
    not a strided column per source. Returns one
    :class:`~bibfs_tpu.query.types.MultiSourceResult` per query.

    ``dist_fn(sources) -> int16 [n, K]`` overrides the sweep
    implementation — the device rung
    (:class:`~bibfs_tpu.serve.routes.taxonomy_device.MsbfsDeviceRoute`)
    passes the jitted kernel over its uploaded table; the default is
    the host NumPy sweep. ``sweeps`` in the results stays in 64-source
    sweep units (the amortization figure the metrics report)."""
    from bibfs_tpu.oracle.trees import multi_source_bfs

    t0 = time.perf_counter()
    col_of: dict[int, int] = {}
    first = queries[0].sources if queries else ()
    shared = all(
        q.sources is first or q.sources == first for q in queries
    )
    if shared:
        # the serving shape: one shared source set across the flush
        # (64-source traffic) — index it once, not per (query, source)
        col_of = {int(s): i for i, s in enumerate(first)}
    if not shared or len(col_of) != len(first):
        # distinct sources per query, or a DUPLICATE inside the shared
        # tuple (validate() allows it): positional indexing would read
        # past the deduped plane — take the deduping walk instead
        col_of = {}
        distinct = []
        for q in queries:
            for s in q.sources:
                s = int(s)
                if s not in col_of:
                    col_of[s] = len(distinct)
                    distinct.append(s)
    else:
        distinct = list(col_of)
    src_arr = np.asarray(distinct, dtype=np.int64)
    if dist_fn is None:
        plane = multi_source_bfs(n, row_ptr, col_ind, src_arr)
    else:
        plane = dist_fn(src_arr)
    sweeps = -(-len(distinct) // MSBFS_WORD)
    elapsed = time.perf_counter() - t0

    def col(s: int) -> np.ndarray:
        return plane[:, col_of[int(s)]]

    out = []
    for q in queries:
        dst = int(q.dst)
        row = plane[dst]
        per = tuple(
            (lambda d: None if d < 0 else int(d))(int(row[col_of[int(s)]]))
            for s in q.sources
        )
        best = None
        for i, h in enumerate(per):
            if h is not None and (best is None or h < per[best]):
                best = i
        path = None
        if best is not None and with_paths:
            path = path_from_dist(
                row_ptr, col_ind, col(q.sources[best]),
                int(q.sources[best]), dst,
            )
        out.append(MultiSourceResult(
            found=best is not None,
            per_source=per,
            best=best,
            hops=per[best] if best is not None else None,
            path=path,
            time_s=elapsed,
            sweeps=sweeps,
        ))
    return out
