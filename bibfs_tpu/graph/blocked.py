"""Blocked (tiled) adjacency — the MXU-native layout.

BLEST and "Graph Traversal on Tensor Cores" (PAPERS.md) reformulate a
BFS level as blocked masked matrix products over a tiled adjacency; on
TPU the analogous statement is that a level's frontier expansion

    next[u] = OR_v  A[u, v] AND frontier[v]

is a boolean matrix-vector product — and a *batched* level over B
queries is a boolean matrix-MATRIX product ``A @ F`` with ``F`` the
``[n, B]`` frontier plane, which is exactly the ``128 x 128``
systolic-array workload the MXU runs at full rate while the ELL
gather-based expansion (``ops/expand.py``, ``solvers/batch_minor.py``)
issues element-at-a-time loads. The trade is arithmetic for locality:
the blocked product touches ``tile`` candidate neighbors per vertex per
stored block instead of ``width`` ELL slots, so it wins exactly on
dense-ish and banded (grid) graphs where the nonempty-tile structure is
compact — the eligibility/adaptive layer (``serve/routes/blocked.py``,
``serve/policy.py``) owns that routing decision.

Layout (block-sparse, only nonempty tiles materialized):

- the vertex space is padded to ``tile`` (=128, the MXU edge) and cut
  into ``nblocks`` tile-rows x tile-cols;
- a tile (bi, bj) is *nonempty* when any edge (u, v) has
  ``u // tile == bi`` and ``v // tile == bj`` (pairs are canonical —
  mirrored — so the tile structure is symmetric);
- nonempty tiles are packed ELL-style per block row: ``bcol[bi, k]``
  is the k-th nonempty tile's block-column (sentinel ``nblocks`` past
  ``bwidth_row[bi]``), and ``tab[bi, k]`` is its dense ``tile x tile``
  int8 0/1 adjacency — int8 is the native MXU input dtype (the Pallas
  guide's (32, 128) int8 tiling), and the storage format whatever
  plane dtype the kernel resolves per substrate
  (:func:`bibfs_tpu.ops.blocked_expand.resolve_plane_dtype`).

This is CSR-of-blocks flattened to ELL-of-blocks: ``bwidth`` is the max
nonempty tiles in any block row, so the device table is one static
``[nblocks, bwidth, tile, tile]`` array and the per-level product needs
no data-dependent shapes. Empty block ROWS (isolated/pad vertices) are
all-sentinel and contribute zero, like every other padding here.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from bibfs_tpu.graph.csr import canonical_pairs

#: the MXU systolic-array edge; also the lane quantum, so [tile, B]
#: frontier sub-planes are whole vector registers
TILE = 128


@dataclasses.dataclass
class BlockedGraph:
    """Host-side blocked adjacency (module docstring).

    - ``tab``: int8 ``[nblocks, bwidth, tile, tile]`` — slot k of block
      row bi is the dense adjacency tile against block column
      ``bcol[bi, k]`` (all-zero for sentinel slots).
    - ``bcol``: int32 ``[nblocks, bwidth]`` block-column indices,
      sentinel ``nblocks`` for dead slots (the kernel pads the frontier
      plane with one zero tile at index ``nblocks``).
    - ``deg``: int32 ``[n_pad]`` true degrees (edge-scan accounting).
    """

    n: int
    n_pad: int
    tile: int
    nblocks: int
    bwidth: int
    num_edges: int  # undirected unique edge count
    nnz_blocks: int  # nonempty tiles actually materialized
    tab: np.ndarray
    bcol: np.ndarray
    deg: np.ndarray

    @property
    def tab_bytes(self) -> int:
        return int(self.tab.nbytes)

    @property
    def block_density(self) -> float:
        """Fraction of the full block grid actually materialized."""
        return self.nnz_blocks / float(self.nblocks * self.nblocks or 1)


def _tile_grid(n: int, tile: int) -> tuple[int, int]:
    """``(n_pad, nblocks)`` of the tile grid — the ONE place the
    padding formula lives (build, meta precheck and the serving
    eligibility gate must agree on the grid by construction)."""
    tile = int(tile)
    n_pad = max(tile, -(-int(n) // tile) * tile)
    return n_pad, n_pad // tile


def blocked_meta(n: int, pairs: np.ndarray, *,
                 tile: int = TILE) -> tuple[int, int, int]:
    """``(nblocks, bwidth, nnz_blocks)`` of the tiling WITHOUT
    materializing the table — one sorted pass over the canonical
    pairs. The serving route's eligibility precheck reads this, so it
    shares the grid/key math with :func:`build_blocked` and can never
    gate on a different layout than the one a routed flush builds."""
    tile = int(tile)
    _n_pad, nblocks = _tile_grid(n, tile)
    if pairs is None or not pairs.size:
        return nblocks, 1, 0
    keys = np.unique(
        (pairs[:, 0] // tile) * nblocks + pairs[:, 1] // tile
    )
    counts = np.bincount(keys // nblocks, minlength=nblocks)
    return nblocks, max(1, int(counts.max())), int(keys.size)


def build_blocked(
    n: int,
    edges: np.ndarray | None = None,
    *,
    pairs: np.ndarray | None = None,
    tile: int = TILE,
) -> BlockedGraph:
    """Tile the canonical pairs into a :class:`BlockedGraph`.

    Fully vectorized: one sort over the directed pairs' (block-row,
    block-col) keys yields the nonempty-tile list, per-row slot ranks
    and the scatter into ``tab`` without a Python loop over tiles."""
    if pairs is None:
        pairs = canonical_pairs(n, edges)
    tile = int(tile)
    n_pad, nblocks = _tile_grid(n, tile)
    deg = np.zeros(n_pad, dtype=np.int32)
    if pairs.size:
        deg[:n] = np.bincount(pairs[:, 0], minlength=n)
    if not pairs.size:
        return BlockedGraph(
            n=int(n), n_pad=n_pad, tile=tile, nblocks=nblocks, bwidth=1,
            num_edges=0, nnz_blocks=0,
            tab=np.zeros((nblocks, 1, tile, tile), dtype=np.int8),
            bcol=np.full((nblocks, 1), nblocks, dtype=np.int32),
            deg=deg,
        )
    br = pairs[:, 0] // tile
    bc = pairs[:, 1] // tile
    keys = br * nblocks + bc
    # nonempty tiles + each directed pair's tile, in one sorted pass
    uniq, inv = np.unique(keys, return_inverse=True)
    rows = (uniq // nblocks).astype(np.int64)
    cols = (uniq % nblocks).astype(np.int64)
    counts = np.bincount(rows, minlength=nblocks)
    bwidth = max(1, int(counts.max()))
    # slot rank of each nonempty tile within its block row (uniq is
    # sorted, so tiles of one row are consecutive)
    row_start = np.zeros(nblocks + 1, dtype=np.int64)
    np.cumsum(counts, out=row_start[1:])
    slot = np.arange(uniq.size) - row_start[rows]
    bcol = np.full((nblocks, bwidth), nblocks, dtype=np.int32)
    bcol[rows, slot] = cols
    tab = np.zeros((nblocks, bwidth, tile, tile), dtype=np.int8)
    tab[br, slot[inv], pairs[:, 0] % tile, pairs[:, 1] % tile] = 1
    return BlockedGraph(
        n=int(n), n_pad=n_pad, tile=tile, nblocks=nblocks, bwidth=bwidth,
        num_edges=int(pairs.shape[0]) // 2, nnz_blocks=int(uniq.size),
        tab=tab, bcol=bcol, deg=deg,
    )


def build_blocked_weights(g: BlockedGraph, pairs: np.ndarray, *,
                          seed: int = 0) -> np.ndarray:
    """The float32 ``[nblocks, bwidth, tile, tile]`` WEIGHT table over
    ``g``'s tiling: the seeded symmetric edge-weight hash
    (:func:`bibfs_tpu.query.weighted.edge_weight_hash`) at every stored
    edge's slot, ``+inf`` everywhere else — the (min, +) semiring's
    absent-edge identity, so dead slots and sentinel tiles never win a
    min. Live entries hash identically to ``synthetic_weights`` over
    the same snapshot (the canonical (min, max) pair), which is what
    pins the blocked SSSP rung to the host/Dijkstra answers."""
    from bibfs_tpu.query.weighted import edge_weight_hash

    wtab = np.full(
        (g.nblocks, g.bwidth, g.tile, g.tile), np.inf, dtype=np.float32
    )
    if pairs is None or not pairs.size:
        return wtab
    br = pairs[:, 0] // g.tile
    bc = pairs[:, 1] // g.tile
    # dense (block row, block col) -> slot map; sentinel column writes
    # land at index nblocks and are never looked up by a real pair
    slot_map = np.full((g.nblocks, g.nblocks + 1), -1, dtype=np.int64)
    slot_map[
        np.arange(g.nblocks)[:, None], g.bcol
    ] = np.arange(g.bwidth)[None, :]
    w = edge_weight_hash(
        pairs[:, 0].astype(np.int64), pairs[:, 1].astype(np.int64), seed
    )
    wtab[br, slot_map[br, bc], pairs[:, 0] % g.tile,
         pairs[:, 1] % g.tile] = w.astype(np.float32)
    return wtab


def blocked_bucket_key(g: BlockedGraph) -> tuple:
    """The compiled-program shape identity of a blocked table — the
    analog of :func:`bibfs_tpu.serve.buckets.ell_bucket_key` for the
    blocked layout. Distinct by construction from the ``("ell", ...)``
    single-device keys and extended with its placement via
    ``placement_bucket_key(kind="blocked")`` at the dispatch site, so a
    blocked program can never count as a hit on a device/mesh
    executable of the same padded vertex shape."""
    return ("blocked", g.nblocks, g.bwidth, g.tile)
