from bibfs_tpu.graph.io import (  # noqa: F401
    read_graph_bin,
    write_graph_bin,
    read_ground_truth,
    write_ground_truth,
)
from bibfs_tpu.graph.csr import build_csr, build_ell, EllGraph  # noqa: F401
from bibfs_tpu.graph.generate import gnp_random_graph, rmat_graph  # noqa: F401
from bibfs_tpu.graph.suite import make_suite  # noqa: F401
