"""Varint+delta compressed CSR adjacency — the cold-tier layout.

The "Compression and Sieve" observation (PAPERS.md): at memory-tier
scale the constraint is footprint and bandwidth, not FLOPs — a graph
that is not currently serving hot traffic should not pin O(E) of
int64 neighbor ids in RAM. Canonical CSR neighbor lists are sorted
ascending within each row (``canonical_pairs`` sorts by ``(u, v)``),
so the classic web-graph encoding applies directly:

- **delta**: within a row, store the first neighbor as its absolute id
  and every later one as the gap to its predecessor (``>= 1`` after
  dedup — small for clustered/local graphs, bounded by ``n`` always);
- **varint**: each value as 1–5 little-endian 7-bit groups with a
  continuation high bit (LEB128), so the common small gaps cost one
  byte instead of eight.

``row_ptr`` stays raw int64 (``n+1`` entries — the neighbor stream at
``2E`` entries dominates it 2·avg_deg:1 in int64, more after
compression), which keeps per-row random access trivial: row ``u``'s
values are the ``row_ptr[u+1]-row_ptr[u]`` varints starting at the
``row_ptr[u]``-th encoded value. Both encode and decode are
NumPy-vectorized (no per-edge Python): byte lengths by thresholds +
``cumsum`` offsets on the way in; continuation-bit scan + at most 5
masked shift/or passes + a segmented ``cumsum`` un-delta on the way
out. The decode is benched in ``bench.py --serve-memtier`` (the
promote path's cost is a gate input, not a guess).

The round-trip is exact by construction and property-tested over
random/grid/RMAT graphs in ``tests/test_compress.py``; the store's
residency accountant (``store/registry.py``) is the consumer: a graph
demoted past the residency budget keeps only this object plus its
``row_ptr``, and a promote decodes back to the identical CSR.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: LEB128 group-count thresholds: value < _THRESH[k] needs k+1 bytes.
#: 5 groups cover 35 bits — vertex ids are < 2**31 by the on-disk
#: uint32 contract (graph/io.py), so gaps always fit.
_THRESH = tuple(np.int64(1) << (7 * k) for k in range(1, 6))
_MAX_GROUPS = 5


@dataclasses.dataclass(frozen=True)
class CompressedCSR:
    """One graph's cold-tier adjacency: raw ``row_ptr`` + the
    varint+delta neighbor stream (module docstring)."""

    n: int
    nnz: int  # directed entries (2E for the mirrored canonical CSR)
    row_ptr: np.ndarray  # int64 [n+1]
    data: np.ndarray  # uint8 varint stream

    @property
    def compressed_bytes(self) -> int:
        return int(self.data.nbytes + self.row_ptr.nbytes)

    @property
    def raw_bytes(self) -> int:
        """What the decoded (row_ptr, col_ind) pair costs resident."""
        return int(self.row_ptr.nbytes + 8 * self.nnz)

    @property
    def ratio(self) -> float:
        """Raw/compressed — > 1 is a win; the neighbor stream alone
        typically lands 4–8x on clustered graphs."""
        return self.raw_bytes / float(self.compressed_bytes or 1)

    def stats(self) -> dict:
        return {
            "n": self.n,
            "nnz": self.nnz,
            "compressed_bytes": self.compressed_bytes,
            "raw_bytes": self.raw_bytes,
            "ratio": round(self.ratio, 3),
        }


def _deltas(row_ptr: np.ndarray, col_ind: np.ndarray) -> np.ndarray:
    """Within-row deltas: first neighbor absolute, rest gaps — all
    non-negative because canonical rows are sorted ascending."""
    vals = np.ascontiguousarray(col_ind, dtype=np.int64).copy()
    if vals.size:
        vals[1:] -= col_ind[:-1]
        starts = np.asarray(row_ptr[:-1], dtype=np.int64)
        starts = starts[starts < vals.size]  # trailing empty rows
        vals[starts] = col_ind[starts]
    if vals.size and int(vals.min()) < 0:
        raise ValueError(
            "CSR rows must be sorted ascending (canonical_pairs order) "
            "to delta-encode"
        )
    return vals


def encode_csr(row_ptr: np.ndarray, col_ind: np.ndarray) -> CompressedCSR:
    """Encode one canonical CSR into the cold-tier layout (vectorized)."""
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    n = int(row_ptr.shape[0]) - 1
    nnz = int(row_ptr[-1]) if row_ptr.size else 0
    if nnz != int(np.asarray(col_ind).shape[0]):
        raise ValueError(
            f"row_ptr claims {nnz} entries but col_ind has "
            f"{np.asarray(col_ind).shape[0]}"
        )
    vals = _deltas(row_ptr, col_ind)
    # bytes per value by threshold comparison (k+1 groups when
    # value >= 2**(7k)); values are non-negative so 5 groups suffice
    nbytes = np.ones(vals.shape[0], dtype=np.int64)
    for t in _THRESH[:-1]:
        nbytes += vals >= t
    offsets = np.zeros(vals.shape[0] + 1, dtype=np.int64)
    np.cumsum(nbytes, out=offsets[1:])
    data = np.zeros(int(offsets[-1]), dtype=np.uint8)
    for k in range(_MAX_GROUPS):
        sel = nbytes > k
        if not sel.any():
            break
        group = ((vals[sel] >> (7 * k)) & 0x7F).astype(np.uint8)
        cont = (nbytes[sel] > k + 1).astype(np.uint8) << 7
        data[offsets[:-1][sel] + k] = group | cont
    return CompressedCSR(n=n, nnz=nnz, row_ptr=row_ptr, data=data)


def decode_csr(c: CompressedCSR) -> tuple[np.ndarray, np.ndarray]:
    """Decode back to the exact ``(row_ptr, col_ind)`` pair
    (vectorized; module docstring). Raises ``ValueError`` on a stream
    whose varint count disagrees with ``row_ptr`` — a truncated or
    foreign byte stream must fail loudly, never decode approximately."""
    data = np.ascontiguousarray(c.data, dtype=np.uint8)
    row_ptr = np.ascontiguousarray(c.row_ptr, dtype=np.int64)
    if c.nnz == 0:
        return row_ptr, np.zeros(0, dtype=np.int64)
    ends = np.flatnonzero((data & 0x80) == 0)
    if ends.size != c.nnz:
        raise ValueError(
            f"varint stream holds {ends.size} values; row_ptr claims "
            f"{c.nnz}"
        )
    starts = np.empty_like(ends)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    lengths = ends - starts + 1
    if int(lengths.max()) > _MAX_GROUPS:
        raise ValueError(
            f"varint longer than {_MAX_GROUPS} groups — not a value "
            "this encoder produced"
        )
    vals = np.zeros(c.nnz, dtype=np.int64)
    for k in range(int(lengths.max())):
        sel = lengths > k
        vals[sel] |= (data[starts[sel] + k] & 0x7F).astype(np.int64) << (7 * k)
    # segmented un-delta: absolute id = within-row prefix sum of deltas
    cs = np.cumsum(vals)
    before = np.concatenate((np.zeros(1, dtype=np.int64), cs))[row_ptr[:-1]]
    col = cs - np.repeat(before, np.diff(row_ptr))
    return row_ptr, col


def encode_snapshot_csr(snapshot) -> CompressedCSR:
    """Encode a :class:`~bibfs_tpu.store.snapshot.GraphSnapshot`'s CSR
    — the residency accountant's demote step (the snapshot's memoized
    builder supplies the canonical CSR whatever tier it is in)."""
    row_ptr, col_ind = snapshot.csr()
    return encode_csr(row_ptr, col_ind)
