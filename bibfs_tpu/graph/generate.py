"""Graph generators: G(n, p) and RMAT, NumPy-vectorized (no NetworkX).

Reference contract: ``generate_graph.py --n --p --src --dst --out`` builds
``nx.fast_gnp_random_graph(N, P)`` (graphs/generate_graph.py:31), writes the
binary edge list (35-39) and a ground-truth JSON with the true shortest path
(42-62). The reference README's own limitation note (README.md:19) says
NetworkX cannot reach 10M-node graphs; these generators are O(M) vectorized
NumPy and do reach them (RMAT scale-23 per BASELINE.json configs).

Ground truth here is computed by this framework's serial oracle solver and
cross-validated against NetworkX in the test suite.
"""

from __future__ import annotations

import argparse

import numpy as np

# The reference suite's average degree (graphs/make_graphs:8) — the odd
# epsilon is reproduced verbatim so regenerated suites match its p exactly.
DEFAULT_AVG_DEG = 2.2000000001


def _linear_to_upper_pair(k: np.ndarray, n: int) -> tuple[np.ndarray, np.ndarray]:
    """Map linear indices over the upper triangle {(i, j): i < j}, ordered by
    row then column, back to (i, j). Float solve + integer correction."""
    k = k.astype(np.int64)
    twon1 = 2 * n - 1
    i = np.floor((twon1 - np.sqrt(np.maximum(twon1 * twon1 - 8.0 * k, 0.0))) / 2.0)
    i = i.astype(np.int64)
    i = np.clip(i, 0, n - 2)

    def start(i):
        return i * n - (i * (i + 1)) // 2

    for _ in range(4):  # fix float rounding, ±2 at most
        i = np.where(start(i + 1) <= k, i + 1, i)
        i = np.where(start(i) > k, i - 1, i)
        i = np.clip(i, 0, n - 2)
    j = i + 1 + (k - start(i))
    return i, j


def gnp_random_graph(
    n: int, p: float, *, seed: int | None = None
) -> np.ndarray:
    """Sample G(n, p) as an ``(M, 2)`` unique undirected edge array.

    Exact in distribution: M ~ Binomial(C(n,2), p), then M distinct pairs
    uniformly without replacement (equivalent to per-pair Bernoulli(p)).
    O(M) memory/time — unlike a dense matrix, works for n in the millions.
    """
    rng = np.random.default_rng(seed)
    total = n * (n - 1) // 2
    if total == 0 or p <= 0:
        return np.zeros((0, 2), dtype=np.int64)
    m = int(rng.binomial(total, min(p, 1.0))) if p < 1.0 else total
    picks = np.zeros(0, dtype=np.int64)
    while picks.size < m:
        need = m - picks.size
        # scale the batch by the expected collision rate against both the
        # already-picked set and intra-batch duplicates, so dense p doesn't
        # degrade into many tiny rounds of full re-unique
        remaining_frac = max(1.0 - picks.size / total, 1e-9)
        batch = int(need / remaining_frac * 1.1) + 16
        cand = rng.integers(0, total, size=batch, dtype=np.int64)
        picks = np.unique(np.concatenate([picks, cand]))
    if picks.size > m:
        picks = rng.permutation(picks)[:m]
    i, j = _linear_to_upper_pair(picks, n)
    return np.stack([i, j], axis=1)


def grid_graph(
    width: int, height: int, *, perforation: float = 0.0,
    seed: int | None = None,
) -> np.ndarray:
    """``width x height`` 4-neighbor lattice as an ``(M, 2)`` edge array
    (row-major vertex ids, ``n = width * height`` for the caller).

    The road-network-shaped serving graph: large diameter
    (``width + height - 2``), so a point-to-point BFS pays a real
    frontier sweep — the workload landmark/ALT distance oracles were
    invented for (and the opposite regime from G(n, p)'s
    log-diameter small worlds, where bidirectional BFS meets after a
    handful of levels). ``perforation`` removes that fraction of lattice
    edges uniformly at random (seeded): detours around the holes break
    the perfect lattice's geodesic regularity so oracle bounds are
    exercised, not just trivially tight.
    """
    if width < 1 or height < 1:
        raise ValueError(f"grid needs positive dims, got {width}x{height}")
    vid = np.arange(width * height, dtype=np.int64).reshape(height, width)
    e_right = np.stack([vid[:, :-1].ravel(), vid[:, 1:].ravel()], axis=1)
    e_down = np.stack([vid[:-1, :].ravel(), vid[1:, :].ravel()], axis=1)
    edges = np.concatenate([e_right, e_down])
    if perforation > 0:
        rng = np.random.default_rng(seed)
        edges = edges[rng.random(len(edges)) >= float(perforation)]
    return edges


def rmat_graph(
    scale: int,
    edge_factor: int = 16,
    *,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int | None = None,
    dedup: bool = True,
) -> tuple[int, np.ndarray]:
    """Graph500-style RMAT generator. Returns ``(n, edges)`` with n = 2**scale.

    Kronecker recursive quadrant sampling, vectorized over all edges per bit
    level (scale iterations over length-M arrays).
    """
    rng = np.random.default_rng(seed)
    n = 1 << scale
    m = n * edge_factor
    row = np.zeros(m, dtype=np.int64)
    col = np.zeros(m, dtype=np.int64)
    ab, abc = a + b, a + b + c
    for _ in range(scale):
        u = rng.random(m)
        row_bit = u >= ab
        col_bit = ((u >= a) & (u < ab)) | (u >= abc)
        row = (row << 1) | row_bit
        col = (col << 1) | col_bit
    edges = np.stack([row, col], axis=1)
    edges = edges[edges[:, 0] != edges[:, 1]]
    if dedup:
        lo = np.minimum(edges[:, 0], edges[:, 1])
        hi = np.maximum(edges[:, 0], edges[:, 1])
        keys = np.unique(lo * n + hi)
        edges = np.stack([keys // n, keys % n], axis=1)
    return n, edges


def _merge_sorted_disjoint(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Merge two sorted, element-disjoint uint64 arrays in O(|a| + |b|)
    (one scatter, no per-chunk re-sort of the accumulated set)."""
    if b.size == 0:
        return a
    if a.size == 0:
        return b
    out = np.empty(a.size + b.size, dtype=np.uint64)
    pos = np.searchsorted(a, b) + np.arange(b.size, dtype=np.int64)
    mask = np.zeros(out.size, dtype=bool)
    mask[pos] = True
    out[mask] = b
    out[~mask] = a
    return out


def rmat_stream_bin(
    out_path: str,
    scale: int,
    edge_factor: int = 16,
    *,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int | None = None,
    chunk_edges: int = 1 << 22,
) -> dict:
    """Stream a Graph500-style RMAT graph straight to the ``.bin`` format
    without materializing the edge list.

    Same distribution as :func:`rmat_graph` (Kronecker quadrant sampling
    per chunk), but the ``n * edge_factor`` raw samples are drawn in
    fixed-size chunks, canonicalized (``lo < hi``), self-loop-dropped and
    EXACTLY deduplicated globally: each chunk's packed
    ``(lo << 32) | hi`` keys are filtered against (then merged into) an
    incrementally-maintained sorted uint64 key set, so the output is
    duplicate-free across chunk boundaries — not just within a chunk.
    Peak memory is the key set (8 bytes per surviving edge) plus one
    chunk, roughly half of what the materialized int64 edge array costs,
    and the output file is committed atomically by
    :func:`~bibfs_tpu.graph.io.stream_graph_bin`.

    Returns ``{"n", "m", "raw", "self_loops", "dupes"}``.
    """
    from bibfs_tpu.graph.io import stream_graph_bin

    if not 1 <= scale <= 31:
        raise ValueError(f"scale must be in [1, 31] (uint32 ids), got {scale}")
    rng = np.random.default_rng(seed)
    n = 1 << scale
    m_target = n * edge_factor
    ab, abc = a + b, a + b + c
    seen = np.zeros(0, dtype=np.uint64)
    stats = {"raw": 0, "self_loops": 0, "dupes": 0}

    def chunks():
        nonlocal seen
        remaining = m_target
        while remaining > 0:
            csize = min(chunk_edges, remaining)
            remaining -= csize
            stats["raw"] += csize
            row = np.zeros(csize, dtype=np.int64)
            col = np.zeros(csize, dtype=np.int64)
            for _ in range(scale):
                u = rng.random(csize)
                row_bit = u >= ab
                col_bit = ((u >= a) & (u < ab)) | (u >= abc)
                row = (row << 1) | row_bit
                col = (col << 1) | col_bit
            keep = row != col
            stats["self_loops"] += int(csize - keep.sum())
            row, col = row[keep], col[keep]
            lo = np.minimum(row, col).astype(np.uint64)
            hi = np.maximum(row, col).astype(np.uint64)
            keys = np.unique((lo << np.uint64(32)) | hi)
            if seen.size:
                idx = np.minimum(np.searchsorted(seen, keys), seen.size - 1)
                keys = keys[seen[idx] != keys]
            stats["dupes"] += int(row.size - keys.size)
            seen = _merge_sorted_disjoint(seen, keys)
            out = np.empty((keys.size, 2), dtype=np.int64)
            out[:, 0] = (keys >> np.uint64(32)).astype(np.int64)
            out[:, 1] = (keys & np.uint64(0xFFFFFFFF)).astype(np.int64)
            yield out

    m = stream_graph_bin(out_path, n, chunks())
    assert m == seen.size
    return {"n": n, "m": m, **stats}


def generate_with_ground_truth(
    out_path: str,
    n: int,
    p: float,
    src: int,
    dst: int | None = None,
    *,
    seed: int | None = None,
) -> dict:
    """Reference ``generate_graph.py`` parity: write .bin + ground-truth .json."""
    from bibfs_tpu.graph.io import (
        ground_truth_path,
        write_graph_bin,
        write_ground_truth,
    )
    from bibfs_tpu.solvers.serial import solve_serial

    if dst is None:
        dst = n - 1
    edges = gnp_random_graph(n, p, seed=seed)
    write_graph_bin(out_path, n, edges)
    res = solve_serial(n, edges, src, dst)
    write_ground_truth(
        ground_truth_path(out_path),
        src,
        dst,
        res.hops if res.found else None,
        res.path if res.found else None,
    )
    return {
        "n": n,
        "m": int(edges.shape[0]),
        "hop_count": res.hops if res.found else None,
    }


def rmat_with_ground_truth(
    out_path: str,
    scale: int,
    edge_factor: int = 16,
    src: int = 0,
    dst: int | None = None,
    *,
    seed: int | None = None,
) -> dict:
    """RMAT suite row (BASELINE.json 'RMAT scale-23 / Graph500' config):
    write .bin + ground-truth .json like the G(n,p) generator."""
    from bibfs_tpu.graph.io import (
        ground_truth_path,
        write_graph_bin,
        write_ground_truth,
    )
    from bibfs_tpu.solvers.serial import solve_serial

    n, edges = rmat_graph(scale, edge_factor, seed=seed)
    if dst is None:
        dst = n - 1
    write_graph_bin(out_path, n, edges)
    res = solve_serial(n, edges, src, dst)
    write_ground_truth(
        ground_truth_path(out_path),
        src,
        dst,
        res.hops if res.found else None,
        res.path if res.found else None,
    )
    return {
        "n": n,
        "m": int(edges.shape[0]),
        "hop_count": res.hops if res.found else None,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description="Generate a random graph + ground truth")
    ap.add_argument("--n", type=int, default=None, help="vertex count (gnp)")
    ap.add_argument("--p", type=float, default=None, help="edge probability (gnp)")
    ap.add_argument(
        "--rmat-scale",
        type=int,
        default=None,
        help="generate a Graph500-style RMAT graph with 2**scale vertices "
        "instead of G(n, p)",
    )
    ap.add_argument(
        "--edge-factor", type=int, default=16, help="RMAT edges per vertex"
    )
    ap.add_argument(
        "--stream",
        action="store_true",
        help="RMAT only: stream chunks straight to the .bin (bounded "
        "memory, exact global dedup) instead of materializing the edge "
        "list; skips the ground-truth JSON",
    )
    ap.add_argument("--src", type=int, default=0)
    ap.add_argument("--dst", type=int, default=None, help="default n-1")
    ap.add_argument("--out", type=str, required=True)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--avg-deg", type=float, default=None, help="sets p = avg_deg / n")
    args = ap.parse_args(argv)
    if (args.rmat_scale is None) == (args.n is None):
        ap.error("exactly one of --n (gnp) or --rmat-scale (RMAT) is required")
    if args.rmat_scale is not None and (
        args.p is not None or args.avg_deg is not None
    ):
        ap.error("--p/--avg-deg apply to gnp only; use --edge-factor with RMAT")
    if args.n is not None and args.edge_factor != 16:
        ap.error("--edge-factor applies to RMAT only; use --p/--avg-deg with gnp")
    if args.stream and args.rmat_scale is None:
        ap.error("--stream applies to RMAT only (needs --rmat-scale)")
    if args.stream:
        info = rmat_stream_bin(
            args.out, args.rmat_scale, args.edge_factor, seed=args.seed
        )
        info = {**info, "hop_count": None}
    elif args.rmat_scale is not None:
        info = rmat_with_ground_truth(
            args.out,
            args.rmat_scale,
            args.edge_factor,
            args.src,
            args.dst,
            seed=args.seed,
        )
    else:
        avg = args.avg_deg if args.avg_deg is not None else DEFAULT_AVG_DEG
        p = args.p if args.p is not None else avg / args.n
        info = generate_with_ground_truth(
            args.out, args.n, p, args.src, args.dst, seed=args.seed
        )
    print(
        f"wrote {args.out}: n={info['n']} m={info['m']} hop_count={info['hop_count']}"
    )


if __name__ == "__main__":
    main()
