"""Benchmark-suite generator — reference ``graphs/make_graphs`` parity.

The reference driver (graphs/make_graphs:13-32) generates four G(n, p)
graphs with N ∈ {1000, 10000, 50000, 100000}, p = 2.2000000001/N, src=0,
dst=N−1, writing ``<label>.bin`` + ground-truth ``<label>.json``. Same
contract here, plus optional RMAT rows (``--rmat SCALE...``) for the
Graph500-style configs the reference could never generate
(README.md:19; BASELINE.json configs).
"""

from __future__ import annotations

import argparse
import os

from bibfs_tpu.graph.generate import (
    DEFAULT_AVG_DEG,
    generate_with_ground_truth,
    rmat_with_ground_truth,
)

SUITE = [(1000, "1k"), (10_000, "10k"), (50_000, "50k"), (100_000, "100k")]


def make_suite(
    out_dir: str,
    *,
    avg_deg: float = DEFAULT_AVG_DEG,
    seed: int | None = 0,
    sizes=SUITE,
) -> list[str]:
    os.makedirs(out_dir, exist_ok=True)
    written = []
    for i, (n, label) in enumerate(sizes):
        path = os.path.join(out_dir, f"{label}.bin")
        info = generate_with_ground_truth(
            path, n, avg_deg / n, 0, n - 1,
            seed=None if seed is None else seed + i,
        )
        print(
            f"{label}: n={info['n']} m={info['m']} hop_count={info['hop_count']}"
        )
        written.append(path)
    return written


def main(argv=None):
    ap = argparse.ArgumentParser(description="Generate the benchmark graph suite")
    ap.add_argument("--out-dir", default="graphs")
    ap.add_argument("--avg-deg", type=float, default=DEFAULT_AVG_DEG)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--rmat",
        type=int,
        nargs="*",
        default=[],
        metavar="SCALE",
        help="also generate RMAT graphs at these scales "
        "(e.g. --rmat 20 23 for 1M/8M-node Graph500 rows)",
    )
    ap.add_argument("--edge-factor", type=int, default=16)
    args = ap.parse_args(argv)
    make_suite(args.out_dir, avg_deg=args.avg_deg, seed=args.seed)
    for scale in args.rmat:
        path = os.path.join(args.out_dir, f"rmat{scale}.bin")
        info = rmat_with_ground_truth(
            path, scale, args.edge_factor, seed=args.seed
        )
        print(
            f"rmat{scale}: n={info['n']} m={info['m']} "
            f"hop_count={info['hop_count']}"
        )


if __name__ == "__main__":
    main()
