"""Host-side graph builders: CSR and ELL (padded) adjacency.

The reference builds CSR on the host with a degree-count + prefix-sum +
scatter pass (v3/bibfs_cuda_only.cu:89-99, v4/mpi_bas.cpp:45-58). We do the
same vectorized in NumPy, then additionally *regularize* the CSR into ELL
form — a dense ``[n_pad, width]`` neighbor table — because TPU frontier
expansion is a dense gather over that table (variable-length CSR rows are
the canonical bad fit for a dense-vector machine; see SURVEY.md §7).

For G(n, p) random graphs with small average degree the max degree is
O(log n / log log n), so ELL padding waste is modest. Power-law graphs
(RMAT) need the hybrid ELL + COO-overflow layout; ``build_ell`` supports a
``width_cap`` that spills high-degree rows into an overflow COO list.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def canonical_pairs(n: int, edges: np.ndarray) -> np.ndarray:
    """Mirror undirected edges into a directed pair list, drop self-loops
    and duplicates. Returns an ``(E, 2)`` int64 array sorted by source.

    The O(M log M) canonicalization pass. Every builder accepts the result
    via its ``pairs=`` kwarg so callers building several layouts of the same
    graph (CSR + ELL + tiered, as the bench does) pay it once."""
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if edges.size and (int(edges.min()) < 0 or int(edges.max()) >= n):
        raise ValueError(
            f"edge endpoints must be in [0, {n}); got "
            f"[{int(edges.min())}, {int(edges.max())}]"
        )
    both = np.concatenate([edges, edges[:, ::-1]], axis=0)
    both = both[both[:, 0] != both[:, 1]]
    # unique via linear keys
    keys = both[:, 0] * n + both[:, 1]
    keys = np.unique(keys)
    out = np.empty((keys.size, 2), dtype=np.int64)
    out[:, 0] = keys // n
    out[:, 1] = keys % n
    return out


def _rank_within_row(pairs: np.ndarray, deg: np.ndarray, n: int) -> np.ndarray:
    """Per-directed-edge rank within its source row (pairs sorted by source,
    which :func:`canonical_pairs` guarantees)."""
    row_ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    return np.arange(pairs.shape[0]) - row_ptr[pairs[:, 0]]


def build_csr(
    n: int, edges: np.ndarray | None = None, *, pairs: np.ndarray | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Build a symmetric CSR adjacency (row_ptr[n+1], col_ind[2E]).

    Mirrors edges for undirectedness like the reference loader
    (graphs/read_graph.py:13-16) and dedups — the reference generator never
    emits duplicates so dedup is a no-op on its files. Rows are ascending
    (``canonical_pairs`` sorts globally), which path validation relies on.
    """
    if pairs is None:
        pairs = canonical_pairs(n, edges)
    deg = np.bincount(pairs[:, 0], minlength=n)
    row_ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    col_ind = pairs[:, 1].copy()  # already grouped+sorted by source
    return row_ptr, col_ind


@dataclasses.dataclass
class EllGraph:
    """Device-ready regularized adjacency.

    - ``nbr``: int32 ``[n_pad, width]`` neighbor table, rows padded with 0
      beyond ``deg[v]`` entries (padding is masked by ``deg`` at use sites).
    - ``deg``: int32 ``[n_pad]`` true degree per vertex (0 for pad vertices).
    - ``overflow``: int32 ``[n_over, 2]`` COO (u, v) pairs for edges that did
      not fit under ``width`` when a cap was applied (empty otherwise).
    """

    n: int
    n_pad: int
    width: int
    num_edges: int  # undirected unique edge count
    nbr: np.ndarray
    deg: np.ndarray
    overflow: np.ndarray

    @property
    def num_directed_edges(self) -> int:
        return int(self.deg.sum()) + self.overflow.shape[0]


def build_ell(
    n: int,
    edges: np.ndarray | None = None,
    *,
    width_cap: int | None = None,
    pad_multiple: int = 8,
    pairs: np.ndarray | None = None,
) -> EllGraph:
    """Regularize an undirected edge list into ELL form.

    ``pad_multiple`` rounds ``n_pad`` up so vertex arrays tile evenly across
    a device mesh (the sharded solver requires ``n_pad % num_devices == 0``).
    """
    if pairs is None:
        pairs = canonical_pairs(n, edges)
    num_edges = pairs.shape[0] // 2
    deg = np.bincount(pairs[:, 0], minlength=n).astype(np.int64)
    max_deg = int(deg.max()) if deg.size and pairs.size else 0
    width = max(1, max_deg)
    overflow = np.zeros((0, 2), dtype=np.int32)
    if width_cap is not None and width > width_cap:
        width = max(1, width_cap)
        spill = _rank_within_row(pairs, deg, n) >= width
        overflow = pairs[spill].astype(np.int32)
        pairs = pairs[~spill]
        deg = np.minimum(deg, width)

    n_pad = -(-n // pad_multiple) * pad_multiple
    nbr = np.zeros((n_pad, width), dtype=np.int32)
    if pairs.size:
        rank = _rank_within_row(pairs, deg, n)
        nbr[pairs[:, 0], rank] = pairs[:, 1]
    deg_pad = np.zeros(n_pad, dtype=np.int32)
    deg_pad[:n] = deg
    return EllGraph(
        n=n,
        n_pad=n_pad,
        width=width,
        num_edges=num_edges,
        nbr=nbr,
        deg=deg_pad,
        overflow=overflow,
    )


def ell_from_file(path, **kwargs) -> EllGraph:
    from bibfs_tpu.graph.io import read_graph_bin

    n, edges = read_graph_bin(path)
    return build_ell(n, edges, **kwargs)


@dataclasses.dataclass
class HubTier:
    """One geometric slice of the high-degree tail: neighbor slots
    ``[start, start + nbr.shape[1])`` for every vertex whose degree exceeds
    ``start``. Hub membership is nested (tier rows are indexed by the shared
    degree-descending ``hub_rank``), so tier t's members are exactly the
    first ``count`` entries of the hub ordering."""

    start: int  # first neighbor-slot rank this tier stores
    count: int  # true member count (rows beyond it are padding)
    nbr: np.ndarray  # int32 [count_pad, width]


@dataclasses.dataclass
class TieredEllGraph:
    """ELL adjacency with geometric hub tiers — the power-law answer.

    A single fixed-width ELL table wastes ``n_pad * max_deg`` slots on
    skewed (RMAT/Graph500) degree distributions where ``max_deg`` can be
    10^4 x the average. Here the base table stores every vertex's first
    ``width`` neighbors, and each :class:`HubTier` t stores slot ranks
    ``[start_t, start_t + width_t)`` for the ``count_t`` vertices whose
    degree exceeds ``start_t``, with widths growing geometrically — so the
    padded footprint stays O(directed edges * small constant) and every
    array is static-shaped for XLA. ``deg`` holds TRUE degrees (unlike
    ``EllGraph`` built with ``width_cap``); use sites clip per tier.

    ``hub_rank[v]`` is v's position in the degree-descending hub ordering
    (-1 for non-hubs): one map serves every tier because membership is
    nested.
    """

    n: int
    n_pad: int
    width: int  # base-tier width
    num_edges: int  # undirected unique edge count
    max_deg: int
    nbr: np.ndarray  # int32 [n_pad, width] first `width` neighbors
    deg: np.ndarray  # int32 [n_pad] TRUE degree (0 for pad vertices)
    hub_rank: np.ndarray  # int32 [n_pad], -1 for non-hub vertices
    hub_ids: np.ndarray  # int32 [num_hubs_pad] rank -> vertex id (-1 pad)
    tiers: tuple  # tuple[HubTier, ...]

    @property
    def num_directed_edges(self) -> int:
        return int(self.deg.sum())

    @property
    def padded_slots(self) -> int:
        return int(
            self.nbr.size + sum(t.nbr.size for t in self.tiers)
        )


# Candidate base widths; the builder picks the one minimizing total padded
# slots (base table + hub tiers), which is also what each pull level reads.
_BASE_WIDTHS = (4, 8, 16, 32, 64, 128)
_TIER_GROWTH = 8
# Hub arrays are replicated (never mesh-sharded), so they pad to the int32
# sublane multiple rather than the caller's pad_multiple.
_HUB_PAD = 8


def _pad_hub_count(count: int) -> int:
    return -(-count // _HUB_PAD) * _HUB_PAD


def _tier_plan(w0: int, max_deg: int):
    """Geometric tier boundaries for a given base width: [(start, width)]."""
    plan = []
    start = w0
    while start < max_deg:
        width = min(start * (_TIER_GROWTH - 1), max_deg - start)
        plan.append((start, width))
        start += width
    return plan


def _padded_slots(w0: int, n_pad: int, deg: np.ndarray, max_deg: int) -> int:
    total = n_pad * w0
    for start, width in _tier_plan(w0, max_deg):
        total += _pad_hub_count(int((deg > start).sum())) * width
    return total


def build_tiered(
    n: int,
    edges: np.ndarray | None = None,
    *,
    base_width: int | None = None,
    pad_multiple: int = 8,
    pairs: np.ndarray | None = None,
) -> TieredEllGraph:
    """Regularize an undirected edge list into tiered ELL form.

    For low-skew graphs (max degree <= the smallest viable base width) this
    degenerates to a plain single-table ELL with no tiers — identical
    layout and cost to :func:`build_ell`.
    """
    if pairs is None:
        pairs = canonical_pairs(n, edges)
    num_edges = pairs.shape[0] // 2
    deg = np.bincount(pairs[:, 0], minlength=n).astype(np.int64)
    max_deg = int(deg.max()) if deg.size and pairs.size else 0

    n_pad = -(-n // pad_multiple) * pad_multiple
    if base_width is None:
        cands = [w for w in _BASE_WIDTHS if w < max_deg] + [max_deg]
        base_width = min(
            cands, key=lambda w: _padded_slots(w, n_pad, deg, max_deg)
        )
    w0 = max(1, min(base_width, max_deg) if max_deg else base_width)
    rank = _rank_within_row(pairs, deg, n)

    nbr = np.zeros((n_pad, w0), dtype=np.int32)
    base_sel = rank < w0
    nbr[pairs[base_sel, 0], rank[base_sel]] = pairs[base_sel, 1]

    hub_rank = np.full(n_pad, -1, dtype=np.int32)
    hub_ids = np.zeros(0, dtype=np.int32)
    tiers = []
    if max_deg > w0:
        # degree-descending hub ordering shared by all tiers
        hub_order = np.argsort(-deg, kind="stable")
        num_hubs = int((deg > w0).sum())
        hub_order = hub_order[:num_hubs]
        hub_rank[hub_order] = np.arange(num_hubs, dtype=np.int32)
        hub_ids = np.full(_pad_hub_count(num_hubs), -1, dtype=np.int32)
        hub_ids[:num_hubs] = hub_order
        for start, width in _tier_plan(w0, max_deg):
            count = int((deg > start).sum())
            count_pad = _pad_hub_count(count)
            arr = np.zeros((count_pad, width), dtype=np.int32)
            sel = (rank >= start) & (rank < start + width)
            arr[hub_rank[pairs[sel, 0]], rank[sel] - start] = pairs[sel, 1]
            tiers.append(HubTier(start=start, count=count, nbr=arr))

    deg_pad = np.zeros(n_pad, dtype=np.int32)
    deg_pad[:n] = deg
    return TieredEllGraph(
        n=n,
        n_pad=n_pad,
        width=w0,
        num_edges=num_edges,
        max_deg=max_deg,
        nbr=nbr,
        deg=deg_pad,
        hub_rank=hub_rank,
        hub_ids=hub_ids,
        tiers=tuple(tiers),
    )
