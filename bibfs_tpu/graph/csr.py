"""Host-side graph builders: CSR and ELL (padded) adjacency.

The reference builds CSR on the host with a degree-count + prefix-sum +
scatter pass (v3/bibfs_cuda_only.cu:89-99, v4/mpi_bas.cpp:45-58). We do the
same vectorized in NumPy, then additionally *regularize* the CSR into ELL
form — a dense ``[n_pad, width]`` neighbor table — because TPU frontier
expansion is a dense gather over that table (variable-length CSR rows are
the canonical bad fit for a dense-vector machine; see SURVEY.md §7).

For G(n, p) random graphs with small average degree the max degree is
O(log n / log log n), so ELL padding waste is modest. Power-law graphs
(RMAT) need the hybrid ELL + COO-overflow layout; ``build_ell`` supports a
``width_cap`` that spills high-degree rows into an overflow COO list.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _mirror_and_dedup(n: int, edges: np.ndarray) -> np.ndarray:
    """Mirror undirected edges into a directed pair list, drop self-loops
    and duplicates. Returns an ``(E, 2)`` int64 array sorted by source."""
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if edges.size and (int(edges.min()) < 0 or int(edges.max()) >= n):
        raise ValueError(
            f"edge endpoints must be in [0, {n}); got "
            f"[{int(edges.min())}, {int(edges.max())}]"
        )
    both = np.concatenate([edges, edges[:, ::-1]], axis=0)
    both = both[both[:, 0] != both[:, 1]]
    # unique via linear keys
    keys = both[:, 0] * n + both[:, 1]
    keys = np.unique(keys)
    out = np.empty((keys.size, 2), dtype=np.int64)
    out[:, 0] = keys // n
    out[:, 1] = keys % n
    return out


def build_csr(n: int, edges: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Build a symmetric CSR adjacency (row_ptr[n+1], col_ind[2E]).

    Mirrors edges for undirectedness like the reference loader
    (graphs/read_graph.py:13-16) and dedups — the reference generator never
    emits duplicates so dedup is a no-op on its files.
    """
    pairs = _mirror_and_dedup(n, edges)
    deg = np.bincount(pairs[:, 0], minlength=n)
    row_ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    col_ind = pairs[:, 1].copy()  # already grouped+sorted by source
    return row_ptr, col_ind


@dataclasses.dataclass
class EllGraph:
    """Device-ready regularized adjacency.

    - ``nbr``: int32 ``[n_pad, width]`` neighbor table, rows padded with 0
      beyond ``deg[v]`` entries (padding is masked by ``deg`` at use sites).
    - ``deg``: int32 ``[n_pad]`` true degree per vertex (0 for pad vertices).
    - ``overflow``: int32 ``[n_over, 2]`` COO (u, v) pairs for edges that did
      not fit under ``width`` when a cap was applied (empty otherwise).
    """

    n: int
    n_pad: int
    width: int
    num_edges: int  # undirected unique edge count
    nbr: np.ndarray
    deg: np.ndarray
    overflow: np.ndarray

    @property
    def num_directed_edges(self) -> int:
        return int(self.deg.sum()) + self.overflow.shape[0]


def build_ell(
    n: int,
    edges: np.ndarray,
    *,
    width_cap: int | None = None,
    pad_multiple: int = 8,
) -> EllGraph:
    """Regularize an undirected edge list into ELL form.

    ``pad_multiple`` rounds ``n_pad`` up so vertex arrays tile evenly across
    a device mesh (the sharded solver requires ``n_pad % num_devices == 0``).
    """
    pairs = _mirror_and_dedup(n, edges)
    num_edges = pairs.shape[0] // 2
    deg = np.bincount(pairs[:, 0], minlength=n).astype(np.int64)
    max_deg = int(deg.max()) if deg.size and pairs.size else 0
    width = max(1, max_deg)
    overflow = np.zeros((0, 2), dtype=np.int32)
    if width_cap is not None and width > width_cap:
        width = max(1, width_cap)
        # rank of each directed edge within its row
        row_ptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(deg, out=row_ptr[1:])
        rank = np.arange(pairs.shape[0]) - row_ptr[pairs[:, 0]]
        spill = rank >= width
        overflow = pairs[spill].astype(np.int32)
        pairs = pairs[~spill]
        deg = np.minimum(deg, width)

    n_pad = -(-n // pad_multiple) * pad_multiple
    nbr = np.zeros((n_pad, width), dtype=np.int32)
    if pairs.size:
        row_ptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(deg, out=row_ptr[1:])
        rank = np.arange(pairs.shape[0]) - row_ptr[pairs[:, 0]]
        nbr[pairs[:, 0], rank] = pairs[:, 1]
    deg_pad = np.zeros(n_pad, dtype=np.int32)
    deg_pad[:n] = deg
    return EllGraph(
        n=n,
        n_pad=n_pad,
        width=width,
        num_edges=num_edges,
        nbr=nbr,
        deg=deg_pad,
        overflow=overflow,
    )


def ell_from_file(path, **kwargs) -> EllGraph:
    from bibfs_tpu.graph.io import read_graph_bin

    n, edges = read_graph_bin(path)
    return build_ell(n, edges, **kwargs)
