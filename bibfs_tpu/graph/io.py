"""Binary graph format IO — bit-compatible with the reference on-disk contract.

Format (little-endian): ``uint32 N``, ``uint32 M``, then ``M`` pairs of
``uint32 (u, v)`` undirected edges. Writer in the reference:
graphs/generate_graph.py:35-39; readers: v1/main-v1.cpp:26-30,
v3/bibfs_cuda_only.cu:74-87, graphs/read_graph.py:6-11.

Alongside each ``<name>.bin`` the reference ships a ground-truth JSON
``{source, target, hop_count, nodes}`` (graphs/generate_graph.py:53-62);
we read and write the same schema so reference graph suites are drop-in.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

_HEADER_DTYPE = np.dtype("<u4")


def _atomic_replace(path, write_payload, *, mode: str = "wb") -> None:
    """Commit a file atomically: ``write_payload(f)`` lands in a
    same-directory tmp file that is flushed, fsynced and
    ``os.replace``d onto ``path`` only once fully written — readers see
    either the old complete file or the new complete file, never a torn
    middle. On any failure the tmp is removed and the error re-raised.
    The one writer idiom every served/ground-truth file in this module
    (and the store's checkpoints, which anchor recovery on this
    property) goes through."""
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as f:
            write_payload(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_graph_bin(path: str | os.PathLike, n: int, edges: np.ndarray) -> None:
    """Write an undirected edge list in the reference binary format.

    ``edges`` is an ``(M, 2)`` integer array of endpoint pairs. Each
    undirected edge is stored once, exactly as the reference writer does.

    Endpoints must be in ``[0, n)``: the on-disk dtype is uint32, so a
    negative endpoint would otherwise WRAP (``-1`` -> ``4294967295``)
    and write a silently corrupt file.

    The write is ATOMIC: bytes land in a same-directory tmp file that is
    ``os.replace``d onto ``path`` only once fully written and flushed, so
    a crash mid-write can never leave a torn ``.bin`` behind — readers
    (and the durable store's checkpoints, which this property anchors)
    see either the old complete file or the new complete file.
    """
    edges = np.asarray(edges).reshape(-1, 2)
    if edges.size and (int(edges.min()) < 0 or int(edges.max()) >= n):
        raise ValueError(
            f"edge endpoints must be in [0, {n}); got "
            f"[{int(edges.min())}, {int(edges.max())}]"
        )
    edges = np.ascontiguousarray(edges, dtype=_HEADER_DTYPE).reshape(-1, 2)
    m = edges.shape[0]

    def _payload(f):
        np.array([n, m], dtype=_HEADER_DTYPE).tofile(f)
        edges.tofile(f)

    _atomic_replace(path, _payload)


def stream_graph_bin(path: str | os.PathLike, n: int, chunks) -> int:
    """Write the reference binary format from an iterable of edge chunks
    without ever materializing the full edge list.

    ``chunks`` yields ``(k, 2)`` integer arrays; each is validated and
    appended as uint32 pairs. The header's edge count is back-patched
    once the iterator is exhausted, then the file is flushed, fsynced
    and ``os.replace``d into place — the same atomic commit contract as
    :func:`write_graph_bin` (readers never see a torn or
    partially-streamed file, because the tmp only becomes ``path`` after
    the count patch lands). Returns the total edge count written.

    This is the 10M-node-scale writer: a scale-24 RMAT edge list is
    ~1 GB as int64 pairs in RAM but streams through here in fixed-size
    chunks, so generation peak memory is bounded by the generator's
    dedup state, not the output size.
    """
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    m = 0
    try:
        with open(tmp, "wb") as f:
            np.array([n, 0], dtype=_HEADER_DTYPE).tofile(f)
            for chunk in chunks:
                chunk = np.asarray(chunk).reshape(-1, 2)
                if chunk.size == 0:
                    continue
                if int(chunk.min()) < 0 or int(chunk.max()) >= n:
                    raise ValueError(
                        f"edge endpoints must be in [0, {n}); got "
                        f"[{int(chunk.min())}, {int(chunk.max())}]"
                    )
                np.ascontiguousarray(chunk, dtype=_HEADER_DTYPE).tofile(f)
                m += int(chunk.shape[0])
            f.flush()
            f.seek(_HEADER_DTYPE.itemsize)  # patch M in the header
            np.array([m], dtype=_HEADER_DTYPE).tofile(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return m


def read_graph_bin(path: str | os.PathLike) -> tuple[int, np.ndarray]:
    """Read the reference binary format. Returns ``(n, edges[M, 2])``.

    Validates the file size against the header the way the reference's
    legacy reader did (v2/read_in.cpp:16-22) — truncated files raise.
    """
    with open(path, "rb") as f:
        header = np.fromfile(f, dtype=_HEADER_DTYPE, count=2)
        if header.size != 2:
            raise ValueError(f"{path}: truncated header")
        n, m = int(header[0]), int(header[1])
        data = np.fromfile(f, dtype=_HEADER_DTYPE)
    if data.size != 2 * m:
        raise ValueError(
            f"{path}: header claims {m} edges ({2 * m} words) but file has "
            f"{data.size} payload words"
        )
    edges = data.reshape(m, 2).astype(np.int64)
    if m:
        # The on-disk dtype is uint32, but every reference reader loads
        # endpoints into C ``int`` (v1/main-v1.cpp:28, read_in.cpp) — a
        # word >= 2^31 is a NEGATIVE endpoint there, written by a buggy
        # (or signed-dtype) generator. Reject it by name: letting it
        # through as a huge positive id corrupts CSR builds downstream
        # (or, with n > 2^31, indexes from the end of every array), and
        # the generic out-of-range message hides what actually happened.
        top = int(edges.max())
        if top >= np.int64(2) ** 31:
            raise ValueError(
                f"{path}: edge endpoint {top} is negative "
                f"({top - 2 ** 32} as the int32 the format's readers "
                f"use) — not a valid vertex id"
            )
        if top >= n:
            raise ValueError(
                f"{path}: edge endpoint {top} out of range for n={n}"
            )
    return n, edges


def read_dense_matrix(path: str | os.PathLike) -> tuple[int, np.ndarray]:
    """Read the reference's LEGACY dense-matrix format: ``uint32 N`` then
    ``N*N`` uint8 adjacency bytes (v2/read_in.cpp:13-25 — the format its
    edge-list ``.bin`` replaced; the stale docstring in
    graphs/generate_graph.py:13-14 still describes it). Returns
    ``(n, edges[M, 2])`` in the canonical undirected form the rest of the
    framework consumes: one row per edge, ``u < v``.

    Validates file size against the header exactly as read_in.cpp:16-22
    does, and additionally requires the matrix to be symmetric with a zero
    diagonal (an asymmetric matrix cannot be an undirected graph).
    """
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        header = np.fromfile(f, dtype=_HEADER_DTYPE, count=1)
        if header.size != 1:
            raise ValueError(f"{path}: truncated header")
        n = int(header[0])
        expected = 4 + n * n
        if size != expected:
            raise ValueError(
                f"{path}: size mismatch: header says N = {n} => expected "
                f"{expected} bytes, but file is {size} bytes"
            )
        mat = np.fromfile(f, dtype=np.uint8, count=n * n).reshape(n, n)
    if np.any(np.diagonal(mat)):
        raise ValueError(f"{path}: dense matrix has self-loops on the diagonal")
    if not np.array_equal(mat, mat.T):
        raise ValueError(f"{path}: dense matrix is not symmetric")
    u, v = np.nonzero(np.triu(mat, k=1))
    return n, np.stack([u, v], axis=1).astype(np.int64)


def write_dense_matrix(
    path: str | os.PathLike, n: int, edges: np.ndarray
) -> None:
    """Write the legacy dense-matrix format (testing/migration aid: lets
    the framework round-trip files for tools that still speak it)."""
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if edges.size and (edges.min() < 0 or edges.max() >= n):
        raise ValueError(f"edge endpoint out of range for n={n}")
    if edges.size and np.any(edges[:, 0] == edges[:, 1]):
        # the format cannot represent self-loops (the reader rejects a
        # non-zero diagonal); refuse loudly instead of dropping data
        raise ValueError("dense-matrix format cannot represent self-loops")
    mat = np.zeros((n, n), dtype=np.uint8)
    mat[edges[:, 0], edges[:, 1]] = 1
    mat[edges[:, 1], edges[:, 0]] = 1

    def _payload(f):
        np.array([n], dtype=_HEADER_DTYPE).tofile(f)
        mat.tofile(f)

    _atomic_replace(path, _payload)


def write_ground_truth(
    path: str | os.PathLike,
    source: int,
    target: int,
    hop_count: Optional[int],
    nodes: Optional[list[int]],
) -> None:
    """Write the reference ground-truth JSON schema (generate_graph.py:53-62)."""
    payload = {
        "source": int(source),
        "target": int(target),
        "hop_count": None if hop_count is None else int(hop_count),
        "nodes": None if nodes is None else [int(v) for v in nodes],
    }
    # atomic: the sidecar is ground truth for its .bin — a torn JSON
    # next to a complete graph would fail suites that trust the pair
    _atomic_replace(path, lambda f: json.dump(payload, f), mode="w")


def read_ground_truth(path: str | os.PathLike) -> dict:
    with open(path) as f:
        return json.load(f)


def ground_truth_path(bin_path: str | os.PathLike) -> str:
    """The JSON sidecar path convention: ``foo.bin`` → ``foo.json``."""
    root, _ = os.path.splitext(os.fspath(bin_path))
    return root + ".json"
