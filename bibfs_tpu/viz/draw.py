"""Graph visualizer — reference ``visual/m3.py`` parity.

The reference loads ``../graphs/1k.bin`` plus its path JSON and renders the
graph with the shortest path as thick red edges over a kamada-kawai layout
(visual/m3.py:22-62). Same output here, with the graph/path arguments on
the CLI instead of hardcoded, and the path optionally computed on the spot
by any backend instead of requiring the JSON.
"""

from __future__ import annotations

import argparse
import os
import sys


def draw(
    bin_path: str,
    out_path: str,
    *,
    path_nodes=None,
    layout: str = "auto",
    labels: bool | None = None,
):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import networkx as nx

    from bibfs_tpu.graph.io import read_graph_bin

    n, edges = read_graph_bin(bin_path)
    g = nx.Graph()
    g.add_nodes_from(range(n))
    g.add_edges_from(map(tuple, edges))

    if layout == "auto":
        # kamada-kawai (the reference's layout, visual/m3.py:50) is O(n^2)
        # and intractable beyond a few thousand nodes
        layout = "kamada_kawai" if n <= 2000 else "spring"
    if layout == "kamada_kawai":
        pos = nx.kamada_kawai_layout(g)
    else:
        pos = nx.spring_layout(g, seed=0, iterations=30)

    fig, ax = plt.subplots(figsize=(12, 12))
    nx.draw_networkx_nodes(g, pos, node_size=20, node_color="#79a7d9", ax=ax)
    nx.draw_networkx_edges(g, pos, width=0.4, alpha=0.5, ax=ax)
    if labels if labels is not None else n <= 1000:
        nx.draw_networkx_labels(g, pos, font_size=4, ax=ax)
    if path_nodes:
        path_edges = list(zip(path_nodes, path_nodes[1:]))
        nx.draw_networkx_edges(
            g, pos, edgelist=path_edges, width=2.5, edge_color="red", ax=ax
        )
        nx.draw_networkx_nodes(
            g, pos, nodelist=path_nodes, node_size=40, node_color="red", ax=ax
        )
    ax.set_axis_off()
    fig.savefig(out_path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return out_path


def main(argv=None):
    ap = argparse.ArgumentParser(description="Render a graph + shortest path")
    ap.add_argument("graph", help=".bin graph file")
    ap.add_argument("--json", default=None, help="path JSON (default: sibling .json)")
    ap.add_argument("--out", default=None, help="output PNG (default: <graph>.png)")
    ap.add_argument(
        "--solve",
        nargs=2,
        type=int,
        metavar=("SRC", "DST"),
        help="compute the path now instead of reading the JSON",
    )
    ap.add_argument("--backend", default="serial")
    args = ap.parse_args(argv)

    out = args.out or os.path.splitext(args.graph)[0] + ".png"
    path_nodes = None
    if args.solve:
        from bibfs_tpu.graph.io import read_graph_bin
        from bibfs_tpu.solvers.api import solve

        n, edges = read_graph_bin(args.graph)
        res = solve(args.backend, n, edges, args.solve[0], args.solve[1])
        path_nodes = res.path
    else:
        from bibfs_tpu.graph.io import ground_truth_path, read_ground_truth

        jpath = args.json or ground_truth_path(args.graph)
        if os.path.exists(jpath):
            path_nodes = read_ground_truth(jpath).get("nodes")
        else:
            print(f"note: no path JSON at {jpath}; drawing graph only",
                  file=sys.stderr)
    draw(args.graph, out, path_nodes=path_nodes)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
