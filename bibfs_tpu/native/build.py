"""Lazy builder for the native shared library.

The .so is compiled on first use (and rebuilt when the source is newer),
so `pip install` needs no compile step and environments without a C++
toolchain simply don't get the `native` backend.
"""

from __future__ import annotations

import os
import shlex
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "bibfs_native.cpp")
SO = os.path.join(_DIR, "libbibfs_native.so")


def ensure_built(force: bool = False) -> str:
    """Compile the native library if missing/stale; returns the .so path."""
    if (
        not force
        and os.path.exists(SO)
        and os.path.getmtime(SO) >= os.path.getmtime(SRC)
    ):
        return SO
    cxx = os.environ.get("CXX", "g++")
    cxxflags = shlex.split(
        os.environ.get("CXXFLAGS", "-std=c++17 -O3 -fPIC -Wall -Wextra -pthread")
    )
    # compile to a temp path and os.replace() so concurrent builders never
    # leave a torn .so for another process's dlopen
    tmp = f"{SO}.tmp.{os.getpid()}"
    cmd = [cxx, *cxxflags, "-shared", "-o", tmp, SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, SO)
    except FileNotFoundError as e:
        raise OSError(f"no C++ compiler ({cxx}): {e}") from e
    except subprocess.CalledProcessError as e:
        raise OSError(f"native build failed:\n{e.stderr}") from e
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return SO


if __name__ == "__main__":
    print(ensure_built(force=True))
