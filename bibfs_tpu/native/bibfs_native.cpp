// Native runtime for bibfs_tpu — C++17 shared library bound via ctypes.
//
// Role: the framework's host-side native layer, replacing what the
// reference implemented in C++ around its solvers — binary graph loading
// (v1/main-v1.cpp:21-34), CSR construction by degree-count + prefix-sum +
// scatter (v3/bibfs_cuda_only.cu:89-99, v4/mpi_bas.cpp:45-58), and the v1
// serial bidirectional-BFS baseline itself (v1/main-v1.cpp:50-97). The TPU
// compute path stays in JAX/Pallas; this .so exists so graph preprocessing
// at 10M-node scale and the wall-clock baseline don't pay Python overheads.
//
// API style: stateless extern "C" functions over caller-allocated buffers
// (NumPy arrays on the Python side). Return 0 on success, negative errno-
// style codes on failure. No globals, no exceptions across the boundary.
// The one stateful object is the OPAQUE solve scratch (bibfs_scratch_*):
// repeated solves over one graph reuse epoch-stamped distance/parent
// arrays, so per-solve setup is O(vertices touched), not O(n) — the O(n)
// re-initialization of four n-sized arrays otherwise dominates wall-clock
// for short searches on large graphs (measured: most of ~100us at n=100k).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <new>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- errors
enum {
  BIBFS_OK = 0,
  BIBFS_EOPEN = -1,     // cannot open file
  BIBFS_EFORMAT = -2,   // truncated / malformed file
  BIBFS_ERANGE = -3,    // endpoint out of range
  BIBFS_EARG = -4,      // bad argument (src/dst out of range, etc.)
  BIBFS_EBUF = -5,      // caller buffer too small
  BIBFS_ENOMEM = -6,    // allocation failure
};

// ------------------------------------------------------------- graph I/O
// Binary format: little-endian uint32 N, uint32 M, then M uint32 pairs
// (the reference on-disk contract, graphs/generate_graph.py:35-39).

int bibfs_read_header(const char* path, uint32_t* n, uint32_t* m) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return BIBFS_EOPEN;
  uint32_t hdr[2];
  size_t got = std::fread(hdr, sizeof(uint32_t), 2, f);
  std::fclose(f);
  if (got != 2) return BIBFS_EFORMAT;
  *n = hdr[0];
  *m = hdr[1];
  return BIBFS_OK;
}

// edges: caller-allocated uint32[2*m]; validates size and endpoint range.
int bibfs_read_edges(const char* path, uint32_t n, uint32_t m,
                     uint32_t* edges) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return BIBFS_EOPEN;
  if (std::fseek(f, 2 * sizeof(uint32_t), SEEK_SET) != 0) {
    std::fclose(f);
    return BIBFS_EFORMAT;
  }
  size_t want = size_t(2) * m;
  size_t got = std::fread(edges, sizeof(uint32_t), want, f);
  std::fclose(f);
  if (got != want) return BIBFS_EFORMAT;
  for (size_t i = 0; i < want; ++i)
    if (edges[i] >= n) return BIBFS_ERANGE;
  return BIBFS_OK;
}

// --------------------------------------------------------------- CSR build
// Mirror undirected edges, drop self-loops and duplicates, produce a
// sorted symmetric CSR. row_ptr: int64[n+1]; col_ind: int32[<=2m]
// (caller allocates the 2m upper bound; *out_nnz reports the used size).
int bibfs_build_csr(uint32_t n, uint64_t m, const uint32_t* edges,
                    int64_t* row_ptr, int32_t* col_ind, int64_t* out_nnz) {
  std::vector<uint64_t> keys;
  keys.reserve(2 * m);
  for (uint64_t e = 0; e < m; ++e) {
    uint32_t u = edges[2 * e], v = edges[2 * e + 1];
    if (u >= n || v >= n) return BIBFS_ERANGE;
    if (u == v) continue;
    keys.push_back((uint64_t(u) << 32) | v);
    keys.push_back((uint64_t(v) << 32) | u);
  }
  std::sort(keys.begin(), keys.end());
  keys.erase(std::unique(keys.begin(), keys.end()), keys.end());

  std::memset(row_ptr, 0, (n + 1) * sizeof(int64_t));
  for (uint64_t k : keys) row_ptr[(k >> 32) + 1]++;
  for (uint32_t v = 0; v < n; ++v) row_ptr[v + 1] += row_ptr[v];
  for (size_t i = 0; i < keys.size(); ++i)
    col_ind[i] = int32_t(keys[i] & 0xffffffffu);
  *out_nnz = int64_t(keys.size());
  return BIBFS_OK;
}

// ---------------------------------------------------- serial bidirectional BFS
// The v1-parity native baseline (v1/main-v1.cpp:50-97): level-synchronous,
// smaller-frontier-first, per-side parent arrays — but with the correct
// termination rule (track best meet, stop when level_s + level_t >= best)
// instead of v1's first-meet early exit (quirk Q2).

namespace {

constexpr int32_t INF = INT32_MAX / 4;

// Epoch-stamped per-side search state: dist/par entries are valid only
// where stamp[v] == epoch, so starting a new solve is one ++epoch instead
// of refilling four n-sized arrays.
struct Side {
  std::vector<int32_t> dist, par;
  std::vector<uint32_t> stamp;
  std::vector<uint32_t> fr, next;

  void init(uint32_t n) {
    dist.assign(n, INF);
    par.assign(n, -1);
    stamp.assign(n, 0);
  }
  int32_t d(uint32_t v, uint32_t ep) const {
    return stamp[v] == ep ? dist[v] : INF;
  }
  void claim(uint32_t v, uint32_t ep, int32_t lvl, int32_t parent) {
    stamp[v] = ep;
    dist[v] = lvl;
    par[v] = parent;
  }
};

struct Scratch {
  uint32_t n = 0;
  uint32_t epoch = 0;
  Side s, t;
};

}  // namespace

void* bibfs_scratch_create(uint32_t n) {
  // no exception may cross the extern "C"/ctypes boundary: vector growth
  // can throw bad_alloc, so the whole construction is fenced
  try {
    auto* sc = new Scratch;
    sc->n = n;
    sc->s.init(n);
    sc->t.init(n);
    return sc;
  } catch (...) {
    return nullptr;
  }
}

void bibfs_scratch_free(void* scratch) { delete static_cast<Scratch*>(scratch); }

namespace {

// May throw (frontier push_back / path vectors on OOM); the extern "C"
// wrapper below fences it so no exception crosses the ABI.
//
// Optional per-level telemetry (all-or-nothing, enabled when lvl_side is
// non-null): level i (< lvl_cap) writes the expanded side (0 = source,
// 1 = target), the post-expansion frontier size, and the edges scanned
// that level; *out_meet_level gets the 1-based level at which the final
// best meet candidate was found (-1 if never). Disabled (the existing
// exports) costs one pointer test per level.
int solve_impl(uint32_t n, const int64_t* row_ptr, const int32_t* col_ind,
               void* scratch, uint32_t src, uint32_t dst,
               int32_t* out_hops, int32_t* path_buf, int32_t path_cap,
               int32_t* out_path_len, double* out_time_s,
               int64_t* out_edges, int32_t* out_levels,
               int32_t lvl_cap = 0, uint8_t* lvl_side = nullptr,
               int32_t* lvl_frontier = nullptr, int64_t* lvl_edges = nullptr,
               int32_t* out_meet_level = nullptr) {
  if (src >= n || dst >= n || !scratch) return BIBFS_EARG;
  auto* sc = static_cast<Scratch*>(scratch);
  if (sc->n != n) return BIBFS_EARG;
  *out_hops = -1;
  *out_path_len = 0;
  *out_edges = 0;
  *out_levels = 0;
  if (out_meet_level) *out_meet_level = -1;

  auto t0 = std::chrono::steady_clock::now();
  auto finish = [&]() {
    *out_time_s =
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count();
  };

  if (src == dst) {
    *out_hops = 0;
    if (path_cap >= 1) {
      path_buf[0] = int32_t(src);
      *out_path_len = 1;
    }
    finish();
    return BIBFS_OK;
  }

  if (++sc->epoch == 0) {  // stamp wrap (once per 2^32 solves): hard reset
    sc->s.init(n);
    sc->t.init(n);
    sc->epoch = 1;
  }
  const uint32_t ep = sc->epoch;
  Side& S = sc->s;
  Side& T = sc->t;
  S.fr.assign(1, src);
  T.fr.assign(1, dst);
  S.claim(src, ep, 0, -1);
  T.claim(dst, ep, 0, -1);

  int32_t level_s = 0, level_t = 0, best = INF;
  int64_t scanned = 0;
  int32_t levels = 0;
  uint32_t meet = UINT32_MAX;

  while (!S.fr.empty() && !T.fr.empty() && level_s + level_t < best) {
    bool s_side = S.fr.size() <= T.fr.size();
    Side& A = s_side ? S : T;
    Side& B = s_side ? T : S;
    int32_t lvl = (s_side ? ++level_s : ++level_t);

    A.next.clear();
    int64_t scanned_before = scanned;
    for (uint32_t u : A.fr) {
      for (int64_t i = row_ptr[u]; i < row_ptr[u + 1]; ++i) {
        ++scanned;
        uint32_t v = uint32_t(col_ind[i]);
        if (A.stamp[v] == ep) continue;  // already visited this side
        A.claim(v, ep, lvl, int32_t(u));
        A.next.push_back(v);
        int32_t dv_other = B.d(v, ep);
        if (dv_other != INF) {
          int32_t cand = lvl + dv_other;
          if (cand < best) {
            best = cand;
            meet = v;
            if (out_meet_level) *out_meet_level = levels + 1;
          }
        }
      }
    }
    A.fr.swap(A.next);
    ++levels;
    if (lvl_side && levels <= lvl_cap) {
      lvl_side[levels - 1] = s_side ? 0 : 1;
      lvl_frontier[levels - 1] = int32_t(A.fr.size());
      lvl_edges[levels - 1] = scanned - scanned_before;
    }
  }
  finish();
  *out_edges = scanned;
  *out_levels = levels;

  if (best >= INF) return BIBFS_OK;  // unreachable: out_hops stays -1
  *out_hops = best;

  // path reconstruction: walk parents both ways from the meet vertex
  // (v1/main-v1.cpp:86-97). Every vertex on a parent chain was claim()ed
  // this epoch (claim stamps before writing par, and best < INF means the
  // meet is stamped on both sides), so the plain -1-terminated walk needs
  // no stamp guards.
  std::vector<int32_t> left;  // meet .. src
  for (int32_t v = int32_t(meet); v != -1; v = S.par[uint32_t(v)])
    left.push_back(v);
  std::vector<int32_t> right;  // after meet .. dst
  for (int32_t v = T.par[meet]; v != -1; v = T.par[uint32_t(v)])
    right.push_back(v);

  int64_t total = int64_t(left.size()) + int64_t(right.size());
  if (total > path_cap) return BIBFS_OK;  // hops valid, path omitted
  int32_t k = 0;
  for (auto it = left.rbegin(); it != left.rend(); ++it) path_buf[k++] = *it;
  for (int32_t v : right) path_buf[k++] = v;
  *out_path_len = k;
  return BIBFS_OK;
}

}  // namespace

// Scratch-reusing solve: per-solve setup cost is O(touched), not O(n).
// Outputs: *out_hops = -1 if unreachable, else hop count; path written to
// path_buf (path_cap entries; *out_path_len = 0 if it doesn't fit);
// *out_time_s = search-loop seconds (reference timing parity);
// *out_edges = directed edges scanned; *out_levels = expansions done.
int bibfs_solve_s(uint32_t n, const int64_t* row_ptr, const int32_t* col_ind,
                  void* scratch, uint32_t src, uint32_t dst,
                  int32_t* out_hops, int32_t* path_buf, int32_t path_cap,
                  int32_t* out_path_len, double* out_time_s,
                  int64_t* out_edges, int32_t* out_levels) {
  try {
    return solve_impl(n, row_ptr, col_ind, scratch, src, dst, out_hops,
                      path_buf, path_cap, out_path_len, out_time_s,
                      out_edges, out_levels);
  } catch (...) {  // bad_alloc etc. must not cross the C ABI
    return BIBFS_ENOMEM;
  }
}

// Scratch-reusing solve WITH per-level telemetry: identical search to
// bibfs_solve_s, plus per-level outputs (see solve_impl) for the first
// lvl_cap levels — side (0=s/1=t), post-expansion frontier size, edges
// scanned — and the 1-based level of the final best meet candidate.
// Levels past lvl_cap still run and count; only recording stops.
int bibfs_solve_levels(uint32_t n, const int64_t* row_ptr,
                       const int32_t* col_ind, void* scratch, uint32_t src,
                       uint32_t dst, int32_t* out_hops, int32_t* path_buf,
                       int32_t path_cap, int32_t* out_path_len,
                       double* out_time_s, int64_t* out_edges,
                       int32_t* out_levels, int32_t lvl_cap,
                       uint8_t* lvl_side, int32_t* lvl_frontier,
                       int64_t* lvl_edges, int32_t* out_meet_level) {
  if (!lvl_side || !lvl_frontier || !lvl_edges || !out_meet_level ||
      lvl_cap < 0)
    return BIBFS_EARG;
  try {
    return solve_impl(n, row_ptr, col_ind, scratch, src, dst, out_hops,
                      path_buf, path_cap, out_path_len, out_time_s,
                      out_edges, out_levels, lvl_cap, lvl_side,
                      lvl_frontier, lvl_edges, out_meet_level);
  } catch (...) {
    return BIBFS_ENOMEM;
  }
}

// Threaded batch solve: `batch` independent queries striped over
// `num_threads` worker threads, each with its own epoch-stamped scratch —
// the host analog of the device backends' vmapped batch (and the
// parallelism the reference's process-per-query harness could not
// express, benchmark_test.sh:44-59). The graph arrays are shared
// read-only; outputs are per-query slices, so no synchronization beyond
// thread join is needed. Per-query paths land in path_buf[q*path_cap ..];
// a path longer than path_cap leaves out_path_len[q] = 0 with hops still
// valid (same rule as the single solve). *out_time_s is the WHOLE batch
// wall-clock. Returns the first non-OK code any query hit (remaining
// queries still run; per-query outputs of failed queries are untouched).
int bibfs_solve_batch(uint32_t n, const int64_t* row_ptr,
                      const int32_t* col_ind, int32_t batch,
                      const uint32_t* srcs, const uint32_t* dsts,
                      int32_t num_threads, int32_t* out_hops,
                      int32_t* path_buf, int32_t path_cap,
                      int32_t* out_path_len, double* out_time_s,
                      int64_t* out_edges, int32_t* out_levels) {
  if (batch < 0 || num_threads < 1) return BIBFS_EARG;
  auto t0 = std::chrono::steady_clock::now();
  int nthreads = std::min<int32_t>(num_threads, batch > 0 ? batch : 1);
  std::atomic<int> err{BIBFS_OK};
  auto work = [&](int tid) {
    void* sc = bibfs_scratch_create(n);
    if (!sc) {
      int want = BIBFS_OK;
      err.compare_exchange_strong(want, BIBFS_ENOMEM);
      return;
    }
    for (int32_t q = tid; q < batch; q += nthreads) {
      double tq = 0.0;
      int rc = bibfs_solve_s(n, row_ptr, col_ind, sc, srcs[q], dsts[q],
                             &out_hops[q], path_buf + size_t(q) * path_cap,
                             path_cap, &out_path_len[q], &tq, &out_edges[q],
                             &out_levels[q]);
      if (rc != BIBFS_OK) {
        int want = BIBFS_OK;
        err.compare_exchange_strong(want, rc);
      }
    }
    bibfs_scratch_free(sc);
  };
  if (nthreads == 1) {
    work(0);
  } else {
    // thread construction can throw (resource exhaustion); nothing may
    // cross the extern "C" boundary — fall back to inline execution of
    // the un-started stripes
    std::vector<std::thread> threads;
    int started = 0;
    try {
      threads.reserve(nthreads);
      for (; started < nthreads; ++started) threads.emplace_back(work, started);
    } catch (...) {
      for (int t = started; t < nthreads; ++t) work(t);
    }
    for (auto& th : threads) th.join();
  }
  *out_time_s =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();
  return err.load();
}

// Stateless one-shot wrapper (original ABI, kept for compatibility):
// allocates a scratch for the single call.
int bibfs_solve(uint32_t n, const int64_t* row_ptr, const int32_t* col_ind,
                uint32_t src, uint32_t dst, int32_t* out_hops,
                int32_t* path_buf, int32_t path_cap, int32_t* out_path_len,
                double* out_time_s, int64_t* out_edges, int32_t* out_levels) {
  void* sc = bibfs_scratch_create(n);
  if (!sc) return BIBFS_ENOMEM;
  int rc = bibfs_solve_s(n, row_ptr, col_ind, sc, src, dst, out_hops,
                         path_buf, path_cap, out_path_len, out_time_s,
                         out_edges, out_levels);
  bibfs_scratch_free(sc);
  return rc;
}

}  // extern "C"
