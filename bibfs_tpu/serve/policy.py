"""Telemetry-driven adaptive routing — learned per graph digest.

The route ladder's eligibility constants have been static calibration
facts (``calibration.json``: batch crossover, push caps, mesh/blocked
crossovers) since PR 1, while PRs 3 and 6 quietly built everything
needed to *learn* them per graph: per-level solver telemetry records
frontier/edge shapes and push/pull choices, and every routed flush is a
measured (route, batch, latency) sample. :class:`AdaptiveRouter` closes
that loop:

- **observe** — the engines note every resolved batch
  (``note(digest, route, batch, seconds)``: an EWMA of per-query
  latency per (route, batch rung)) and periodically sample one query of
  a flush through a telemetry-enabled serial solve
  (``observe_levels``: push/pull level counts, direction flips, peak
  frontier fraction — also feeding the process
  ``bibfs_level_frontier_fraction`` histogram);
- **decide** — ``order(digest, batch, ladder)`` returns the ladder the
  flush actually walks. While any rung lacks ``min_obs`` samples near
  the batch rung it moves one under-observed rung to the front (reason
  ``explore`` — fewest samples first, ties broken REVERSE ladder
  order, because default traffic measures the static first rung anyway
  and exploration should buy the missing information soonest; a
  totally-cold digest explores from the reverse end for the same
  reason); once every rung is measured it orders rungs by measured
  per-query latency (reason ``learned``). The static
  ``calibration.json`` ladder remains the backbone throughout: every
  eligibility constant stays calibrated, an ineligible rung is skipped
  whatever the ordering says, and a ladder the policy cannot reorder
  (fewer than two live rungs) passes through unchanged (reason
  ``default``). Every decision lands in
  ``bibfs_routes_adaptive_total{route,reason}``.
- **persist** — the learned state is a JSON sidecar next to the
  store's checkpoints (``<wal_dir>/policy.json``, atomic
  tmp+``os.replace`` writes, merge-on-save so concurrent engines over
  one store compose): a respawned/catch-up replica loads it at
  construction and serves its FIRST flush on the learned route — the
  warm-start the durability layer's recovery story was missing on the
  data plane. Until the sidecar (or live traffic) supplies
  observations, every decision falls back to the static
  ``calibration.json`` ladder, never a guess.

The derived fields a policy carries per digest — learned route order,
``push_frontier_max`` (the largest frontier a push level was observed
at: the measured push/pull threshold for this graph's shape) and
``batch_crossover`` (the smallest batch rung where a dispatch route
measured faster than the host route) — are what the README documents
as the policy triple (route choice, push/pull threshold, batch
crossover).
"""

from __future__ import annotations

import json
import os
import threading

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.obs.telemetry import frontier_fraction_hist
from bibfs_tpu.serve.buckets import bucket_batch

#: decision taxonomy for ``bibfs_routes_adaptive_total{reason=}``
ADAPTIVE_REASONS = ("default", "explore", "learned")

#: sidecar filename, rooted in the store's ``wal_dir`` — next to the
#: checkpoint manifests, so fleet respawn/catch-up machinery that
#: already ships that directory ships the learned policy with it
POLICY_SIDECAR = "policy.json"

#: observations per (route, batch rung) before the ordering trusts the
#: measurement over the static ladder
MIN_OBS = 2

#: explore promotions of one rung that produced NO sample before the
#: rung is treated as unmeasurable (permanently ineligible for this
#: graph — e.g. the blocked rung on a tile-sparse digest): without the
#: cap, `under` never empties and the learned ordering never engages
EXPLORE_CAP = 3

#: EWMA weight of the newest latency sample (route warmup/compile
#: outliers wash out in a few flushes)
EWMA_ALPHA = 0.5

#: notes between sidecar writes (plus one final write at engine close)
SAVE_EVERY = 32

#: notes between telemetry-sampled serial solves (the level-shape
#: observation costs one extra serial BFS — bounded to ~1.5% of
#: flushes)
TELEMETRY_SAMPLE_EVERY = 64


@guarded_by("_lock", "_digests", "_notes", "_dirty", "_loaded", "_first",
            "_saving", "_sampling")
class AdaptiveRouter:
    """Per-graph-digest routing policy (module docstring).

    ``path`` roots the persistence sidecar (None = in-memory only);
    ``routes`` is the ladder this engine can walk (labels minted
    eagerly so the families render at zero); ``label`` the owning
    engine's metrics label.
    """

    def __init__(self, *, label: str, routes=(), path: str | None = None,
                 min_obs: int = MIN_OBS):
        self._lock = threading.Lock()
        self._digests: dict = {}
        self._notes = 0
        self._dirty = 0
        self._saving = False  # one in-flight background saver at a time
        self._sampling = False  # one in-flight telemetry sample likewise
        self._loaded = False
        # this session's first order() decision — the warm-start
        # witness (a respawned replica's first flush must already ride
        # the learned route); never persisted
        self._first: dict | None = None
        self._path = None if path is None else os.fspath(path)
        self.min_obs = int(min_obs)
        self._label = label
        family = REGISTRY.counter(
            "bibfs_routes_adaptive_total",
            "Adaptive routing decisions by chosen first rung and reason "
            "(default = static ladder, explore = measuring an "
            "under-observed rung, learned = measured ordering)",
            ("engine", "route", "reason"),
        )
        self._cells = {
            (r, why): family.labels(engine=label, route=r, reason=why)
            for r in routes
            for why in ADAPTIVE_REASONS
        }
        self._cell_family = family
        # mint the shape histogram so an adaptive process renders the
        # whole ADAPTIVE_METRIC_FAMILIES group at zero (telemetry-
        # enabled solves share the same cell)
        frontier_fraction_hist()
        if self._path is not None:
            self._load()

    # ---- persistence -------------------------------------------------
    @staticmethod
    def _sanitize(digests: dict) -> dict:
        """Coerce loaded sidecar data to the shapes the decision path
        indexes without guards — a hand-edited / version-drifted /
        partially-merged file must degrade to fewer observations, never
        to a KeyError on the flusher thread (the ``_load`` contract:
        corrupt means cold start, never a crash)."""
        clean: dict = {}
        for digest, entry in digests.items():
            if not isinstance(entry, dict):
                continue
            routes: dict = {}
            for route, buckets in (entry.get("routes") or {}).items():
                if not isinstance(buckets, dict):
                    continue
                cells = {}
                for bucket, cell in buckets.items():
                    try:
                        int(bucket)
                        lat = cell.get("lat_us")
                        cells[str(bucket)] = {
                            "lat_us": None if lat is None else float(lat),
                            "n": int(cell.get("n", 0)),
                        }
                    except (TypeError, ValueError, AttributeError):
                        continue
                if cells:
                    routes[str(route)] = cells
            clean[str(digest)] = {
                "routes": routes,
                "levels": (
                    entry.get("levels")
                    if isinstance(entry.get("levels"), dict) else None
                ),
                "last": (
                    entry.get("last")
                    if isinstance(entry.get("last"), dict) else None
                ),
            }
        return clean

    def _load(self) -> None:
        try:
            with open(self._path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return  # absent/corrupt sidecar: cold start, never a crash
        digests = data.get("digests")
        if not isinstance(digests, dict):
            return
        digests = self._sanitize(digests)
        with self._lock:
            self._digests = digests
            self._loaded = bool(digests)

    def save(self) -> None:
        """Write the sidecar: merge our digests over whatever is on
        disk (concurrent engines over one store compose; ours wins per
        digest) and commit by atomic tmp+``os.replace`` — the file
        sits in the store's durable directory and must never be
        half-written. The read-merge-replace runs under an exclusive
        ``flock`` on a ``.lock`` sibling (per-fd, so it also
        serializes this process's close()-time save against the
        in-flight background saver): without it two writers could both
        read, then replace in turn, and the second commit would
        silently drop every digest only the first had learned. All
        file I/O runs OFF the policy lock."""
        if self._path is None:
            return
        import fcntl

        with self._lock:
            mine = json.loads(json.dumps(self._digests))  # deep snapshot
            self._dirty = 0
        # per-writer tmp name: belt to the flock's braces — even a
        # platform where the advisory lock is a no-op can never commit
        # another writer's half-written file
        tmp = f"{self._path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(f"{self._path}.lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            merged = {}
            try:
                with open(self._path) as f:
                    on_disk = json.load(f)
                if isinstance(on_disk.get("digests"), dict):
                    merged = on_disk["digests"]
            except (OSError, json.JSONDecodeError, ValueError):
                pass
            merged.update(mine)
            payload = {"version": 1, "digests": merged}
            try:
                with open(tmp, "w") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                    f.write("\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

    @property
    def loaded(self) -> bool:
        """Whether construction warm-started from a non-empty sidecar."""
        with self._lock:
            return self._loaded

    # ---- observation -------------------------------------------------
    def _entry_locked(self, digest: str) -> dict:
        e = self._digests.get(digest)
        if e is None:
            e = {"routes": {}, "levels": None, "last": None}
            self._digests[digest] = e
        return e

    @staticmethod
    def policy_key(digest: str, kind: str | None = None) -> str:
        """The per-(graph, query-kind) policy namespace: point-to-point
        stays keyed by bare digest (the pre-taxonomy sidecar format —
        old sidecars keep warm-starting), every other kind gets its own
        ``digest#kind`` entry so the msbfs ladder's learned ordering
        never leaks into the pt ladder's."""
        if kind in (None, "pt"):
            return str(digest)
        return f"{digest}#{kind}"

    def note(self, digest: str, route: str, batch: int,
             seconds: float, *, kind: str | None = None) -> bool:
        """Record one resolved batch's measured latency (``kind``
        namespaces taxonomy kinds — :meth:`policy_key`). Returns True
        when the caller should run the periodic telemetry sample
        (:meth:`observe_levels` with a fresh level-stats dict)."""
        digest = self.policy_key(digest, kind)
        per_q = float(seconds) / max(int(batch), 1) * 1e6
        bucket = str(bucket_batch(batch))
        save = False
        with self._lock:
            entry = self._entry_locked(str(digest))
            cell = (
                entry["routes"]
                .setdefault(route, {})
                .setdefault(bucket, {"lat_us": None, "n": 0})
            )
            prev = cell["lat_us"]
            cell["lat_us"] = round(
                per_q if prev is None
                else EWMA_ALPHA * per_q + (1 - EWMA_ALPHA) * prev,
                3,
            )
            cell["n"] += 1
            # a fresh sample proves the route measurable: its explore
            # promotion budget restarts (EXPLORE_CAP note in order())
            ex = entry.get("explored")
            if ex:
                ex.pop(route, None)
            self._notes += 1
            self._dirty += 1
            sample = (
                self._notes % TELEMETRY_SAMPLE_EVERY == 1
                and not self._sampling
            )
            if sample:
                # one in-flight diagnostic sample at a time — the
                # caller's background solve releases the slot via
                # sample_done(); without the guard a slow serial BFS on
                # a big graph would accumulate threads (each pinning a
                # snapshot) faster than they finish
                self._sampling = True
            if (self._path is not None and self._dirty >= SAVE_EVERY
                    and not self._saving):
                # claim the saver slot AND reset the dirty count HERE,
                # in the locked section that decides — deferring either
                # to save() would keep every subsequent note spawning
                # another saver until the first one ran
                self._saving = True
                self._dirty = 0
                save = True
        if save:
            # periodic persistence runs OFF the serving thread (note()
            # is called from the pipelined engine's one finish worker:
            # an inline read-merge-fsync-replace would queue every
            # in-flight batch behind a disk write every SAVE_EVERY
            # flushes) and best-effort like the close()-time save — a
            # full disk must not fail anything, the next note retries
            def _bg_save():
                try:
                    self.save()
                except OSError:
                    pass
                finally:
                    with self._lock:
                        self._saving = False

            threading.Thread(
                target=_bg_save, name="bibfs-policy-save", daemon=True
            ).start()
        return sample

    def sample_done(self) -> None:
        """Release the telemetry-sample slot claimed by a True return
        from :meth:`note` (the engine's background sample thread calls
        this in its ``finally``)."""
        with self._lock:
            self._sampling = False

    def observe_levels(self, digest: str, level_stats: dict,
                       n: int) -> None:
        """Fold one telemetry-enabled solve's per-level record into the
        digest's level-shape aggregate: push/pull level counts,
        direction flips, the push/pull threshold observation
        (``push_frontier_max`` — the largest frontier any push level
        carried) and the peak frontier fraction."""
        levels = level_stats.get("levels") or []
        if not levels:
            return
        pushes = sum(1 for lv in levels if lv["dir"] == "push")
        flips = sum(
            1 for a, b in zip(levels, levels[1:]) if a["dir"] != b["dir"]
        )
        push_max = max(
            (lv["frontier"] for lv in levels if lv["dir"] == "push"),
            default=0,
        )
        frac_max = max(lv["frontier"] for lv in levels) / max(int(n), 1)
        with self._lock:
            agg = self._entry_locked(str(digest)).get("levels")
            if agg is None:
                agg = {
                    "solves": 0, "levels": 0, "push_levels": 0,
                    "flips": 0, "push_frontier_max": 0,
                    "frontier_frac_max": 0.0,
                }
                self._digests[str(digest)]["levels"] = agg
            agg["solves"] += 1
            agg["levels"] += len(levels)
            agg["push_levels"] += pushes
            agg["flips"] += flips
            agg["push_frontier_max"] = max(
                agg["push_frontier_max"], push_max
            )
            agg["frontier_frac_max"] = round(
                max(agg["frontier_frac_max"], frac_max), 6
            )
            self._dirty += 1

    # ---- decision ----------------------------------------------------
    @staticmethod
    def _obs_near(routes_data: dict, route: str, bucket: str) -> dict:
        """The route's observation cell for ``bucket``, falling back to
        the NEAREST measured batch rung (by rung distance) when the
        exact one has no samples: learned orderings generalize across
        batch rungs, and a respawned replica's first flush (a deadline
        flush popping whatever arrived) rarely lands on exactly the
        rung the sidecar measured — re-exploring from scratch there
        would defeat the warm start."""
        buckets = routes_data.get(route, {})
        cell = buckets.get(bucket)
        if cell and cell["n"]:
            return cell
        target = int(bucket).bit_length()
        best = None
        for bk, c in buckets.items():
            if c["n"] and c["lat_us"] is not None:
                d = abs(int(bk).bit_length() - target)
                if best is None or d < best[0]:
                    best = (d, c)
        return best[1] if best else {"lat_us": None, "n": 0}

    def order(self, digest: str, batch: int, ladder, *,
              kind: str | None = None) -> tuple:
        """The ladder this flush walks (``host`` stays terminal) and
        why — see the module docstring's decision rules. Counted in
        ``bibfs_routes_adaptive_total{route,reason}``. ``kind``
        namespaces taxonomy kinds (:meth:`policy_key`): each kind's
        ladder — e.g. ``(msbfs, host)`` — explores and learns its own
        per-digest ordering."""
        digest = self.policy_key(digest, kind)
        rungs = [r for r in ladder if r != "host"]
        tail = [r for r in ladder if r == "host"]
        bucket = str(bucket_batch(batch))
        with self._lock:
            entry = self._digests.get(str(digest), {})
            routes = entry.get("routes", {})
            promos = entry.get("explored", {})
            obs = {r: self._obs_near(routes, r, bucket) for r in rungs}
            # the host rung is measured too (it carries sub-crossover
            # and fallback traffic); its latency anchors the learned
            # batch crossover in stats(). A rung promoted EXPLORE_CAP
            # times without producing a NEW sample (note() resets the
            # count on every sample, so a measurable rung never caps
            # out) is ineligible for this graph's traffic: treating it
            # as still-under-observed would pin the policy in the
            # explore phase forever and the measured ordering of the
            # rungs that DO serve would never engage.
            under = [
                r for r in rungs
                if obs[r]["n"] < self.min_obs
                and promos.get(r, 0) < EXPLORE_CAP
            ]
            if len(rungs) < 2:
                # nothing to reorder: the static calibration ladder
                # passes through unchanged
                out, reason = list(ladder), "default"
            elif len(under) == len(rungs) and not any(
                obs[r]["n"] for r in rungs
            ) and not self._loaded:
                # nothing measured anywhere yet: explore, starting from
                # the rung the static ladder would try LAST (reverse
                # order — the static first rung gets measured by the
                # very next default walk anyway)
                out = list(reversed(rungs)) + tail
                reason = "explore"
            elif under:
                under.sort(
                    key=lambda r: (obs[r]["n"], -rungs.index(r))
                )
                first = under[0]
                out = (
                    [first] + [r for r in rungs if r != first] + tail
                )
                reason = "explore"
            else:
                # unmeasurable rungs (capped out with zero samples)
                # sort behind every measured one
                out = sorted(
                    rungs,
                    key=lambda r: (obs[r]["lat_us"] is None,
                                   obs[r]["lat_us"] or 0.0),
                ) + tail
                reason = "learned"
            if reason == "explore":
                ex = self._entry_locked(str(digest)).setdefault(
                    "explored", {}
                )
                ex[out[0]] = ex.get(out[0], 0) + 1
            decision = {
                "digest": str(digest), "route": out[0],
                "reason": reason, "bucket": bucket,
            }
            if entry:
                entry["last"] = {
                    "route": out[0], "reason": reason, "bucket": bucket,
                }
            elif reason != "default":
                self._entry_locked(str(digest))["last"] = {
                    "route": out[0], "reason": reason, "bucket": bucket,
                }
            if self._first is None:
                self._first = decision
        cell = self._cells.get((out[0], reason))
        if cell is None:
            cell = self._cell_family.labels(
                engine=self._label, route=out[0], reason=reason
            )
            self._cells[(out[0], reason)] = cell
        cell.inc()
        return tuple(out), reason

    # ---- introspection -----------------------------------------------
    def batch_crossover(self, digest: str, default: int) -> int:
        """The learned batch crossover for this graph: the smallest
        measured batch rung where some dispatch route beat the host
        route. Falls back to ``default`` (the calibration constant)
        until both sides are measured."""
        with self._lock:
            routes = self._digests.get(str(digest), {}).get("routes", {})
            host = routes.get("host", {})
            best = None
            for route, buckets in routes.items():
                if route == "host":
                    continue
                for bucket, cell in buckets.items():
                    h = host.get(bucket)
                    if (h and h["lat_us"] is not None
                            and cell["n"] >= self.min_obs
                            and cell["lat_us"] is not None
                            and cell["lat_us"] < h["lat_us"]):
                        b = int(bucket)
                        best = b if best is None else min(best, b)
        return default if best is None else best

    def stats(self) -> dict:
        with self._lock:
            return {
                "path": self._path,
                "loaded": self._loaded,
                "notes": self._notes,
                "first_decision": (
                    None if self._first is None else dict(self._first)
                ),
                "digests": json.loads(json.dumps(self._digests)),
            }
