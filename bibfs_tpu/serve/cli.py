"""``bibfs-serve`` — serve shortest-path queries over one graph.

The serving-shaped counterpart of ``bibfs-solve``: instead of one
process per query (the reference harness's model,
benchmark_test.sh:44-59) the engine keeps the graph device-resident,
micro-batches queued queries through one compiled program per flush,
and answers repeat traffic from the distance cache with zero solver
dispatches. Queries come from ``--pairs FILE`` or stdin (one
``src dst`` per line); results print in the ``bibfs-solve --pairs``
line format, and ``--stats-json`` writes the engine's machine-readable
serving counters.
"""

from __future__ import annotations

import argparse
import json
import sys


def _print_result(src, dst, res, no_path: bool) -> None:
    if res.found:
        line = f"{src} -> {dst}: length = {res.hops}"
        if res.path and not no_path:
            line += "  path: " + " -> ".join(str(v) for v in res.path)
    else:
        line = f"{src} -> {dst}: no path"
    print(line)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serve (src, dst) queries through the adaptive "
        "micro-batching engine"
    )
    ap.add_argument("graph", help=".bin graph file")
    ap.add_argument(
        "--pairs",
        default=None,
        metavar="FILE",
        help='query file of "src dst" lines (default: stream stdin, '
        "flushing each time the queue fills a batch)",
    )
    ap.add_argument(
        "--mode",
        default="auto",
        choices=["auto", "sync", "minor", "minor8"],
        help="batch layout for device flushes (default auto: the "
        "measured preference order)",
    )
    ap.add_argument(
        "--layout",
        default="ell",
        choices=["ell", "tiered"],
        help="adjacency layout (ell is shape-bucketed for executable "
        "reuse; tiered for power-law graphs)",
    )
    ap.add_argument(
        "--threshold",
        type=int,
        default=None,
        help="queue depth at which a flush dispatches as a device batch "
        "(default: the calibrated batch-vs-latency crossover); below "
        "it queries run per-query on the host runtime",
    )
    ap.add_argument("--max-batch", type=int, default=1024,
                    help="largest single device flush (default 1024)")
    ap.add_argument("--cache-entries", type=int, default=64,
                    help="distance-cache forest capacity (default 64)")
    ap.add_argument("--no-path", action="store_true",
                    help="skip path printing")
    ap.add_argument(
        "--stats-json",
        default=None,
        metavar="FILE",
        help="write the engine's serving counters (dispatches, cache "
        "hit rates, executable reuse) to FILE as JSON",
    )
    args = ap.parse_args(argv)

    from bibfs_tpu.graph.io import read_graph_bin
    from bibfs_tpu.serve import QueryEngine
    from bibfs_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    try:
        n, edges = read_graph_bin(args.graph)
    except (OSError, ValueError) as e:
        print(f"Error reading graph: {e}", file=sys.stderr)
        return 2

    try:
        engine = QueryEngine(
            n, edges,
            mode=args.mode,
            layout=args.layout,
            flush_threshold=args.threshold,
            max_batch=args.max_batch,
            cache_entries=args.cache_entries,
        )
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2

    try:
        if args.pairs is not None:
            import numpy as np

            pairs = np.loadtxt(args.pairs, dtype=np.int64, ndmin=2)
            if pairs.shape[1] != 2:
                print(
                    f"Error: {args.pairs} must have two columns (src dst)",
                    file=sys.stderr,
                )
                return 2
            results = engine.query_many(pairs)
            for (src, dst), res in zip(pairs, results):
                _print_result(src, dst, res, args.no_path)
        else:
            # stream stdin: tickets resolve at each engine flush (the
            # queue fills to max_batch, or EOF drains the remainder)
            tickets: list = []
            emitted = 0

            def drain():
                nonlocal emitted
                while emitted < len(tickets):
                    t = tickets[emitted]
                    if t.result is None:
                        break
                    _print_result(t.src, t.dst, t.result, args.no_path)
                    emitted += 1

            for line in sys.stdin:
                parts = line.split()
                if not parts:
                    continue
                if len(parts) != 2:
                    print(f"Error: bad query line {line!r}",
                          file=sys.stderr)
                    return 2
                tickets.append(engine.submit(int(parts[0]), int(parts[1])))
                drain()
            engine.flush()
            drain()
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2

    stats = engine.stats()
    print(
        "[Serve] {q} queries: {dq} device-batched ({db} flushes), "
        "{hq} host, {cs} cache-served; exec programs {ep} "
        "({eh} reused)".format(
            q=stats["queries"], dq=stats["device_queries"],
            db=stats["device_batches"], hq=stats["host_queries"],
            cs=stats["cache_served"],
            ep=stats["exec_cache"]["programs"],
            eh=stats["exec_cache"]["hits"],
        ),
        file=sys.stderr,
    )
    if args.stats_json:
        with open(args.stats_json, "w") as f:
            json.dump(stats, f, indent=1, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
