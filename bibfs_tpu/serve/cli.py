"""``bibfs-serve`` — serve shortest-path queries over one graph.

The serving-shaped counterpart of ``bibfs-solve``: instead of one
process per query (the reference harness's model,
benchmark_test.sh:44-59) the engine keeps the graph device-resident,
micro-batches queued queries through one compiled program per flush,
and answers repeat traffic from the distance cache with zero solver
dispatches. Queries come from ``--pairs FILE`` or stdin (one
``src dst`` per line); results print in the ``bibfs-solve --pairs``
line format, and ``--stats-json`` writes the engine's machine-readable
serving counters.

``--pipeline`` swaps in the asynchronous
:class:`~bibfs_tpu.serve.pipeline.PipelinedQueryEngine`: a background
flusher overlaps device dispatch with host-side finish and honors the
``--max-wait-ms`` latency SLO (a sub-crossover queue flushes on
deadline instead of waiting for depth). ``--load RATE[,RATE...]`` runs
the open-loop latency-SLO load harness instead of serving: sync vs
pipelined engines at each offered rate, oracle-verified, p50/p95/p99
reported (``bibfs_tpu/serve/loadgen``).

``--store DIR`` serves a whole :class:`~bibfs_tpu.store.GraphStore`
instead of one fixed ``.bin``: every ``DIR/*.bin`` registers under its
file stem, and the stdin stream grows store commands alongside
``src dst`` queries —

- ``use NAME`` switches the stream's current graph;
- ``update add U V`` / ``update del U V`` applies a live edge update
  (answered exactly through the delta overlay until compaction);
- ``swap`` forces a synchronous compaction + atomic hot-swap of the
  current graph (in-flight batches finish on the old snapshot);
- ``graphs`` lists the registered graphs with versions.

``--durable`` (with ``--store``) turns on the store's durability layer
(``bibfs_tpu/store/wal``): every acked update is write-ahead-logged
before the ack under the ``--fsync`` policy, compactions/swaps commit
crash-consistent checkpoints, and startup RECOVERS manifest + WAL —
a killed server respawns at its latest acked state, not the v1 seed.

``--oracle K`` enables the landmark distance-oracle tier
(``bibfs_tpu/oracle``): K landmark BFS trees answer landmark-endpoint,
bound-pinned, and provably-disconnected queries exactly with no BFS at
all (``route="oracle"``), and arm an upper-bound search cutoff
otherwise. Under ``--store`` the store owns one index per graph
(background builds off the serving path, follow-the-graph swaps); with
a plain ``.bin`` the engine builds one index at startup. The stdin
command ``oracle`` (works with or without ``--store``) prints the
current graph's index status and hit counters in the result stream.

Command replies land in the result stream (``use g: ...``), and a
malformed command answers an ``error invalid: ...`` line without
killing the stream — same contract as malformed query lines.
"""

from __future__ import annotations

import argparse
import json
import sys


_STORE_COMMANDS = ("use", "update", "swap", "graphs")


class _SigTerm(Exception):
    """Raised by the SIGTERM handler out of the blocking stdin read —
    the graceful-drain path: health flips to draining, in-flight
    flushes finish, queued results print, and the process exits 0 (the
    contract a fleet rolling restart relies on)."""


def _control_reply(engine, store, cmd: str) -> str:
    """The stdin ``health`` / ``stats`` / ``memory`` commands' one-line
    JSON reply
    (``health {...}`` / ``stats {...}`` — same reply-in-the-result-
    stream grammar as ``oracle``/``graphs``): the control surface a
    fleet router's subprocess replica driver and a human operator
    share. Deliberately non-blocking: no flush is forced, so a health
    probe never perturbs batching."""
    if cmd == "health":
        payload = engine.health_snapshot()
    elif cmd == "memory":
        # the memory-tier probe: per-graph tier + resident/mapped bytes
        # and residency-budget headroom (store/registry.memory_stats)
        payload = store.memory_stats()
    else:
        payload = engine.stats()
        if store is not None:
            payload["store"] = store.stats()
        # the fleet metrics aggregator's scrape path: a subprocess
        # replica has no HTTP port of its own, so the Prometheus text
        # rides the stats reply and the router re-exposes it with a
        # replica label (fleet/cli.py)
        from bibfs_tpu.obs.metrics import REGISTRY

        payload["metrics_render"] = REGISTRY.render()
    return cmd + " " + json.dumps(
        payload, sort_keys=True, default=str, separators=(",", ":")
    )


def _analytics_command(engine, current, parts: list[str]) -> str:
    """The stdin ``analytics KIND [k=v ...]`` command: submit one
    whole-graph kind through the engine's ladder (host / blocked rung
    picked per the calibrated crossover) and reply with the one-line
    JSON summary — never the whole vector; the vector lives in the
    per-digest result store and the kind cache. Unknown kinds and
    malformed params reply ``error invalid:`` in the result stream,
    same contract as malformed query lines."""
    from bibfs_tpu.analytics.queries import (
        analytics_query_from_spec, analytics_summary,
    )
    from bibfs_tpu.serve.resilience import QueryError

    params = {}
    for tok in parts[2:]:
        key, eq, val = tok.partition("=")
        if not eq or not key:
            return ("error invalid: usage: analytics KIND [k=v ...] "
                    f"(bad token {tok!r})")
        params[key] = val
    try:
        q = analytics_query_from_spec(parts[1] if len(parts) > 1 else "",
                                      params)
        res = engine.query_one(q, graph=current)
    except (ValueError, TypeError) as e:
        return f"error invalid: {e}"
    except QueryError as e:
        return f"error {e.kind}: {e}"
    return "analytics " + json.dumps(
        analytics_summary(res), sort_keys=True, default=str,
        separators=(",", ":"),
    )


def _oracle_status(engine, store, current) -> str:
    """The stdin ``oracle`` command's reply line: the current graph's
    index status + hit counters (store-backed or engine-local)."""
    if store is not None:
        if store.oracle_k is None:
            return "oracle: off (serve with --oracle K)"
        st = store.stats()["graphs"][current]["oracle"]
        state = ("ready" if st["ready"]
                 else "building" if st["building"] else "stale")
        head = (
            f"oracle {current}: {state} k={st['k']} gen={st['gen']} "
            f"builds={st['builds']} repairs={st['repairs']}"
        )
        idx = st.get("index")
        if idx is not None:
            head += f" age={idx['age_s']}s"
    else:
        st = engine.stats().get("oracle")
        if st is None:
            return "oracle: off (serve with --oracle K)"
        idx = st["index"]
        head = f"oracle: ready k={idx['k']} age={idx['age_s']}s"
    hits = st.get("hits")
    if hits:
        head += "  hits " + " ".join(f"{k}={v}" for k, v in hits.items())
    return head


def _store_command(store, current: str, parts: list[str]) -> tuple[str, str]:
    """Execute one stdin store command. Returns ``(reply_line,
    current_graph)`` — replies (including malformed-command errors) land
    in the result stream, same contract as malformed query lines."""
    cmd = parts[0]
    if cmd == "graphs":
        if len(parts) != 1:
            return "error invalid: usage: graphs", current
        st = store.stats()["graphs"]
        listing = " ".join(
            "{star}{name}(v{v})".format(
                star="*" if name == current else "", name=name,
                v=st[name]["version"],
            )
            for name in sorted(st)
        )
        return f"graphs: {listing}", current
    if cmd == "use":
        if len(parts) != 2:
            return "error invalid: usage: use NAME", current
        name = parts[1]
        try:
            snap = store.current(name)
        except KeyError as e:
            return f"error invalid: {e.args[0]}", current
        return f"use {name}: v{snap.version} digest {snap.digest[:12]}", name
    if cmd == "swap":
        if len(parts) != 1:
            return "error invalid: usage: swap", current
        old = store.current(current)
        new = store.compact(current)  # synchronous fold + hot-swap
        if new.version == old.version:
            return f"swap {current}: no pending delta (v{old.version})", \
                current
        return (
            f"swap {current}: v{old.version} -> v{new.version} "
            f"digest {new.digest[:12]}"
        ), current
    # update add|del U V
    if len(parts) != 4 or parts[1] not in ("add", "del"):
        return "error invalid: usage: update add|del U V", current
    try:
        u, v = int(parts[2]), int(parts[3])
    except ValueError:
        return (
            "error invalid: non-integer node id in "
            f"{' '.join(parts)!r}"
        ), current
    try:
        out = store.update(
            current,
            adds=[(u, v)] if parts[1] == "add" else (),
            dels=[(u, v)] if parts[1] == "del" else (),
        )
    except ValueError as e:
        return f"error invalid: {e}", current
    return (
        "update {g}: +{a}/-{d} pending{c}".format(
            g=current, a=out["adds"], d=out["dels"],
            c=" (compacting)" if out["compacting"] else "",
        )
    ), current


def _print_result(src, dst, res, no_path: bool) -> None:
    if res.found:
        line = f"{src} -> {dst}: length = {res.hops}"
        if res.path and not no_path:
            line += "  path: " + " -> ".join(str(v) for v in res.path)
    else:
        line = f"{src} -> {dst}: no path"
    print(line)


def _run_load(args, n, edges) -> int:
    from bibfs_tpu.serve.loadgen import compare_engines, sample_query_pairs

    try:
        rates = [float(r) for r in args.load.split(",") if r.strip()]
    except ValueError:
        print(f"Error: bad --load rate list {args.load!r}", file=sys.stderr)
        return 2
    if not rates or any(r <= 0 for r in rates):
        print("Error: --load needs positive rates (queries/s)",
              file=sys.stderr)
        return 2
    pairs = sample_query_pairs(n, args.load_queries)
    out = compare_engines(
        n, edges, pairs, rates,
        max_wait_ms=args.max_wait_ms,
        verify=not args.no_verify,
        mode=args.mode, layout=args.layout,
        flush_threshold=args.threshold, max_batch=args.max_batch,
        cache_entries=args.cache_entries,
    )
    for p in out["rates"]:
        for flavor in ("sync", "pipelined"):
            row = p[flavor]
            print(
                "[Load] {r:>9.1f} q/s offered | {f:9s} sustained "
                "{s:>9.1f} q/s  p50 {p50:7.2f} ms  p95 {p95:7.2f} ms  "
                "p99 {p99:7.2f} ms{bad}".format(
                    r=p["offered_qps"], f=flavor,
                    s=row["sustained_qps"] or 0.0,
                    p50=row["latency_ms"]["p50_ms"],
                    p95=row["latency_ms"]["p95_ms"],
                    p99=row["latency_ms"]["p99_ms"],
                    bad="" if row["ok"] else "  ORACLE MISMATCH",
                ),
                file=sys.stderr,
            )
    print(
        "[Load] pipelined_beats_sync={b} deadline_ok={d} "
        "verified={v}".format(
            b=out["pipelined_beats_sync"], d=out["deadline_ok"],
            v=out["verified_vs_oracle"],
        ),
        file=sys.stderr,
    )
    if args.stats_json:
        with open(args.stats_json, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")
    return 0 if (out["verified_vs_oracle"] and out["deadline_ok"]) else 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serve (src, dst) queries through the adaptive "
        "micro-batching engine"
    )
    ap.add_argument("graph", nargs="?", default=None,
                    help=".bin graph file (or serve a directory of "
                    "graphs with --store)")
    ap.add_argument(
        "--store",
        default=None,
        metavar="DIR",
        help="serve every *.bin graph in DIR through one versioned "
        "GraphStore (each registers under its file stem): per-query "
        "graph routing, live edge updates with exact overlay "
        "answering, and atomic hot-swap via the stdin commands "
        "use/update/swap (bibfs_tpu/store). Mutually exclusive with a "
        "positional .bin and with --load",
    )
    ap.add_argument(
        "--use",
        default=None,
        metavar="NAME",
        help="initial current graph under --store (default: the "
        "store's first graph, alphabetically)",
    )
    ap.add_argument(
        "--durable",
        action="store_true",
        help="enable the store's durability layer (requires --store): "
        "every acked edge update is write-ahead-logged before the ack, "
        "compactions/swaps checkpoint crash-consistently (atomic .bin "
        "+ manifest rename + WAL segment switch), and startup RECOVERS "
        "any graph that left a manifest/WAL behind — manifest + "
        "ordered replay, torn tails truncated (bibfs_tpu/store/wal)",
    )
    ap.add_argument(
        "--fsync",
        default="batch",
        choices=["always", "batch", "off"],
        help="WAL fsync policy under --durable (what 'durable enough "
        "to ack' means): always = fsync per update (survives OS/power "
        "loss), batch = group commit (survives process death; the "
        "default), off = OS flush only",
    )
    ap.add_argument(
        "--compact-threshold",
        type=int,
        default=256,
        metavar="EDGES",
        help="pending delta edges at which a store graph compacts in "
        "the background (rebuild + hot-swap off the serving path; "
        "default 256). 0 disables auto-compaction (explicit 'swap' "
        "only)",
    )
    ap.add_argument(
        "--residency-budget",
        type=int,
        default=None,
        metavar="BYTES",
        help="store residency budget: private (non-mapped) snapshot "
        "bytes above which the store demotes least-recently-acquired "
        "graphs to the compressed cold tier (promoted back on access; "
        "default: unlimited). The stdin command 'memory' prints "
        "per-graph tier + bytes",
    )
    ap.add_argument(
        "--no-mmap",
        action="store_true",
        help="disable the arrays sidecar: durable recovery rebuilds "
        "snapshots from the .bin instead of memory-mapping the "
        "checkpointed arrays (each replica then holds a private copy)",
    )
    ap.add_argument(
        "--pairs",
        default=None,
        metavar="FILE",
        help='query file of "src dst" lines (default: stream stdin, '
        "flushing each time the queue fills a batch)",
    )
    ap.add_argument(
        "--mode",
        default="auto",
        choices=["auto", "sync", "minor", "minor8"],
        help="batch layout for device flushes (default auto: the "
        "measured preference order)",
    )
    ap.add_argument(
        "--layout",
        default="ell",
        choices=["ell", "tiered"],
        help="adjacency layout (ell is shape-bucketed for executable "
        "reuse; tiered for power-law graphs)",
    )
    ap.add_argument(
        "--threshold",
        type=int,
        default=None,
        help="queue depth at which a flush dispatches as a device batch "
        "(default: the calibrated batch-vs-latency crossover); below "
        "it queries run per-query on the host runtime",
    )
    ap.add_argument(
        "--mesh",
        default=None,
        metavar="DEVICES",
        help='enable route="mesh": serve batches from a DEVICES-wide '
        "device mesh (serve/routes/mesh.py) — dp-batch flushes "
        "(query-sharded, zero collectives) for throughput, the "
        "1D vertex-sharded program with the bitpacked frontier "
        "exchange for mesh-scale graphs. 'auto' uses every visible "
        "device. Below-crossover traffic (calibration.json, the "
        "platform entry's mesh block) reroutes to the single-device "
        "rungs automatically; the mesh rung carries its own breaker "
        "and retry policy",
    )
    ap.add_argument(
        "--blocked",
        action="store_true",
        help='enable route="blocked": MXU-native blocked-adjacency '
        "frontier expansion (serve/routes/blocked.py) — above-crossover "
        "flushes on tile-compact (dense-ish/grid) graphs advance as "
        "masked block matmuls over the 128x128 int8 tiled adjacency "
        "instead of ELL gathers. The blocked rung leads the "
        "single-device ladder (blocked -> device -> host) with its own "
        "breaker and retry policy; eligibility constants come from "
        "calibration.json (the platform entry's blocked block)",
    )
    ap.add_argument(
        "--adaptive",
        action="store_true",
        help="telemetry-driven adaptive routing (serve/policy.py): "
        "learn a per-graph-digest route ordering from measured "
        "per-route latencies + sampled level telemetry instead of the "
        "static ladder. With --store --durable the learned policy "
        "persists as policy.json next to the checkpoints, so a "
        "respawned replica serves its first flush on the learned route",
    )
    ap.add_argument("--max-batch", type=int, default=1024,
                    help="largest single device flush (default 1024)")
    ap.add_argument("--cache-entries", type=int, default=64,
                    help="distance-cache forest capacity (default 64)")
    ap.add_argument(
        "--oracle",
        type=int,
        default=None,
        metavar="K",
        help="enable the landmark distance-oracle tier with K landmark "
        "BFS trees (bibfs_tpu/oracle): landmark-endpoint, bound-pinned "
        "and provably-disconnected queries answer exactly with no BFS "
        'at all (route="oracle"), everything else falls through with '
        "an upper-bound search cutoff armed. Under --store the store "
        "owns one index per graph (background builds, follow-the-graph "
        "swaps); with a .bin graph the engine builds one at startup. "
        "The stdin command 'oracle' prints index status",
    )
    ap.add_argument(
        "--pipeline",
        action="store_true",
        help="serve through the pipelined async engine: background "
        "deadline flusher, device dispatch overlapped with host-side "
        "finish (bibfs_tpu/serve/pipeline)",
    )
    ap.add_argument(
        "--port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve the concurrent network front door instead of the "
        "stdin REPL (bibfs_tpu/serve/net): length-prefixed JSON frames "
        "over TCP, correlation ids, per-request deadlines feeding the "
        "--max-wait-ms SLO, per-tenant token-bucket quotas, structured "
        "capacity refusals, graceful drain on SIGTERM. PORT 0 binds an "
        "ephemeral port (printed to stderr; see --port-file). Requires "
        "--pipeline (the background flusher is what resolves framed "
        "submits)",
    )
    ap.add_argument(
        "--port-file",
        default=None,
        metavar="FILE",
        help="atomically write 'host port' to FILE once the --port "
        "listener is bound — the readiness handshake the NetReplica "
        "fleet driver polls instead of parsing stderr",
    )
    ap.add_argument(
        "--net-host",
        default="127.0.0.1",
        metavar="HOST",
        help="bind address for --port (default 127.0.0.1; 0.0.0.0 to "
        "serve off-host)",
    )
    ap.add_argument(
        "--net-max-inflight",
        type=int,
        default=512,
        metavar="N",
        help="admission-controlled in-flight request cap for --port "
        "(default 512): excess submits answer structured capacity "
        "errors instead of queueing behind the engine's blocking "
        "backpressure",
    )
    ap.add_argument(
        "--net-quota-qps",
        type=float,
        default=None,
        metavar="RATE",
        help="per-tenant token-bucket quota for --port (queries/s, "
        "sustained; default: unlimited). Over-quota submits answer "
        "structured capacity errors with reason=quota",
    )
    ap.add_argument(
        "--net-quota-burst",
        type=float,
        default=None,
        metavar="TOKENS",
        help="per-tenant burst allowance above --net-quota-qps "
        "(default: 2x the rate)",
    )
    ap.add_argument(
        "--net-deadline-ms",
        type=float,
        default=None,
        metavar="MS",
        help="default per-request deadline for --port queries that "
        "carry none (default: none — requests wait for their result)",
    )
    ap.add_argument(
        "--net-brownout",
        action="store_true",
        help="arm the overload brownout on --port (serve/net.py "
        "BrownoutPolicy defaults): deadline-feasibility shedding plus "
        "the expensive-kind ladder, counted in "
        "bibfs_admission_shed_total. Default: off — no shedding",
    )
    ap.add_argument(
        "--coordinator",
        default=None,
        metavar="HOST:PORT",
        help="join a multi-process jax.distributed job before touching "
        "any backend (parallel/mesh.init_distributed): one logical "
        "replica spans every process's devices as a global mesh. "
        "Process 0 serves; processes > 0 run the pod worker loop "
        "(parallel/podmesh) and execute the broadcast mesh batches in "
        "lockstep. Use with --num-processes and --process-id",
    )
    ap.add_argument(
        "--num-processes", type=int, default=None, metavar="N",
        help="job size for --coordinator",
    )
    ap.add_argument(
        "--process-id", type=int, default=None, metavar="I",
        help="this process's index for --coordinator (0 = the serving "
        "primary)",
    )
    ap.add_argument(
        "--pod-port",
        type=int,
        default=None,
        metavar="PORT",
        help="pod control-plane port (default: the --coordinator port "
        "+ 1): the primary listens here for worker control "
        "connections; workers connect to it on the coordinator host",
    )
    ap.add_argument(
        "--mesh-shard-min-n",
        type=int,
        default=None,
        metavar="N",
        help="override the mesh rung's vertex-sharded crossover "
        "(graphs with >= N vertices route sharded; default: the "
        "calibrated constant). The multi-process dryrun pins this to 1 "
        "so every batch exercises the cross-process exchange",
    )
    ap.add_argument(
        "--max-wait-ms",
        type=float,
        default=5.0,
        help="latency SLO for --pipeline/--load: a sub-crossover queue "
        "flushes once its oldest query has waited this long "
        "(default 5.0)",
    )
    ap.add_argument(
        "--inject-faults",
        default=None,
        metavar="SPEC",
        help="chaos-test against the REAL engine: inject faults at the "
        "serving seams per SPEC (grammar in bibfs_tpu/serve/faults — "
        "e.g. 'device:p=0.1' fails 10%% of device dispatches, "
        "'host_batch:every=4,kind=latency,ms=20' stalls every 4th "
        "native batch). The BIBFS_FAULTS env var is the flagless "
        "equivalent; this flag wins when both are set. The resilience "
        "layer (retry, fallback ladder, breaker) handles what this "
        "throws",
    )
    ap.add_argument(
        "--load",
        default=None,
        metavar="RATE[,RATE...]",
        help="run the open-loop load harness at these offered rates "
        "(queries/s) instead of serving: sync vs pipelined engines, "
        "oracle-verified, per-rate latency percentiles; --stats-json "
        "then writes the full comparison artifact",
    )
    ap.add_argument("--load-queries", type=int, default=1000,
                    help="queries per offered rate under --load "
                    "(default 1000)")
    ap.add_argument(
        "--no-verify",
        action="store_true",
        help="skip the per-query serial-oracle check under --load "
        "(big graphs: the oracle pass can dwarf the measurement)",
    )
    ap.add_argument("--no-path", action="store_true",
                    help="skip path printing")
    ap.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve the process metrics registry over HTTP: GET "
        "/metrics returns Prometheus text exposition (counters, cache "
        "hit rates, flush causes, latency histograms — "
        "bibfs_tpu/obs/metrics), /healthz returns ok. PORT 0 binds an "
        "ephemeral port; the chosen one is printed to stderr",
    )
    ap.add_argument(
        "--trace",
        default=None,
        metavar="FILE",
        help="record tracing spans (flush, device_launch/device_finish, "
        "host_batch, cache ops) and write them to FILE as Chrome-trace "
        "JSON on exit — open in https://ui.perfetto.dev or "
        "chrome://tracing (bibfs_tpu/obs/trace)",
    )
    ap.add_argument(
        "--trace-spool",
        default=None,
        metavar="DIR",
        help="distributed tracing: append this process's spans to "
        "DIR/<proc>.<pid>.jsonl (crash-tolerant line spool; merge the "
        "fleet's spools with 'bibfs-trace merge DIR'). Queries sampled "
        "at ingress carry their trace context across the net frames, "
        "the stdin line protocol, and the pod control plane "
        "(bibfs_tpu/obs/dtrace). Equivalent to BIBFS_TRACE_SPOOL",
    )
    ap.add_argument(
        "--trace-sample",
        type=float,
        default=None,
        metavar="RATE",
        help="fraction of ingress queries to sample into the "
        "distributed trace spool (default 1.0 when --trace-spool is "
        "set; 0 disables sampling but keeps propagating contexts "
        "minted upstream). Equivalent to BIBFS_TRACE_SAMPLE",
    )
    ap.add_argument(
        "--stats-json",
        default=None,
        metavar="FILE",
        help="write the engine's serving counters (dispatches, cache "
        "hit rates, executable reuse; under --load the whole "
        "comparison) to FILE as JSON",
    )
    args = ap.parse_args(argv)

    from bibfs_tpu.graph.io import read_graph_bin
    from bibfs_tpu.serve import PipelinedQueryEngine, QueryEngine
    from bibfs_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    # the distributed-trace flags just set the env knobs the installer
    # (and any child process we describe work to) reads — one config
    # surface whether tracing came from the CLI or the environment
    import os as _os

    from bibfs_tpu.obs import dtrace

    if args.trace_spool is not None:
        _os.environ[dtrace.ENV_SPOOL] = args.trace_spool
    if args.trace_sample is not None:
        _os.environ[dtrace.ENV_SAMPLE] = str(args.trace_sample)
    podctx = None
    if args.coordinator is not None:
        # must run before anything touches a backend (jax requirement);
        # apply_platform_env only sets env vars, so this is still first
        from bibfs_tpu.parallel.mesh import init_distributed

        try:
            podctx = init_distributed(
                args.coordinator, args.num_processes, args.process_id
            )
        except (RuntimeError, ValueError) as e:
            print(f"Error joining distributed job: {e}", file=sys.stderr)
            return 2
        print(
            "[Pod] joined: process {i}/{p}, devices {ld}/{gd}".format(
                i=podctx.process_index, p=podctx.process_count,
                ld=podctx.local_device_count,
                gd=podctx.global_device_count,
            ),
            file=sys.stderr, flush=True,
        )
        if podctx.process_index > 0:
            # workers never open the store or build an engine: they
            # run the descriptor loop until the primary says shutdown
            from bibfs_tpu.parallel.podmesh import run_pod_worker

            host, port = _pod_control_addr(args)
            # each worker spools its own spans: a sampled query's pod
            # broadcast shows up as pod_worker_solve spans in every
            # worker process of the merged trace
            dtracer = dtrace.install_from_env(
                f"podworker{podctx.process_index}"
            )
            try:
                return run_pod_worker(
                    host, port, process_index=podctx.process_index,
                    log=lambda m: print(m, file=sys.stderr, flush=True),
                )
            finally:
                if dtracer is not None:
                    dtrace.set_dtracer(None)
                    dtracer.close()
    if args.port is not None:
        if not args.pipeline:
            print("Error: --port needs --pipeline (the background "
                  "flusher resolves framed submits)", file=sys.stderr)
            return 2
        if args.pairs is not None or args.load is not None:
            print("Error: --port serves the network front door; it "
                  "does not combine with --pairs/--load",
                  file=sys.stderr)
            return 2
    n = edges = store = None
    if args.load is not None and args.oracle is not None:
        print("Error: --load A/Bs the sync vs pipelined engines on one "
              "fixed graph; the oracle tier's A/B lives in 'python "
              "bench.py --serve-oracle'", file=sys.stderr)
        return 2
    if args.store is not None:
        if args.graph is not None:
            print("Error: pass a .bin graph OR --store DIR, not both",
                  file=sys.stderr)
            return 2
        if args.load is not None:
            print("Error: --load measures one fixed graph; it does not "
                  "combine with --store", file=sys.stderr)
            return 2
        from bibfs_tpu.store import GraphStore

        try:
            store = GraphStore.from_dir(
                args.store,
                compact_threshold=(args.compact_threshold or None),
                oracle_k=args.oracle,
                durable=args.durable,
                fsync=args.fsync,
                mmap_arrays=not args.no_mmap,
                residency_budget=args.residency_budget,
            )
        except (OSError, ValueError) as e:
            print(f"Error reading store: {e}", file=sys.stderr)
            return 2
        print(
            "[Store] serving {k} graph(s): {names}{d}".format(
                k=len(store.names()), names=", ".join(store.names()),
                d=f" (durable, fsync={args.fsync})" if args.durable
                else "",
            ),
            file=sys.stderr, flush=True,
        )
        sstats = store.stats()["graphs"]
        for gname in store.names():
            rec = (sstats[gname].get("durable") or {}).get("recovered")
            if rec is not None:
                print(
                    "[Store] recovered {g}: v{v}, {r} WAL record(s) "
                    "replayed{t}".format(
                        g=gname, v=rec["version"],
                        r=rec["replayed_records"],
                        t=(", torn tail truncated"
                           if rec["torn_tail_truncated"] else ""),
                    ),
                    file=sys.stderr, flush=True,
                )
    elif args.durable:
        print("Error: --durable needs --store DIR", file=sys.stderr)
        return 2
    else:
        if args.graph is None:
            print("Error: a .bin graph (or --store DIR) is required",
                  file=sys.stderr)
            return 2
        try:
            n, edges = read_graph_bin(args.graph)
        except (OSError, ValueError) as e:
            print(f"Error reading graph: {e}", file=sys.stderr)
            return 2

    # observability surfaces: both wrap the whole serving (or load) run
    metrics_server = None
    if args.metrics_port is not None:
        from bibfs_tpu.obs.http import start_metrics_server

        try:
            metrics_server = start_metrics_server(args.metrics_port)
        except OSError as e:
            print(f"Error: cannot bind metrics port "
                  f"{args.metrics_port}: {e}", file=sys.stderr)
            return 2
        print(f"[Obs] serving /metrics on {metrics_server.url}",
              file=sys.stderr, flush=True)
    tracer = None
    if args.trace is not None:
        from bibfs_tpu.obs.trace import Tracer, set_tracer

        tracer = Tracer()
        set_tracer(tracer)
    # the distributed-trace spool (per-process span log + flight
    # recorder dump path); None unless --trace-spool/BIBFS_TRACE_SPOOL
    dtracer = dtrace.install_from_env("serve")

    try:
        if args.load is not None:
            try:
                return _run_load(args, n, edges)
            except ValueError as e:
                print(f"Error: {e}", file=sys.stderr)
                return 2
        return _serve(args, n, edges, store, QueryEngine,
                      PipelinedQueryEngine, metrics_server, podctx)
    finally:
        if tracer is not None:
            from bibfs_tpu.obs.trace import uninstall_and_save

            # served queries already printed; a bad trace path must not
            # turn a completed run into a traceback (or skip the
            # metrics-server teardown below) — the helper reports it
            uninstall_and_save(tracer, args.trace)
        if dtracer is not None:
            dtrace.set_dtracer(None)
            dtracer.close()
        if metrics_server is not None:
            metrics_server.close()


def _pod_control_addr(args) -> tuple:
    """The pod control plane's (host, port): the coordinator host, on
    ``--pod-port`` or the coordinator port + POD_PORT_OFFSET."""
    from bibfs_tpu.parallel.podmesh import POD_PORT_OFFSET

    host, _, port = args.coordinator.rpartition(":")
    pod_port = (args.pod_port if args.pod_port is not None
                else int(port) + POD_PORT_OFFSET)
    return host or "127.0.0.1", pod_port


def _serve_net(args, engine, store) -> int:
    """The ``--port`` serving loop: bind the framed front door, park
    until SIGTERM/SIGINT, then drain gracefully (new queries refused
    with structured capacity errors, pending tickets resolved, reply
    buffers flushed) before the caller's engine teardown."""
    import signal
    import threading

    from bibfs_tpu.serve.net import (
        BrownoutPolicy,
        NetServer,
        write_port_file,
    )

    try:
        server = NetServer(
            engine, store=store, host=args.net_host, port=args.port,
            max_inflight=args.net_max_inflight,
            quota_qps=args.net_quota_qps,
            quota_burst=args.net_quota_burst,
            default_deadline_ms=args.net_deadline_ms,
            brownout=BrownoutPolicy() if args.net_brownout else None,
        )
    except OSError as e:
        print(f"Error: cannot bind --port {args.port}: {e}",
              file=sys.stderr)
        return 2
    try:
        if args.port_file:
            write_port_file(args.port_file, server.host, server.port)
        print(f"[Net] serving on {server.host}:{server.port}",
              file=sys.stderr, flush=True)
        stop = threading.Event()

        def _on_signal(signum, frame):
            stop.set()

        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, _on_signal)
            except ValueError:
                pass  # not the main thread (in-process embedding)
        try:
            while not stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            for sig, handler in prev.items():
                try:
                    signal.signal(sig, handler)
                except ValueError:
                    pass
        print("[Net] SIGTERM: draining (refusing new queries, "
              "finishing in-flight)", file=sys.stderr, flush=True)
        server.drain(timeout=30.0)
        engine.begin_drain()
        engine.flush()
    finally:
        server.close()
    return 0


def _serve(args, n, edges, store, QueryEngine, PipelinedQueryEngine,
           metrics_server=None, podctx=None):
    from bibfs_tpu.serve.resilience import QueryError

    pod = None
    try:
        kwargs = dict(
            mode=args.mode,
            layout=args.layout,
            flush_threshold=args.threshold,
            max_batch=args.max_batch,
            cache_entries=args.cache_entries,
        )
        mesh_devices = None
        want_mesh = args.mesh is not None or (
            podctx is not None and podctx.process_count > 1
        )
        if args.mesh is not None and args.mesh != "auto":
            mesh_devices = int(args.mesh)
        if want_mesh:
            if args.mesh_shard_min_n is not None:
                from bibfs_tpu.serve.routes import MeshConfig

                kwargs["mesh"] = MeshConfig(
                    devices=mesh_devices,
                    shard_min_n=args.mesh_shard_min_n,
                )
            else:
                kwargs["mesh"] = (
                    "auto" if mesh_devices is None else mesh_devices
                )
        if args.blocked:
            kwargs["blocked"] = True
        if args.adaptive:
            kwargs["adaptive"] = True
        if args.inject_faults is not None:
            import os

            from bibfs_tpu.serve.faults import FaultPlan

            # same seed knob as the BIBFS_FAULTS env path (README
            # documents BIBFS_FAULTS_SEED for both spec sources)
            kwargs["faults"] = FaultPlan.parse(
                args.inject_faults,
                seed=int(os.environ.get("BIBFS_FAULTS_SEED", 0)),
            )
        if store is not None:
            kwargs.update(store=store, graph=args.use)
        else:
            kwargs.update(n=n, edges=edges)
            if args.oracle is not None:
                kwargs["oracle_k"] = args.oracle
        if args.pipeline:
            engine = PipelinedQueryEngine(
                max_wait_ms=args.max_wait_ms, **kwargs
            )
        else:
            engine = QueryEngine(**kwargs)
    except (KeyError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2
    from bibfs_tpu.obs.dtrace import get_dtracer

    _dt = get_dtracer()
    if _dt is not None and engine._faults is not None:
        # arm the trace_flush chaos seam: spool appends now fire the
        # engine's fault plan before writing (a failed flush drops the
        # span, never the query)
        _dt.faults = engine._faults
    if metrics_server is not None:
        # /healthz answers from the live engine from here on (the
        # standalone 'ok' covered the construction window)
        metrics_server.set_health(engine.health_snapshot)
    if podctx is not None and podctx.process_count > 1:
        # the pod control plane: accept every worker, then swap the
        # mesh rung for the broadcasting pod rung (routes/pod.py)
        from bibfs_tpu.parallel.podmesh import PodError, PodPrimary
        from bibfs_tpu.serve.routes.pod import attach_pod

        _host, pod_port = _pod_control_addr(args)
        try:
            pod = PodPrimary(podctx.process_count - 1, port=pod_port)
            print(
                f"[Pod] waiting for {pod.num_workers} worker(s) on "
                f"port {pod.port}", file=sys.stderr, flush=True,
            )
            pod.accept_workers()
            attach_pod(engine, pod)
        except (OSError, PodError, ValueError) as e:
            print(f"Error: pod control plane: {e}", file=sys.stderr)
            engine.close()
            if pod is not None:
                pod.close()
            return 2
        print(
            f"[Pod] {podctx.process_count}-process mesh replica ready",
            file=sys.stderr, flush=True,
        )

    try:
        if args.port is not None:
            rc = _serve_net(args, engine, store)
            if rc:
                return rc
        elif args.pairs is not None:
            import numpy as np

            pairs = np.loadtxt(args.pairs, dtype=np.int64, ndmin=2)
            if pairs.shape[1] != 2:
                print(
                    f"Error: {args.pairs} must have two columns (src dst)",
                    file=sys.stderr,
                )
                return 2
            results = engine.query_many(pairs)
            for (src, dst), res in zip(pairs, results):
                _print_result(src, dst, res, args.no_path)
        else:
            # stream stdin: tickets resolve at each engine flush (the
            # queue fills to max_batch, or EOF drains the remainder;
            # under --pipeline the background deadline flusher resolves
            # them within --max-wait-ms on its own). The REPL is
            # long-lived by construction, so a malformed line (wrong
            # arity, non-integer, out-of-range id) answers a structured
            # ``error ...`` line in the result stream and the loop
            # CONTINUES — one bad client line must never kill the
            # server every other client is talking to
            tickets: list = []
            emitted = 0
            failed = 0
            current = (
                None if store is None
                else (args.use or store.default_graph())
            )

            def drain():
                nonlocal emitted, failed
                while emitted < len(tickets):
                    t = tickets[emitted]
                    err = getattr(t, "error", None)
                    if err is not None:
                        # a failed ticket must surface in-stream, not
                        # silently stall everything queued behind it
                        kind = getattr(err, "kind", "internal")
                        print(f"error {kind}: {t.src} -> {t.dst}: {err}")
                        failed += 1
                    elif t.result is None:
                        break
                    else:
                        _print_result(t.src, t.dst, t.result, args.no_path)
                    emitted += 1

            # graceful drain on SIGTERM (rolling restarts): the handler
            # raises out of the blocking stdin read; the except arm
            # below flips health to draining, finishes in-flight
            # flushes, prints everything queued, and exits 0
            import signal

            def _on_sigterm(signum, frame):
                # one-shot: disarm BEFORE raising, so a second SIGTERM
                # landing anywhere in the drain path (even inside the
                # except arm below, before it could disarm) cannot
                # re-raise outside the try and abort the drain
                try:
                    signal.signal(signal.SIGTERM, signal.SIG_IGN)
                except ValueError:
                    pass
                raise _SigTerm()

            from bibfs_tpu.obs.dtrace import (
                TOKEN_PREFIX, dspan, parse_token, sample_ctx,
            )

            prev_handler = None
            sigterm = False
            try:
                prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:
                pass  # not the main thread (in-process embedding)
            try:
                for line in sys.stdin:
                    parts = line.split()
                    if not parts:
                        continue
                    if parts[0] == "oracle":
                        if len(parts) != 1:
                            print("error invalid: usage: oracle")
                            continue
                        print(_oracle_status(engine, store, current))
                        continue
                    if parts[0] in ("health", "stats", "memory"):
                        if len(parts) != 1:
                            print(f"error invalid: usage: {parts[0]}")
                            continue
                        if parts[0] == "memory" and store is None:
                            print("error invalid: 'memory' needs --store")
                            continue
                        # print already-resolved results FIRST: the
                        # control reply doubles as the subprocess
                        # replica driver's result-drain nudge
                        drain()
                        print(_control_reply(engine, store, parts[0]))
                        continue
                    if parts[0] == "flightrec":
                        # the always-on post-mortem ring: dump it on
                        # demand (same surface the net front door's
                        # flightrec op exposes)
                        from bibfs_tpu.obs.dtrace import FLIGHT

                        if len(parts) == 2 and parts[1] == "dump":
                            snap = FLIGHT.snapshot()
                            snap["dumped_to"] = FLIGHT.dump(
                                reason="demand"
                            )
                        elif len(parts) == 1:
                            snap = FLIGHT.snapshot()
                        else:
                            print("error invalid: usage: "
                                  "flightrec [dump]")
                            continue
                        drain()
                        print("flightrec " + json.dumps(
                            snap, sort_keys=True, default=str,
                            separators=(",", ":"),
                        ))
                        continue
                    if parts[0] == "analytics":
                        # the whole-graph tier: submit-and-flush one
                        # typed kind and answer with its JSON summary.
                        # The forced flush also resolves any queued
                        # src/dst tickets — emit those (in submit
                        # order) before the analytics reply
                        reply = _analytics_command(
                            engine, current, parts
                        )
                        drain()
                        print(reply)
                        continue
                    if parts[0] in _STORE_COMMANDS:
                        if store is None:
                            print(f"error invalid: {parts[0]!r} needs "
                                  "--store")
                            continue
                        # sequential REPL semantics: resolve everything
                        # queued BEFORE the command mutates store state,
                        # so a query answers on the graph it was typed
                        # against (the engine's own swap barrier protects
                        # in-flight batches; this protects still-queued
                        # tickets). Only force the flush when something
                        # IS unresolved: a no-op flush still arms the
                        # pipelined flusher's drain request, and a `use`
                        # arriving just ahead of a query burst would
                        # then pop a partial below-crossover batch the
                        # moment the flusher thread wakes
                        if any(
                            t.result is None
                            and getattr(t, "error", None) is None
                            for t in tickets[emitted:]
                        ):
                            engine.flush()
                        drain()
                        reply, current = _store_command(
                            store, current, parts
                        )
                        print(reply)
                        continue
                    # a trailing '@t:TRACE:SPAN' token is the stdin
                    # protocol's trace-context carrier (the fleet
                    # router appends it to sampled queries); a bare
                    # 'src dst' line may still get sampled HERE when
                    # this process is the ingress
                    ctx = None
                    if len(parts) == 3 and parts[2].startswith(
                            TOKEN_PREFIX):
                        ctx = parse_token(parts.pop())
                    if len(parts) != 2:
                        print("error invalid: expected 'src dst', got "
                              f"{line.strip()!r}")
                        continue
                    try:
                        src, dst = int(parts[0]), int(parts[1])
                    except ValueError:
                        print("error invalid: non-integer node id in "
                              f"{line.strip()!r}")
                        continue
                    if ctx is None:
                        ctx = sample_ctx()
                    sp = dspan("repl_ingress", ctx, src=src, dst=dst)
                    try:
                        tickets.append(
                            engine.submit(src, dst, current, ctx=sp.ctx)
                        )
                        sp.finish()
                    except QueryError as e:
                        # a draining engine refuses admissions with a
                        # structured capacity error: answer it in-stream
                        # (retryable on a peer replica) and keep serving
                        # what is already queued
                        sp.finish(error=e.kind)
                        print(f"error {e.kind}: {src} -> {dst}: {e}")
                        continue
                    except RuntimeError as e:
                        sp.finish(error="capacity")
                        print(f"error capacity: {src} -> {dst}: {e}")
                        continue
                    except ValueError as e:
                        sp.finish(error="invalid")
                        print(f"error invalid: {src} -> {dst}: {e}")
                        continue
                    drain()
            except _SigTerm:
                sigterm = True
                # restart managers re-send SIGTERM: ignore repeats from
                # here on — a second signal mid-drain must not raise
                # outside the try (or, once the previous handler were
                # restored, kill the process) before the queued results
                # below get printed
                try:
                    signal.signal(signal.SIGTERM, signal.SIG_IGN)
                except ValueError:
                    pass
                engine.begin_drain()  # health -> draining; submits now
                # answer structured capacity errors (nothing more will
                # arrive from stdin — the loop is done)
                print("[Serve] SIGTERM: draining (finishing in-flight "
                      "flushes)", file=sys.stderr, flush=True)
            finally:
                if prev_handler is not None and not sigterm:
                    try:
                        signal.signal(signal.SIGTERM, prev_handler)
                    except ValueError:
                        pass
            engine.flush()
            drain()
            if failed:
                return 1
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2
    finally:
        engine.close()
        if pod is not None:
            # after engine.close(): the last mesh flush needed the
            # workers in the collective; only now may they exit
            pod.shutdown()

    stats = engine.stats()
    print(
        "[Serve] {q} queries: {mq} mesh, {dq} device-batched "
        "({db} flushes), {hq} host, {ov} overlay-exact, "
        "{orc} oracle-served, {cs} cache-served; "
        "exec programs {ep} ({eh} reused)".format(
            q=stats["queries"], mq=stats["mesh_queries"],
            dq=stats["device_queries"],
            db=stats["device_batches"], hq=stats["host_queries"],
            ov=stats["overlay_queries"], cs=stats["cache_served"],
            orc=stats["oracle_served"],
            ep=stats["exec_cache"]["programs"],
            eh=stats["exec_cache"]["hits"],
        ),
        file=sys.stderr,
    )
    if store is not None:
        store.close()  # join any in-flight background compaction
        sstats = store.stats()
        stats["store"] = sstats
        print(
            "[Store] {k} graph(s), {sw} swap(s), {co} compaction(s), "
            "{de} delta edge(s) pending".format(
                k=len(sstats["graphs"]),
                sw=sum(g["swaps"] for g in sstats["graphs"].values()),
                co=sum(
                    g["compactions"] for g in sstats["graphs"].values()
                ),
                de=sum(
                    g["delta_edges"] for g in sstats["graphs"].values()
                ),
            ),
            file=sys.stderr,
        )
    if args.stats_json:
        with open(args.stats_json, "w") as f:
            json.dump(stats, f, indent=1, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
