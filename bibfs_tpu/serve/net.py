"""Network front door — concurrent framed-TCP serving for the engines.

The serving interface up to PR 16 was a stdin/stdout REPL: one strictly
sequential text stream per process, driven either by an operator or by
:class:`~bibfs_tpu.fleet.replica.ProcessReplica`'s pipe plumbing. That
surface cannot express the production shape ROADMAP item 1 names —
thousands of concurrent clients, per-request deadlines, per-tenant
admission — so this module replaces it with a real wire protocol:

**Wire format.** Length-prefixed JSON frames: a 4-byte big-endian
payload length followed by that many bytes of UTF-8 JSON (one object
per frame). Requests carry a caller-chosen correlation ``id`` echoed on
the reply, so any number of requests may be in flight per connection
and replies arrive in COMPLETION order, not submit order — the
pipelined engine's whole point. Ops:

- ``{"op": "query", "id", "src", "dst", "graph"?, "deadline_ms"?,
  "tenant"?, "kind"?}`` → ``{"id", "ok": true, "found", "hops"}`` or
  ``{"id", "ok": false, "kind": <taxonomy>, "error": msg}``. The
  ``kind`` is the :data:`~bibfs_tpu.serve.resilience.ERROR_KINDS`
  taxonomy verbatim — a quota/admission refusal is a structured
  ``capacity`` error the client can retry elsewhere, never a dropped
  connection.
- control ops ``health`` / ``stats`` / ``memory`` / ``graphs`` /
  ``version`` / ``update`` / ``roll`` / ``ping`` →
  ``{"id", "ok": true, "result": ...}`` — the same control surface the
  stdin REPL exposed, now multiplexed beside queries on one socket
  (what :class:`~bibfs_tpu.fleet.netreplica.NetReplica` drives).

**Deadlines.** A query's optional ``deadline_ms`` is a reply SLO
measured from frame arrival: the completer guarantees SOME reply by the
deadline — the result if the engine landed it, else a structured
``timeout`` error (counted ``bibfs_net_deadline_misses_total``) with
the ticket cancelled so an unlaunched query never burns a solve.
Requests without a deadline ride the engine's ``max_wait_ms`` flush SLO
unchanged.

**Admission.** A server-wide in-flight bound (``max_inflight``,
refused as ``capacity`` reason=capacity) checked first, then per-tenant
token buckets (``quota_qps``/``quota_burst``, reason=quota) — in that
order, so a request refused for capacity never burns the tenant's
quota token. The in-flight bound is sized to stay under the pipelined
engine's blocking admission queue: the IO thread must never park
inside ``engine.submit``, because it is the thread every other
connection's reads ride on.

**Brownout (opt-in).** A server built with a :class:`BrownoutPolicy`
grows two more admission rungs between the in-flight bound and the
tenant bucket (so a shed burns no quota token either), both counted in
``bibfs_admission_shed_total{reason}`` — never in the rejection
taxonomy above, because a shed is a load-management choice, not an
error class:

- **deadline feasibility** (reason=infeasible): once the engine's own
  latency histogram holds enough samples, a query whose ``deadline_ms``
  is below the live p99 estimate is refused up front — the reply is a
  structured ``capacity`` error carrying ``retry_after_ms``, so the
  client backs off instead of burning a solve that will time out
  anyway.
- **the kind ladder** (reason=kshortest/weighted/msbfs): queries
  declare an admission class via an optional ``kind`` frame field
  (absent = point lookup — the only kind the wire computes today; the
  ladder is the admission contract for the expensive families the
  engine roadmap adds). Under queue pressure the expensive kinds shed
  first — ``kshortest`` at the lowest occupancy, ``msbfs`` last,
  point lookups never — each rung engaging/releasing with hysteresis
  so admission does not flap at a threshold.

Brownout is OFF by default: a plain ``NetServer`` sheds nothing, and
the tight-deadline phases of ``bench.py --serve-net`` (which *count on*
observing deadline timeouts) are unaffected.

**Threads.** One selector-based IO thread owns the listener and every
connection (non-blocking reads, frame parse, submit, buffered writes);
one completer thread wakes on the engine's batch-done broadcast, sweeps
resolved tickets and expired deadlines into reply frames, and hands the
bytes back to the IO thread via per-connection out-buffers and a
socketpair wakeup. Lock discipline for the lockgraph detector: the
server lock and the engine's lock are never held together — the
completer leaves the engine's condvar before touching server state, and
the IO thread releases the server lock before ``engine.submit``.
"""

from __future__ import annotations

import json
import os
import selectors
import socket
import struct
import threading
import time

from bibfs_tpu.analysis import guarded_by
from bibfs_tpu.obs.dtrace import (
    FLIGHT,
    ctx_fields,
    ctx_from_fields,
    dspan,
    sample_ctx,
    stage_histogram,
    wall_us,
)
from bibfs_tpu.obs.metrics import REGISTRY
from bibfs_tpu.serve.resilience import ERROR_KINDS, QueryError
from bibfs_tpu.solvers.api import BFSResult

_LEN = struct.Struct(">I")

#: default per-frame payload bound — generous for query/control traffic
#: (a roll batch of ~30k edges still fits), small enough that a hostile
#: length prefix cannot balloon a connection buffer
MAX_FRAME_BYTES = 1 << 20

#: admission-refusal reason labels on ``bibfs_net_rejections_total``
#: (tenant-less by design: tenant ids are unbounded cardinality)
REJECT_REASONS = ("quota", "capacity", "draining", "oversize",
                  "malformed")

#: the brownout kind ladder, most-expensive first: under pressure
#: ``kshortest`` sheds at the lowest occupancy, ``msbfs`` holds
#: longest, and point lookups (no ``kind`` field) are never ladder-shed
BROWNOUT_LADDER = ("kshortest", "weighted", "msbfs")

#: shed-reason labels on ``bibfs_admission_shed_total`` — the ladder
#: kinds plus the deadline-feasibility rung
SHED_REASONS = ("infeasible",) + BROWNOUT_LADDER

#: control ops the server answers beside queries (the stdin REPL's
#: command surface, multiplexed; ``metrics`` returns this process's
#: Prometheus rendering for fleet-wide aggregation, ``flightrec`` the
#: flight-recorder ring — and dumps it with ``dump: true``)
CONTROL_OPS = ("health", "stats", "memory", "graphs", "version",
               "update", "roll", "ping", "metrics", "flightrec",
               "analytics")


class FrameError(ValueError):
    """Unrecoverable framing violation (oversize length prefix): the
    stream position can no longer be trusted, so the connection must
    close — unlike malformed JSON inside a well-framed payload, which
    is answered and survived."""


def encode_frame(obj) -> bytes:
    """One wire frame: 4-byte big-endian length + compact UTF-8 JSON."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(
            f"frame payload {len(payload)}B exceeds {MAX_FRAME_BYTES}B"
        )
    return _LEN.pack(len(payload)) + payload


def extract_frames(buf: bytearray,
                   max_frame: int = MAX_FRAME_BYTES) -> list:
    """Pop every complete frame's payload bytes off ``buf`` (mutated in
    place, partial tail left for the next read). Raises
    :class:`FrameError` on a length prefix beyond ``max_frame``."""
    out = []
    while True:
        if len(buf) < _LEN.size:
            return out
        (length,) = _LEN.unpack_from(buf)
        if length > max_frame:
            raise FrameError(
                f"frame length {length}B exceeds {max_frame}B"
            )
        if len(buf) < _LEN.size + length:
            return out
        out.append(bytes(buf[_LEN.size: _LEN.size + length]))
        del buf[: _LEN.size + length]


def write_port_file(path: str, host: str, port: int) -> None:
    """Publish the bound address as ``"host port\\n"`` atomically
    (tmp + rename): a spawning :class:`NetReplica` polls this file, and
    must never read a half-written line."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{host} {port}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_port_file(path: str):
    """The ``(host, port)`` a :func:`write_port_file` published, or
    None while the file has not landed yet."""
    try:
        with open(path) as f:
            parts = f.read().split()
    except OSError:
        return None
    if len(parts) != 2:
        return None
    try:
        return parts[0], int(parts[1])
    except ValueError:
        return None


class TokenBucket:
    """One tenant's refill-on-read token bucket (``rate`` tokens/s up
    to ``burst``). NOT internally locked — the server mutates buckets
    only under its own lock, and a bucket never leaves the server."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.monotonic()

    def allow(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        # a caller-supplied ``now`` may predate the construction stamp
        # (the server anchors it at frame arrival, the bucket is built
        # later under the lock): elapsed clamps at zero so the burst
        # is never silently shaved
        elapsed = max(0.0, now - self.stamp)
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self.stamp = max(self.stamp, now)
        # the refill is computed as (now - stamp) * rate in floats: a
        # token earned over an interval like 0.1s can land ~1e-11 shy
        # of 1.0 depending on the magnitude of ``now``. An epsilon on
        # the spend keeps "waited exactly one token's worth" admitted
        # instead of rounding-refused
        if self.tokens >= 1.0 - 1e-9:
            self.tokens = max(0.0, self.tokens - 1.0)
            return True
        return False


class _Conn:
    """One accepted connection: its socket plus the receive/transmit
    buffers the IO thread and completer share (``wbuf`` is mutated only
    under the server lock; ``rbuf`` only by the IO thread)."""

    __slots__ = ("sock", "fd", "addr", "rbuf", "wbuf", "closed",
                 "want_write")

    def __init__(self, sock, addr):
        self.sock = sock
        self.fd = sock.fileno()
        self.addr = addr
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.closed = False
        self.want_write = False


class _PendingNet:
    """One submitted query awaiting its reply frame."""

    __slots__ = ("ticket", "conn", "rid", "deadline", "tenant", "t0",
                 "rx")

    def __init__(self, ticket, conn, rid, deadline, tenant, t0,
                 rx=None):
        self.ticket = ticket
        self.conn = conn
        self.rid = rid
        self.deadline = deadline
        self.tenant = tenant
        self.t0 = t0
        self.rx = rx  # wall-µs arrival stamp, traced queries only


class BrownoutPolicy:
    """Knobs for the front door's overload brownout (module docstring).
    Constructing one and passing it to :class:`NetServer` IS the
    opt-in — servers built without one shed nothing.

    ``ladder`` maps admission-class kinds to ENGAGE occupancy fractions
    of ``max_inflight``; a rung releases at ``engage - release`` (the
    hysteresis band). ``headroom`` scales the p99 estimate in the
    feasibility rung (>1.0 sheds earlier), which only arms once the
    engine's latency histogram holds ``min_samples`` observations."""

    __slots__ = ("feasibility", "min_samples", "headroom", "ladder",
                 "release", "retry_after_ms")

    def __init__(self, *, feasibility: bool = True,
                 min_samples: int = 50, headroom: float = 1.0,
                 ladder=None, release: float = 0.15,
                 retry_after_ms: float = 250.0):
        self.feasibility = bool(feasibility)
        self.min_samples = int(min_samples)
        self.headroom = float(headroom)
        self.ladder = dict(ladder) if ladder is not None else {
            "kshortest": 0.50, "weighted": 0.65, "msbfs": 0.80,
        }
        for k in self.ladder:
            if k not in BROWNOUT_LADDER:
                raise ValueError(
                    f"unknown ladder kind {k!r} "
                    f"(known: {BROWNOUT_LADDER})"
                )
        self.release = float(release)
        self.retry_after_ms = float(retry_after_ms)
        if self.min_samples < 1:
            raise ValueError("min_samples must be >= 1")
        if not (0.0 < self.release < 1.0):
            raise ValueError("release must be in (0, 1)")


# _state stays un-annotated by design (lock-free fast reads in the IO
# loop; every transition happens under the lock)
@guarded_by("_lock", "_conns", "_pending", "_buckets", "_submitting",
            "_seq", "_shed_engaged")
class NetServer:
    """The framed-TCP front door over one (pipelined) engine.

    Parameters
    ----------
    engine : a :class:`~bibfs_tpu.serve.pipeline.PipelinedQueryEngine`
        (or anything submit-compatible whose tickets self-resolve on a
        background flusher and that exposes a batch-done condvar as
        ``_cv``; the synchronous engine does neither, and serving it
        here would strand every non-inline ticket).
    store : the engine's :class:`~bibfs_tpu.store.GraphStore` when one
        is attached — enables the ``memory``/``graphs``/``update``/
        ``roll`` control ops (refused as ``invalid`` otherwise).
    host, port : bind address; port 0 picks an ephemeral port
        (republished via :attr:`port` and :func:`write_port_file`).
    max_inflight : server-wide submitted-but-unreplied bound. Keep it
        BELOW the engine's ``max_queue`` so admission refuses here with
        a structured ``capacity`` error instead of blocking the IO
        thread inside the engine's own admission gate.
    quota_qps, quota_burst : per-tenant token-bucket admission (None
        disables quotas; burst defaults to 2x qps).
    default_deadline_ms : deadline applied to queries that carry none
        (None = engine SLO only).
    brownout : a :class:`BrownoutPolicy` to arm the overload brownout
        rungs (module docstring); None (the default) sheds nothing.
    """

    def __init__(self, engine, *, store=None, host: str = "127.0.0.1",
                 port: int = 0, max_frame: int = MAX_FRAME_BYTES,
                 max_inflight: int = 512, quota_qps: float | None = None,
                 quota_burst: float | None = None,
                 default_deadline_ms: float | None = None,
                 brownout: BrownoutPolicy | None = None,
                 registry=None):
        self._engine = engine
        self._store = store
        self._max_frame = int(max_frame)
        self._max_inflight = int(max_inflight)
        self._quota_qps = None if quota_qps is None else float(quota_qps)
        self._quota_burst = (
            2.0 * self._quota_qps if quota_burst is None
            and self._quota_qps is not None else quota_burst
        )
        self._default_deadline_ms = default_deadline_ms
        self._brownout = brownout
        self._lock = threading.RLock()
        self._conns: dict[int, _Conn] = {}
        self._pending: dict[int, _PendingNet] = {}
        self._buckets: dict[str, TokenBucket] = {}
        self._shed_engaged: set = set()
        self._submitting = 0
        self._seq = 0
        self._state = "serving"

        self._registry = REGISTRY if registry is None else registry
        # the whole bibfs_net_* family group renders at zero from
        # construction — the soak's /metrics gate scrapes before traffic
        self._m_conns = self._registry.gauge(
            "bibfs_net_connections",
            "Open front-door TCP connections",
        )
        self._m_requests = self._registry.counter(
            "bibfs_net_requests_total",
            "Frames accepted for processing, by op class",
            ("op",),
        )
        for op in ("query", "control"):
            self._m_requests.labels(op=op)
        self._m_rejects = self._registry.counter(
            "bibfs_net_rejections_total",
            "Frames refused at admission, by reason (tenant-less)",
            ("reason",),
        )
        for reason in REJECT_REASONS:
            self._m_rejects.labels(reason=reason)
        self._m_bytes = self._registry.counter(
            "bibfs_net_bytes_total",
            "Wire bytes moved through the front door",
            ("direction",),
        )
        for d in ("in", "out"):
            self._m_bytes.labels(direction=d)
        self._m_deadline = self._registry.counter(
            "bibfs_net_deadline_misses_total",
            "Queries answered with a structured timeout because their "
            "per-request deadline expired before the result landed",
        )
        # the brownout shed counter is minted only on brownout-armed
        # servers (mint-at-zero would misread as "brownout available"
        # on plain front doors); every reason cell pre-minted
        self._c_shed = None
        if brownout is not None:
            self._c_shed = self._registry.counter(
                "bibfs_admission_shed_total",
                "Brownout admission sheds at the front door, by reason "
                "(infeasible = deadline-feasibility; ladder kinds shed "
                "under queue pressure before point lookups)",
                ("reason",),
            )
            for r in SHED_REASONS:
                self._c_shed.labels(reason=r)
        # per-query cost attribution (obs/dtrace.py): the front door
        # owns the ingress stage (frame arrival -> ticket submitted)
        self._stage_cells = stage_histogram()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
        )
        self._listener.bind((host, int(port)))
        self._listener.listen(1024)
        self._listener.setblocking(False)
        self.host, self.port = self._listener.getsockname()[:2]

        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listener, selectors.EVENT_READ, None)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")

        self._io_thread = threading.Thread(
            target=self._io_main, name="bibfs-net-io", daemon=True,
        )
        self._completer = threading.Thread(
            target=self._completer_main, name="bibfs-net-completer",
            daemon=True,
        )
        self._io_thread.start()
        self._completer.start()

    # ---- IO thread ---------------------------------------------------
    def _io_main(self) -> None:
        while self._state != "closed":
            with self._lock:
                dirty = [
                    c for c in self._conns.values()
                    if not c.closed
                    and bool(c.wbuf) != c.want_write
                ]
                for c in dirty:
                    c.want_write = bool(c.wbuf)
            for c in dirty:
                mask = selectors.EVENT_READ
                if c.want_write:
                    mask |= selectors.EVENT_WRITE
                try:
                    self._sel.modify(c.sock, mask, c)
                except (KeyError, ValueError, OSError):
                    pass
            try:
                events = self._sel.select(timeout=0.05)
            except OSError:
                continue
            for key, mask in events:
                data = key.data
                if data is None:
                    self._accept_ready()
                elif data == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                else:
                    try:
                        if mask & selectors.EVENT_READ:
                            self._read_ready(data)
                        if mask & selectors.EVENT_WRITE:
                            self._write_ready(data)
                    except Exception:
                        # the handlers contain their own faults; this
                        # is the listener's last line of defense
                        self._close_conn(data)

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass

    def _accept_ready(self) -> None:
        while True:
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            if self._state != "serving":
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass
            conn = _Conn(sock, addr)
            with self._lock:
                self._conns[conn.fd] = conn
                self._m_conns.inc()
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (KeyError, ValueError, OSError):
                self._close_conn(conn)

    def _read_ready(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 18)
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        conn.rbuf += data
        with self._lock:
            self._m_bytes.labels(direction="in").inc(len(data))
        try:
            frames = extract_frames(conn.rbuf, self._max_frame)
        except FrameError as e:
            with self._lock:
                self._m_rejects.labels(reason="oversize").inc()
            self._enqueue(conn, {
                "id": None, "ok": False, "kind": "invalid",
                "error": f"{e}; closing connection",
            })
            self._flush_then_close(conn)
            return
        for raw in frames:
            try:
                self._handle_frame(conn, raw)
            except Exception as e:
                # a handler bug costs this one connection, never the
                # IO thread every other connection's reads ride on
                self._enqueue(conn, {
                    "id": None, "ok": False, "kind": "internal",
                    "error": f"{type(e).__name__}: {e}; "
                             "closing connection",
                })
                self._flush_then_close(conn)
                return

    def _write_ready(self, conn: _Conn) -> None:
        with self._lock:
            chunk = b"" if conn.closed else bytes(conn.wbuf[: 1 << 18])
        if not chunk:
            return
        try:
            sent = conn.sock.send(chunk)
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        with self._lock:
            del conn.wbuf[:sent]

    def _flush_then_close(self, conn: _Conn) -> None:
        """Best-effort synchronous drain of ``conn.wbuf`` (the goodbye
        frame of a fatal protocol error), then close. Runs on the IO
        thread with the socket still non-blocking: whatever does not
        send immediately is dropped with the connection."""
        with self._lock:
            chunk = bytes(conn.wbuf)
            conn.wbuf.clear()
        try:
            conn.sock.send(chunk)
        except OSError:
            pass
        self._close_conn(conn)

    def _close_conn(self, conn: _Conn) -> None:
        with self._lock:
            if conn.closed:
                return
            conn.closed = True
            self._conns.pop(conn.fd, None)
            self._m_conns.dec()
            stale = [
                (k, e) for k, e in self._pending.items()
                if e.conn is conn
            ]
            for k, _ in stale:
                self._pending.pop(k, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        # a disconnected client's unlaunched queries should not burn
        # solves; cancel() is best-effort (False once launched)
        for _, e in stale:
            cancel = getattr(e.ticket, "cancel", None)
            if cancel is not None:
                try:
                    cancel()
                except Exception:
                    pass

    # ---- frame handling (IO thread) ---------------------------------
    def _handle_frame(self, conn: _Conn, raw: bytes) -> None:
        try:
            msg = json.loads(raw.decode("utf-8"))
            if not isinstance(msg, dict):
                raise ValueError("frame payload is not a JSON object")
        except (ValueError, UnicodeDecodeError):
            with self._lock:
                self._m_rejects.labels(reason="malformed").inc()
            self._enqueue(conn, {
                "id": None, "ok": False, "kind": "invalid",
                "error": "malformed frame payload",
            })
            return
        op = msg.get("op")
        rid = msg.get("id")
        if op == "query":
            self._handle_query(conn, msg, rid)
        elif op in CONTROL_OPS:
            self._handle_control(conn, op, msg, rid)
        else:
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": "invalid",
                "error": f"unknown op {op!r}",
            })

    def _handle_query(self, conn: _Conn, msg: dict, rid) -> None:
        # the deadline SLO is measured from frame arrival (module
        # docstring): anchor it here, before admission and submit
        now = time.monotonic()
        t_in = time.perf_counter()  # ingress-stage anchor
        tenant = str(msg.get("tenant") or "default")
        # deadline_ms is client-controlled: it must parse BEFORE any
        # admission state moves, so a junk value can neither burn a
        # quota token nor leak the _submitting count
        dl_ms = msg.get("deadline_ms", self._default_deadline_ms)
        if dl_ms is not None:
            try:
                dl_ms = float(dl_ms)
            except (TypeError, ValueError):
                with self._lock:
                    self._m_requests.labels(op="query").inc()
                    self._m_rejects.labels(reason="malformed").inc()
                self._enqueue(conn, {
                    "id": rid, "ok": False, "kind": "invalid",
                    "error": "deadline_ms must be a number, got "
                             f"{msg.get('deadline_ms')!r}",
                })
                return
        deadline = None if dl_ms is None else now + dl_ms / 1e3
        qkind = str(msg.get("kind") or "point")
        reason = None
        shed = None
        with self._lock:
            self._m_requests.labels(op="query").inc()
            if self._state != "serving":
                reason = "draining"
            elif (len(self._pending) + self._submitting
                    >= self._max_inflight):
                # the server-wide bound comes BEFORE the tenant bucket:
                # a capacity refusal must not also cost a quota token
                reason = "capacity"
            else:
                if self._brownout is not None:
                    # brownout rungs also come BEFORE the tenant
                    # bucket — a shed must not burn a quota token
                    shed = self._shed_locked(qkind, dl_ms)
                if shed is None and self._quota_qps is not None:
                    bucket = self._buckets.get(tenant)
                    if bucket is None:
                        bucket = TokenBucket(
                            self._quota_qps, self._quota_burst
                        )
                        self._buckets[tenant] = bucket
                    if not bucket.allow(now):
                        reason = "quota"
            if reason is None and shed is None:
                self._submitting += 1
            elif reason is not None:
                self._m_rejects.labels(reason=reason).inc()
            else:
                self._c_shed.labels(reason=shed[0]).inc()
        if reason is not None:
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": "capacity",
                "error": f"admission refused ({reason})",
            })
            return
        if shed is not None:
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": "capacity",
                "error": f"brownout shed ({shed[0]})",
                "retry_after_ms": shed[1],
            })
            return
        # distributed-trace ingress: adopt the frame's context, or make
        # the sampling decision HERE when an untraced client hits a
        # traced server (the front door is the ingress). Unsampled is
        # ctx=None all the way down — no span, no extra reply fields.
        ctx = ctx_from_fields(msg)
        if ctx is None:
            ctx = sample_ctx()
        sp = dspan("net_ingress", ctx, tenant=tenant)
        # submit OUTSIDE the server lock: the engine takes its own lock
        try:
            src = int(msg["src"])
            dst = int(msg["dst"])
            ticket = self._engine.submit(src, dst, msg.get("graph"),
                                         ctx=sp.ctx)
        except QueryError as e:
            sp.finish(error=e.kind)
            with self._lock:
                self._submitting -= 1
                if e.kind == "capacity":
                    self._m_rejects.labels(reason="capacity").inc()
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": e.kind,
                "error": str(e),
            })
            return
        except (KeyError, TypeError, ValueError) as e:
            sp.finish(error=type(e).__name__)
            with self._lock:
                self._submitting -= 1
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": "invalid",
                "error": f"{type(e).__name__}: {e}",
            })
            return
        except RuntimeError as e:  # engine closed underneath us
            sp.finish(error="closed")
            with self._lock:
                self._submitting -= 1
                self._m_rejects.labels(reason="capacity").inc()
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": "capacity",
                "error": f"{e}",
            })
            return
        # the ingress stage: frame arrival -> ticket submitted
        self._stage_cells["ingress"].record(time.perf_counter() - t_in)
        sp.finish(src=src, dst=dst)
        rx = round(wall_us(t_in), 3) if ctx is not None else None
        if ticket.result is not None or ticket.error is not None:
            # inline-resolved (cache/trivial/oracle): reply immediately
            # instead of waiting for the next completer wake
            with self._lock:
                self._submitting -= 1
            reply = self._ticket_reply(rid, ticket)
            if rx is not None:
                reply["rx"] = rx
                reply["stx"] = round(wall_us(time.perf_counter()), 3)
            self._enqueue(conn, reply)
            return
        entry = _PendingNet(ticket, conn, rid, deadline, tenant, now, rx)
        with self._lock:
            self._submitting -= 1
            self._pending[self._seq] = entry
            self._seq += 1

    def _shed_locked(self, qkind: str, dl_ms):
        """The two brownout admission rungs (module docstring), server
        lock held. Returns ``(reason, retry_after_ms)`` to shed, or
        None to admit."""
        pol = self._brownout
        # rung 1: deadline feasibility — refuse a deadline the engine's
        # own live p99 says cannot be met, once the estimate has enough
        # samples to mean anything
        if dl_ms is not None and pol.feasibility:
            lat = getattr(self._engine, "latency", None)
            if lat is not None and lat.count >= pol.min_samples:
                p99_ms = lat.percentile(0.99) * 1e3
                if dl_ms < p99_ms * pol.headroom:
                    return "infeasible", round(
                        max(p99_ms, pol.retry_after_ms), 1
                    )
        # rung 2: the kind ladder — expensive admission classes shed
        # under queue pressure, each rung with its own hysteresis band
        # so admission does not flap at the threshold
        occ = ((len(self._pending) + self._submitting)
               / max(1, self._max_inflight))
        for k, hi in pol.ladder.items():
            if k in self._shed_engaged:
                if occ <= hi - pol.release:
                    self._shed_engaged.discard(k)
            elif occ >= hi:
                self._shed_engaged.add(k)
        if qkind in self._shed_engaged:
            return qkind, pol.retry_after_ms
        return None

    def _handle_control(self, conn: _Conn, op: str, msg: dict,
                        rid) -> None:
        with self._lock:
            self._m_requests.labels(op="control").inc()
        try:
            result = self._control(op, msg)
        except QueryError as e:
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": e.kind,
                "error": str(e),
            })
            return
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": "invalid",
                "error": f"{type(e).__name__}: {e}",
            })
            return
        except Exception as e:
            self._enqueue(conn, {
                "id": rid, "ok": False, "kind": "internal",
                "error": f"{type(e).__name__}: {e}",
            })
            return
        self._enqueue(conn, {"id": rid, "ok": True, "result": result})

    def _control(self, op: str, msg: dict):
        """One control op. Store mutations (``update``/``roll``) run on
        the IO thread — a roll stalls this replica's traffic for its
        duration, which is exactly the window the router's rolling-swap
        drain already brackets."""
        eng = self._engine
        if op == "ping":
            return {"pong": True}
        if op == "health":
            return eng.health_snapshot()
        if op == "stats":
            return eng.stats()
        if op == "metrics":
            # the fleet-wide scrape seam: this process's full
            # Prometheus text rendering, aggregated by the router's
            # /metrics with a replica label
            return {"render": self._registry.render()}
        if op == "flightrec":
            snap = FLIGHT.snapshot()
            if msg.get("dump"):
                snap["dumped_to"] = FLIGHT.dump(reason="demand")
            return snap
        if op == "memory":
            if self._store is None:
                raise ValueError("no store attached")
            return self._store.memory_stats()
        if op == "graphs":
            if self._store is None:
                raise ValueError("no store attached")
            return {
                "graphs": {
                    name: int(self._store.current(name).version)
                    for name in self._store.names()
                },
                "default": self._store.default_graph(),
            }
        if op == "version":
            g = msg.get("graph")
            if self._store is not None:
                name = (self._store.default_graph() if g is None
                        else str(g))
                return {
                    "graph": name,
                    "version": int(self._store.current(name).version),
                }
            st = eng.stats()
            return {
                "graph": g,
                "version": st.get("graph", {}).get("version"),
            }
        if op == "analytics":
            # the whole-graph tier over the wire: submit-and-flush one
            # typed kind and reply with the scalar summary (the vector
            # stays server-side, in the kind cache and the per-digest
            # result store — a reply frame never carries O(n) data).
            # Runs on the IO thread like update/roll: an analytics
            # flush brackets this replica's traffic for its duration
            from bibfs_tpu.analytics.queries import (
                analytics_query_from_spec, analytics_summary,
            )

            g = msg.get("graph")
            name = None if g is None else str(g)
            q = analytics_query_from_spec(
                str(msg.get("kind") or ""), msg.get("params") or {}
            )
            return analytics_summary(eng.query_one(q, graph=name))
        if op in ("update", "roll"):
            if self._store is None:
                raise ValueError("no store attached")
            g = msg.get("graph")
            name = self._store.default_graph() if g is None else str(g)
            adds = [(int(u), int(v)) for u, v in msg.get("adds", ())]
            dels = [(int(u), int(v)) for u, v in msg.get("dels", ())]
            if op == "update":
                self._store.update(name, adds=adds, dels=dels)
                return {
                    "graph": name, "applied": len(adds) + len(dels),
                }
            snap = self._store.roll(name, adds=adds, dels=dels)
            return {"graph": name, "version": int(snap.version)}
        raise ValueError(f"unknown control op {op!r}")

    # ---- replies -----------------------------------------------------
    @staticmethod
    def _ticket_reply(rid, ticket) -> dict:
        err = ticket.error
        if err is not None:
            kind = getattr(err, "kind", "internal")
            if kind not in ERROR_KINDS:
                kind = "internal"
            return {
                "id": rid, "ok": False, "kind": kind,
                "error": str(err),
            }
        r = ticket.result
        return {
            "id": rid, "ok": True, "found": bool(r.found),
            "hops": None if r.hops is None else int(r.hops),
        }

    def _enqueue(self, conn: _Conn, obj: dict) -> None:
        try:
            data = encode_frame(obj)
        except ValueError:
            data = encode_frame({
                "id": obj.get("id"), "ok": False, "kind": "internal",
                "error": "reply exceeded the frame bound",
            })
        with self._lock:
            if conn.closed:
                return
            conn.wbuf += data
            self._m_bytes.labels(direction="out").inc(len(data))
        self._wake()

    # ---- completer thread -------------------------------------------
    def _completer_main(self) -> None:
        # the pipelined engine broadcasts its condvar once per landed
        # batch; the short timeout bounds deadline-check latency (and
        # is the whole loop for engines without a condvar)
        cv = getattr(self._engine, "_cv", None)
        while self._state != "closed":
            if cv is not None:
                with cv:
                    cv.wait(timeout=0.01)
            else:
                time.sleep(0.005)
            # engine condvar released BEFORE the server lock: holding
            # both would order the locks both ways against the IO
            # thread's submit path (lockgraph cycle)
            with self._lock:
                items = list(self._pending.items())
            if not items:
                continue
            now = time.monotonic()
            done, missed = [], []
            for k, e in items:
                t = e.ticket
                if t.result is not None or t.error is not None:
                    done.append((k, e))
                elif e.deadline is not None and now >= e.deadline:
                    missed.append((k, e))
            if not done and not missed:
                continue
            with self._lock:
                done = [
                    (k, e) for k, e in done
                    if self._pending.pop(k, None) is not None
                ]
                missed = [
                    (k, e) for k, e in missed
                    if self._pending.pop(k, None) is not None
                ]
                if missed:
                    self._m_deadline.inc(len(missed))
            for _, e in missed:
                # the deadline passed: the reply is a timeout even if
                # the result lands between cancel and send — the SLO is
                # about WHEN the client hears back, and cancel() feeds
                # the engine's own timeout accounting for the unlaunched
                cancel = getattr(e.ticket, "cancel", None)
                if cancel is not None:
                    try:
                        cancel()
                    except Exception:
                        pass
                self._enqueue(e.conn, {
                    "id": e.rid, "ok": False, "kind": "timeout",
                    "error": "deadline exceeded before the result "
                             "landed",
                })
            for _, e in done:
                reply = self._ticket_reply(e.rid, e.ticket)
                if e.rx is not None:
                    # traced query: both server clock stamps ride the
                    # reply so the client can subtract server time from
                    # its RTT (the wire stage + clock-offset bound)
                    reply["rx"] = e.rx
                    reply["stx"] = round(wall_us(time.perf_counter()), 3)
                self._enqueue(e.conn, reply)

    # ---- lifecycle ---------------------------------------------------
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending) + self._submitting

    def connection_count(self) -> int:
        with self._lock:
            return len(self._conns)

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting queries (structured ``capacity``
        reason=draining; control ops still answer) and wait for every
        in-flight query to be REPLIED and its bytes handed to the
        kernel. Returns True when quiet. New connections are refused
        for the drain's duration."""
        if self._state == "serving":
            self._state = "draining"
        deadline = time.monotonic() + max(float(timeout), 0.0)
        while True:
            with self._lock:
                quiet = (
                    not self._pending and not self._submitting
                    and all(
                        not c.wbuf for c in self._conns.values()
                    )
                )
            if quiet:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def close(self) -> None:
        """Stop both threads and close every socket. Pending queries
        that never got a reply frame die with their connections (call
        :meth:`drain` first for a graceful stop)."""
        if self._state == "closed":
            return
        self._state = "closed"
        self._wake()
        self._io_thread.join(timeout=10.0)
        self._completer.join(timeout=10.0)
        with self._lock:
            conns = list(self._conns.values())
        for conn in conns:
            self._close_conn(conn)
        for sock in (self._listener, self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except (KeyError, ValueError, OSError):
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# --------------------------------------------------------------------
# client side
# --------------------------------------------------------------------

class NetTicket:
    """One in-flight client query, resolved by the reader thread.
    ``t_done`` is the reader's ``perf_counter`` resolve stamp — the
    same per-ticket latency contract the engines' tickets expose, so
    the open-loop loadgen reads net latencies identically."""

    __slots__ = ("src", "dst", "graph", "result", "error", "event",
                 "t_done", "span", "t_sent")

    def __init__(self, src: int, dst: int, graph):
        self.src = src
        self.dst = dst
        self.graph = graph
        self.result: BFSResult | None = None
        self.error: BaseException | None = None
        self.event = threading.Event()
        self.t_done: float | None = None
        self.span = None  # the client-side DSpan, sampled queries only
        self.t_sent: float | None = None

    def wait(self, timeout: float | None = None):
        if not self.event.wait(timeout):
            raise TimeoutError(
                f"query ({self.src}, {self.dst}) unresolved"
            )
        if self.error is not None:
            raise self.error
        return self.result


class _CtrlWaiter:
    __slots__ = ("msg", "event")

    def __init__(self):
        self.msg: dict | None = None
        self.event = threading.Event()


# the waiter table is shared between submitters and the reader thread;
# _dead stays un-annotated by design (lock-free fast-refusal read)
@guarded_by("_lock", "_waiters", "_seq")
class NetClient:
    """One connection to a :class:`NetServer`: correlation-id
    multiplexed request/reply with a background reader thread, shared
    by :class:`~bibfs_tpu.fleet.netreplica.NetReplica` and the tests.
    Thread-safe; any number of queries may be in flight. Socket writes
    serialize on their own leaf lock (``_wlock``) so concurrent
    submitters cannot interleave frame bytes."""

    def __init__(self, host: str, port: int, *,
                 connect_timeout: float = 30.0, tenant: str | None = None):
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        self._sock.settimeout(None)
        try:
            self._sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        except OSError:
            pass
        self.tenant = tenant
        # the wire stage (client RTT minus server time) lands in this
        # process's bibfs_stage_seconds when tracing samples a query
        self._stage_cells = stage_histogram()
        self._lock = threading.RLock()
        self._wlock = threading.Lock()
        self._waiters: dict[int, object] = {}
        self._seq = 0
        self._dead = False
        self._reader = threading.Thread(
            target=self._read_main, name="bibfs-net-client-reader",
            daemon=True,
        )
        self._reader.start()

    # ---- plumbing ----------------------------------------------------
    def _send(self, data: bytes) -> None:
        try:
            with self._wlock:
                self._sock.sendall(data)
        except (BrokenPipeError, OSError, ValueError) as e:
            raise ConnectionError(f"front-door send failed: {e}") from e

    def _register(self, waiter) -> int:
        with self._lock:
            if self._dead:
                raise ConnectionError("front-door connection is closed")
            rid = self._seq
            self._seq += 1
            self._waiters[rid] = waiter
        return rid

    def _read_main(self) -> None:
        buf = bytearray()
        try:
            while True:
                data = self._sock.recv(1 << 16)
                if not data:
                    break
                buf += data
                for raw in extract_frames(buf):
                    try:
                        msg = json.loads(raw.decode("utf-8"))
                    except (ValueError, UnicodeDecodeError):
                        continue
                    self._dispatch(msg)
        except (OSError, ValueError):
            pass
        finally:
            self._fail_all()

    def _dispatch(self, msg: dict) -> None:
        rid = msg.get("id")
        with self._lock:
            waiter = self._waiters.pop(rid, None)
        if waiter is None:
            return
        if isinstance(waiter, NetTicket):
            if msg.get("ok"):
                hops = msg.get("hops")
                waiter.result = BFSResult(
                    bool(msg.get("found")),
                    None if hops is None else int(hops),
                    None, None, 0.0, 0, 0,
                )
            else:
                kind = msg.get("kind", "internal")
                if kind not in ERROR_KINDS:
                    kind = "internal"
                # bibfs: allow(error-kind): deserializes the server's wire kind — validated against ERROR_KINDS on the line above, unknowns coerced to internal
                waiter.error = QueryError(
                    str(msg.get("error", "front-door error")),
                    kind=kind, query=(waiter.src, waiter.dst),
                )
                ra = msg.get("retry_after_ms")
                if ra is not None:
                    # brownout sheds carry a backoff hint; ride it on
                    # the structured error for the caller's retry loop
                    waiter.error.retry_after_ms = ra
            waiter.t_done = time.perf_counter()
            if waiter.span is not None:
                self._finish_traced(waiter, msg)
            waiter.event.set()
        else:
            waiter.msg = msg
            waiter.event.set()

    def _finish_traced(self, waiter: NetTicket, msg: dict) -> None:
        """Close a sampled query's client span: subtract the server's
        own processing time (its ``rx``/``stx`` wall stamps) from the
        client RTT to get the wire stage, and estimate the clock offset
        NTP-style — ``(rx - t0) + (stx - t3)) / 2`` with the wire time
        itself bounding the estimate's error."""
        rtt_s = waiter.t_done - waiter.t_sent
        rx, stx = msg.get("rx"), msg.get("stx")
        args = {"rtt_ms": round(rtt_s * 1e3, 3)}
        if isinstance(rx, (int, float)) and isinstance(stx, (int, float)):
            wire_s = max(rtt_s - (stx - rx) / 1e6, 0.0)
            self._stage_cells["wire"].record(wire_s)
            t0 = wall_us(waiter.t_sent)
            t3 = wall_us(waiter.t_done)
            args["wire_ms"] = round(wire_s * 1e3, 3)
            args["clock_offset_us"] = round(
                ((rx - t0) + (stx - t3)) / 2.0, 1
            )
            args["offset_bound_us"] = round(wire_s * 5e5, 1)
        if waiter.error is not None:
            args["error"] = getattr(waiter.error, "kind", "internal")
        waiter.span.finish(**args)

    def _fail_all(self) -> None:
        with self._lock:
            self._dead = True
            waiters = list(self._waiters.values())
            self._waiters.clear()
        for waiter in waiters:
            if isinstance(waiter, NetTicket):
                if waiter.result is None and waiter.error is None:
                    waiter.error = QueryError(
                        "connection closed with the query pending",
                        kind="internal",
                        query=(waiter.src, waiter.dst),
                    )
                waiter.t_done = time.perf_counter()
                if waiter.span is not None:
                    waiter.span.finish(error="disconnected")
                waiter.event.set()
            else:
                waiter.event.set()  # msg stays None: ConnectionError

    # ---- API ---------------------------------------------------------
    @property
    def alive(self) -> bool:
        return not self._dead

    def pending_count(self) -> int:
        """In-flight requests (queries + control) awaiting replies —
        the NetReplica's load signal."""
        with self._lock:
            return len(self._waiters)

    def submit(self, src: int, dst: int, graph: str | None = None, *,
               deadline_ms: float | None = None,
               tenant: str | None = None, kind: str | None = None,
               ctx=None) -> NetTicket:
        ticket = NetTicket(int(src), int(dst), graph)
        rid = self._register(ticket)
        frame = {"op": "query", "id": rid, "src": ticket.src,
                 "dst": ticket.dst}
        if graph is not None:
            frame["graph"] = graph
        if deadline_ms is not None:
            frame["deadline_ms"] = float(deadline_ms)
        if kind is not None:
            # the admission class for brownout-armed servers (module
            # docstring); the wire still computes a point lookup
            frame["kind"] = str(kind)
        t = tenant if tenant is not None else self.tenant
        if t is not None:
            frame["tenant"] = t
        # distributed trace: the client IS the ingress when it holds a
        # tracer — sample here (or adopt the caller's ctx), open the
        # client span, and carry its context on the frame so the
        # server's spans parent under it
        if ctx is None:
            ctx = sample_ctx()
        if ctx is not None:
            sp = dspan("net_client", ctx, src=ticket.src, dst=ticket.dst)
            ticket.span = sp
            ticket.t_sent = time.perf_counter()
            frame.update(ctx_fields(sp.ctx))
        try:
            self._send(encode_frame(frame))
        except ConnectionError:
            with self._lock:
                self._waiters.pop(rid, None)
            raise
        return ticket

    def request(self, op: str, timeout: float = 60.0, **fields) -> dict:
        """One control op round-trip; returns the reply's ``result``.
        Structured server refusals raise :class:`QueryError` with the
        wire kind; a dead connection raises :class:`ConnectionError`."""
        waiter = _CtrlWaiter()
        rid = self._register(waiter)
        frame = {"op": op, "id": rid}
        frame.update(fields)
        try:
            self._send(encode_frame(frame))
        except ConnectionError:
            with self._lock:
                self._waiters.pop(rid, None)
            raise
        if not waiter.event.wait(timeout):
            with self._lock:
                self._waiters.pop(rid, None)
            raise TimeoutError(f"no reply to {op!r} in {timeout}s")
        msg = waiter.msg
        if msg is None:
            raise ConnectionError("connection closed mid-command")
        if not msg.get("ok"):
            kind = msg.get("kind", "internal")
            if kind not in ERROR_KINDS:
                kind = "internal"
            # bibfs: allow(error-kind): deserializes the server's wire kind — validated against ERROR_KINDS on the line above, unknowns coerced to internal
            raise QueryError(
                str(msg.get("error", f"{op} refused")), kind=kind
            )
        return msg.get("result")

    def close(self) -> None:
        with self._lock:
            self._dead = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
