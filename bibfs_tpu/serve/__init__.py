"""Query-throughput serving layer.

Turns the measured batch asymptote (PERF_NOTES.md §3: per-query device
cost flat by batch ~256) into an end-to-end serving path: an adaptive
micro-batcher over the batch solvers (:mod:`bibfs_tpu.serve.engine`), a
shape-bucketed executable cache, a distance/result cache, a pipelined
async engine that overlaps device dispatch with host-side finish and
flushes on a ``max_wait_ms`` latency SLO
(:mod:`bibfs_tpu.serve.pipeline`), and an open-loop arrival-rate load
harness (:mod:`bibfs_tpu.serve.loadgen`).
"""

from bibfs_tpu.serve.buckets import (  # noqa: F401
    DEFAULT_EXEC_CACHE,
    ExecutableCache,
    bucket_batch,
    bucket_rows,
    bucket_shape,
    bucket_width,
    bucketed_ell,
    ell_bucket_key,
)
from bibfs_tpu.store import (  # noqa: F401  (the graph-store subsystem)
    DeltaOverlay,
    GraphSnapshot,
    GraphStore,
)
from bibfs_tpu.serve.cache import DistanceCache  # noqa: F401
from bibfs_tpu.serve.engine import QueryEngine  # noqa: F401
from bibfs_tpu.serve.faults import FaultPlan, InjectedFault  # noqa: F401
from bibfs_tpu.serve.pipeline import (  # noqa: F401
    LatencyHistogram,
    PipelinedQueryEngine,
    QueryTicket,
)
from bibfs_tpu.serve.resilience import (  # noqa: F401
    CircuitBreaker,
    HealthMonitor,
    QueryError,
    RetryPolicy,
)
