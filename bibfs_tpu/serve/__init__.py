"""Query-throughput serving layer (PR: adaptive micro-batching engine).

Turns the measured batch asymptote (PERF_NOTES.md §3: per-query device
cost flat by batch ~256) into an end-to-end serving path: an adaptive
micro-batcher over the batch solvers, a shape-bucketed executable cache,
and a distance/result cache. See :mod:`bibfs_tpu.serve.engine`.
"""

from bibfs_tpu.serve.buckets import (  # noqa: F401
    DEFAULT_EXEC_CACHE,
    ExecutableCache,
    bucket_batch,
    bucket_rows,
    bucket_shape,
    bucket_width,
    bucketed_ell,
)
from bibfs_tpu.serve.cache import DistanceCache  # noqa: F401
from bibfs_tpu.serve.engine import QueryEngine  # noqa: F401
