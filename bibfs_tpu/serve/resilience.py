"""Failure handling for the serving stack: error taxonomy, retry with
backoff, circuit breaking, and the health state machine.

PRs 1-3 built a fast, observable serving pipeline that was brittle in
exactly the way distributed BFS work warns about (arXiv:1208.5542
treats communication failure modes as first-class; the reference
paper's hybrid MPI+CUDA build had no degradation path when its
interconnect underperformed): one failing dispatch failed every ticket
in the batch, and a dead device route meant a dead server. The pieces
here give the engines the opposite behavior — a failing route degrades
THROUGHPUT, never availability:

- :class:`QueryError` — the structured per-query failure the engines
  hand a ticket instead of a raw backend traceback, with a small
  taxonomy (``invalid`` / ``timeout`` / ``capacity`` / ``internal``)
  that callers and the ``bibfs_errors_total{kind}`` metric share.
- :class:`RetryPolicy` — bounded retries with exponential backoff and
  jitter (seeded, so chaos runs reproduce): the transient-blip answer.
- :class:`CircuitBreaker` — consecutive-failure threshold opens the
  device route; after ``reset_s`` a half-open probe is let through and
  its outcome closes or re-opens the breaker. A dead accelerator costs
  one failed batch per reset window, not one per flush.
- :class:`HealthMonitor` — the ``live`` / ``ready`` / ``degraded`` /
  ``draining`` state machine ``/healthz`` serves (200 for
  ready/degraded with detail, 503 otherwise), derived from breaker
  state, recent error rate, and queue depth vs the admission bound.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque

#: the error taxonomy (README "Robustness"): what a failed query means
#: - invalid:  the query itself is malformed (out-of-range node id, bad
#:             arity) — retrying cannot help
#: - timeout:  the caller stopped waiting (ticket cancelled after a
#:             wait timeout) or a bounded wait expired
#: - capacity: the engine refused work it cannot absorb (admission
#:             queue full in a non-blocking submit, engine draining)
#: - internal: a solver/dispatch failure (including injected faults)
#:             that survived every retry and fallback rung
ERROR_KINDS = ("invalid", "timeout", "capacity", "internal")


class QueryError(RuntimeError):
    """A structured per-query failure (one ticket, not its batch)."""

    def __init__(self, message: str, *, kind: str = "internal",
                 query=None, cause: BaseException | None = None):
        if kind not in ERROR_KINDS:
            raise ValueError(
                f"unknown error kind {kind!r} (known: {ERROR_KINDS})"
            )
        self.kind = kind
        self.query = None if query is None else (
            int(query[0]), int(query[1])
        )
        self.cause = cause
        prefix = f"[{kind}]"
        if self.query is not None:
            prefix += f" query {self.query[0]}->{self.query[1]}"
        super().__init__(f"{prefix}: {message}")


def classify_exception(exc: BaseException) -> str:
    """Map an arbitrary failure onto the taxonomy (the fallback ladder
    wraps whatever the last rung raised). ``invalid`` is deliberately
    NOT inferred here: a ValueError out of a solver rung is an internal
    failure, not the client's — only submit-time validation (which
    knows it is looking at client input) may tag ``invalid``, via the
    explicit ``kind=`` on :func:`to_query_error`."""
    if isinstance(exc, QueryError):
        return exc.kind
    if isinstance(exc, TimeoutError):
        return "timeout"
    return "internal"


def to_query_error(exc: BaseException, query=None,
                   kind: str | None = None) -> QueryError:
    if isinstance(exc, QueryError):
        return exc
    return QueryError(
        f"{type(exc).__name__}: {exc}",
        kind=classify_exception(exc) if kind is None else kind,
        query=query, cause=exc,
    )


#: taxonomy kinds that degrade /healthz: server-side failures only.
#: A client sending malformed queries (invalid) or abandoning tickets
#: (timeout) must not be able to drive a healthy node's health state —
#: that would hand health alerts to whoever talks to the socket.
HEALTH_ERROR_KINDS = ("internal", "capacity")


class RetryPolicy:
    """Bounded retry with exponential backoff and jitter.

    ``attempts`` counts TOTAL tries of a route (so 2 = one retry before
    the fallback rung). Backoff for the sleep between try ``k`` and
    ``k+1`` is ``base_ms * 2**k`` capped at ``max_ms``, scaled by a
    uniform jitter in ``[1-jitter, 1+jitter]`` — jitter is what keeps N
    engines that failed together from hammering the recovered route in
    lockstep, which is exactly why the default is UNSEEDED (identical
    seeds would reproduce the lockstep jitter exists to break). Pass
    ``seed=`` explicitly when a chaos run must reproduce its
    schedule."""

    def __init__(self, attempts: int = 2, *, base_ms: float = 1.0,
                 max_ms: float = 50.0, jitter: float = 0.5,
                 seed: int | None = None):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        if not (0.0 <= jitter <= 1.0):
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.attempts = int(attempts)
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry number ``attempt + 1`` (0-based)."""
        d = min(self.base_ms * (2.0 ** attempt), self.max_ms)
        lo, hi = 1.0 - self.jitter, 1.0 + self.jitter
        return d * self._rng.uniform(lo, hi) / 1e3

    def snapshot(self) -> dict:
        return {
            "attempts": self.attempts,
            "base_ms": self.base_ms,
            "max_ms": self.max_ms,
            "jitter": self.jitter,
        }


#: breaker state -> the ``bibfs_breaker_state`` gauge value
BREAKER_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Consecutive-failure circuit breaker for one route.

    closed --[``fail_threshold`` consecutive failures]--> open
    open --[``reset_s`` elapsed]--> half_open (ONE probe allowed)
    half_open --[probe success]--> closed
    half_open --[probe failure]--> open (timer re-armed)

    ``allow()`` is the route gate: True means "try the route" (and, in
    half-open, claims the single probe slot — every True MUST be
    followed by ``record_success`` or ``record_failure``). Thread-safe;
    transition listeners (``on_transition`` at construction, more via
    :meth:`add_listener` — a breaker SHARED by several engines keeps
    every engine's gauge exact) fire under the lock on every state
    change.
    """

    def __init__(self, fail_threshold: int = 3, *, reset_s: float = 5.0,
                 clock=time.monotonic, on_transition=None):
        if fail_threshold < 1:
            raise ValueError(
                f"fail_threshold must be >= 1, got {fail_threshold}"
            )
        self.fail_threshold = int(fail_threshold)
        self.reset_s = float(reset_s)
        self._clock = clock
        self._listeners = [] if on_transition is None else [on_transition]
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at: float | None = None
        self._probe_in_flight = False
        self._opens = 0

    def add_listener(self, on_transition) -> None:
        """Subscribe to state changes (fires under the breaker lock).
        A listener that returns ``False`` is UNREGISTERED — how
        weakly-bound listeners prune themselves once their engine is
        gone (same contract as the registry's ``add_collector``), so a
        breaker shared across churning engines doesn't accumulate dead
        subscribers firing on every transition."""
        self._listeners.append(on_transition)

    def _transition(self, state: str) -> None:
        self._state = state
        self._listeners = [
            cb for cb in self._listeners if cb(state) is not False
        ]

    @property
    def state(self) -> str:
        with self._lock:
            # an elapsed open window reads as half_open: the state a
            # health probe should report even before traffic arrives
            if (self._state == "open"
                    and self._clock() - self._opened_at >= self.reset_s):
                return "half_open"
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at < self.reset_s:
                    return False
                self._transition("half_open")
                self._probe_in_flight = True
                return True
            # half_open: one probe at a time
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != "closed":
                self._transition("closed")

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == "half_open":
                # failed probe: straight back to open, timer re-armed
                self._probe_in_flight = False
                self._opened_at = self._clock()
                self._opens += 1
                self._transition("open")
            elif (self._state == "closed"
                    and self._consecutive_failures >= self.fail_threshold):
                self._opened_at = self._clock()
                self._opens += 1
                self._transition("open")

    def snapshot(self) -> dict:
        with self._lock:
            state = self._state
            if (state == "open"
                    and self._clock() - self._opened_at >= self.reset_s):
                state = "half_open"
            return {
                "state": state,
                "consecutive_failures": self._consecutive_failures,
                "fail_threshold": self.fail_threshold,
                "reset_s": self.reset_s,
                "opens": self._opens,
            }


#: health state -> the ``bibfs_health_state`` gauge value
HEALTH_STATE_CODES = {"live": 0, "ready": 1, "degraded": 2, "draining": 3}


class HealthMonitor:
    """The serving health state machine (module docstring).

    Inputs are pulled lazily at :meth:`state` time (a /healthz probe or
    a ``stats()`` read), so steady-state serving pays nothing:

    - ``breaker`` — any non-closed breaker state degrades;
    - recent errors — ticket failures noted via :meth:`note_error`
      within the last ``window_s`` degrade (and age out on their own:
      this is what "recovered" means after a fault clears);
    - ``queue_depth``/``max_queue`` — a queue at or past
      ``queue_high`` of the admission bound degrades (the server is
      up but saturating).

    ``live`` is the before-ready state (constructed, not yet serving);
    ``draining`` is terminal (close() started). 200 vs 503 mapping
    lives in :func:`healthz_status`.
    """

    def __init__(self, *, breaker: CircuitBreaker | None = None,
                 window_s: float = 5.0, error_threshold: int = 1,
                 queue_depth=None, max_queue: int | None = None,
                 queue_high: float = 0.9, clock=time.monotonic,
                 gauge=None):
        self._breaker = breaker
        self.window_s = float(window_s)
        self.error_threshold = max(int(error_threshold), 1)
        self._queue_depth = queue_depth
        self._max_queue = max_queue
        self._queue_high = float(queue_high)
        self._clock = clock
        self._gauge = gauge
        self._lock = threading.Lock()
        self._errors: deque[float] = deque(maxlen=4096)
        self._errors_total = 0
        self._ready = False
        self._draining = False

    def set_ready(self) -> None:
        self._ready = True

    def set_draining(self) -> None:
        self._draining = True

    def clear_draining(self) -> None:
        """Leave the draining state — the rolling-swap re-admit path
        (``engine.end_drain()``): the monitor goes back to deriving
        ready/degraded from its live inputs. A ``close()``-style
        terminal drain simply never calls this."""
        self._draining = False

    def note_error(self, count: int = 1) -> None:
        now = self._clock()
        with self._lock:
            self._errors_total += count
            for _ in range(min(count, self._errors.maxlen)):
                self._errors.append(now)

    def recent_errors(self) -> int:
        cutoff = self._clock() - self.window_s
        with self._lock:
            while self._errors and self._errors[0] < cutoff:
                self._errors.popleft()
            return len(self._errors)

    def state(self) -> tuple[str, list[str]]:
        """``(state, reasons)``; reasons name every degradation input
        that tripped (empty for live/ready/draining)."""
        if self._draining:
            state, reasons = "draining", []
        elif not self._ready:
            state, reasons = "live", []
        else:
            reasons = []
            if self._breaker is not None:
                bstate = self._breaker.state
                if bstate != "closed":
                    reasons.append(f"breaker_{bstate}")
            errs = self.recent_errors()
            if errs >= self.error_threshold:
                reasons.append(
                    f"errors={errs} in last {self.window_s:g}s"
                )
            if self._queue_depth is not None and self._max_queue:
                depth = self._queue_depth()
                if depth >= self._queue_high * self._max_queue:
                    reasons.append(
                        f"queue_depth={depth}/{self._max_queue}"
                    )
            state = "degraded" if reasons else "ready"
        if self._gauge is not None:
            self._gauge.set(HEALTH_STATE_CODES[state])
        return state, reasons

    def snapshot(self) -> dict:
        """The /healthz payload (and the ``stats()['health']`` block)."""
        state, reasons = self.state()
        out = {
            "state": state,
            "reasons": reasons,
            "errors_total": self._errors_total,
            "recent_errors": self.recent_errors(),
            "window_s": self.window_s,
        }
        if self._breaker is not None:
            out["breaker"] = self._breaker.snapshot()
        if self._queue_depth is not None and self._max_queue:
            out["queue_depth"] = self._queue_depth()
            out["max_queue"] = self._max_queue
        return out


def healthz_status(state: str) -> int:
    """HTTP status for a health state: a degraded server still SERVES
    (200 — load balancers must not eject a node that is answering,
    merely slowly), a live-not-ready or draining one must not receive
    traffic (503)."""
    return 200 if state in ("ready", "degraded") else 503
